"""Checkpoint / resume: params + optimizer state + loop counter.

The reference had save/load of net weights only, never wired into training
(`libs/CaffeNet.scala:152-165`; SURVEY §5.4 flags this as a genuine gap).
Here checkpoints are first-class: the FULL TrainState (per-device params AND
worker-local momentum AND iteration counter) plus the round index round-trips
exactly, so a resumed run continues bit-identically.

Format: a directory with
  - state.npz   — flattened pytree leaves, keys are /-joined paths
  - meta.json   — {"step": N, "keys": [...], "digests": {key: sha256-hex},
                   "extra": {...}}
Atomic via write-to-temp + rename. `step-N` naming with retention.

SHARDED layout (r8, the gather-free checkpoint path): instead of one
state.npz materialized from a full `fetch_global` allgather, a step may
hold N `shard-xxxxx-of-NNNNN.npz` files — one per mesh device — each
carrying only the distinct state pieces that device owns (replicated
leaves are chunked across files so no byte is written twice and the
files stay balanced). meta.json then carries a SHARD MANIFEST: per-file
entries ({key: [offsets, shape]}) plus a per-shard digest of the exact
file bytes, and is still written LAST as the commit marker — a killed
parallel save leaves meta-less shard files every reader treats as
not-a-checkpoint and the next save sweeps. `save_sharded` writes the
files in parallel (stage 1 is `parallel.mesh.fetch_state_shards`, which
replaces the full-state gather with per-shard host fetches), and
`restore_flat`/`verify`/`retain` read BOTH layouts transparently: the
manifest loader reassembles the exact flat {key: array} map a monolithic
restore returns, bit for bit, so every adapt/resume/serve path is
layout-blind. Checkpoint wall time becomes O(1/n_workers) and the state
no longer has to fit one host's RAM on the save side.

The "directory" may be a LOCAL path or a BUCKET URI (`gs://` / `s3://`):
every public function here accepts both, so pod checkpoints go straight to
the object store over the same native HTTP clients the data plane streams
from (no FUSE mount, no SDK — `data/gcs.py` / `data/s3.py`). The bucket
layout mirrors the local one (`<root>/step-N/{state.npz,meta.json}`);
`state.npz` is pushed through the parallel chunked writers (GCS resumable
sessions + compose, S3 multipart) so a killed writer never leaves a
partial object, and `meta.json` is written LAST as the commit marker —
the same not-a-checkpoint-until-meta-parses rule the local store already
enforces makes an interrupted bucket save invisible to readers. Reads go
through the ranged-GET streams with reconnect-resume.

`AsyncCheckpointWriter` is stage 2 of the train loop's two-stage save:
stage 1 (blocking, short) fetches device state to host buffers; stage 2
(this writer's single background thread) serializes, digests, and
persists. At most one snapshot is in flight — submitting the next save
waits for the previous write (backpressure lands on the SAVE cadence, not
on every round) and re-raises its failure loudly.

Integrity (the health supervisor's substrate): `save` records a SHA-256
digest of every array's bytes in meta.json; `verify` recomputes them, and
`restore_flat` (auto-latest) falls back to the newest checkpoint that
verifies instead of dying on a torn/corrupt latest — a byte flipped by a
bad disk or a truncated copy on a network FS is detected and skipped, with
a warning. The digest schema is a compatibility surface: checkpoints
written before it (no "digests" key) still restore — their integrity check
is vacuous beyond "meta parses and every key loads".

`retain` never deletes the newest checkpoint that verifies, even when a
newer (corrupt) one would otherwise push it out of the keep window — the
rollback target must survive retention. Checkpoints written during an
unhealthy training window carry `extra["anomalous"] = True`;
`newest_verified_step(skip_anomalous=True)` is the rollback selector.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import tempfile
import time
import urllib.error
import warnings
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Tuple

import jax
import ml_dtypes
import numpy as np

# np.savez silently degrades extension dtypes (bfloat16 & friends from
# ml_dtypes) to void ('V2') — the restored leaf is unusable. Such leaves are
# stored as same-width uint views with the real dtype name recorded in
# meta.json, and re-viewed on restore.
_UINT_OF_SIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory exists but fails integrity verification
    (unreadable meta.json / state.npz, missing keys, or digest mismatch)."""


class CheckpointVanishedError(CheckpointCorruptError):
    """An explicitly requested step has no meta.json commit marker — it
    was retention-pruned (or never committed) between listing and fetch.
    Subclasses CheckpointCorruptError so every existing rollback/skip
    path still treats it as not-loadable, but callers that react to
    CORRUPTION (serve's rejected-swap cooldown, rollout halts) can tell
    "the bytes are bad" from "the step is simply gone"."""


def _is_extension_dtype(dt: np.dtype) -> bool:
    # bfloat16/float8_e4m3fn report kind 'V', but float8_e5m2 reports kind
    # 'f' (and still breaks savez) — match on the registering module too,
    # excluding structured dtypes (which have .names)
    return dt.names is None and (
        dt.kind == "V" or dt.type.__module__ == "ml_dtypes")


def _flatten(tree: Any) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def _digest(arr: np.ndarray) -> str:
    """SHA-256 over the array's C-order bytes (the exact bytes savez
    writes; tobytes() serializes non-contiguous arrays in C order too)."""
    return hashlib.sha256(arr.tobytes()).hexdigest()


# -- store plumbing: local directories vs gs://|s3:// bucket prefixes -------

def is_bucket_path(path: str) -> bool:
    return isinstance(path, str) and path.startswith(("gs://", "s3://"))


def _bucket_ops(path: str) -> SimpleNamespace:
    """The scheme-matched object operations (read / ranged stream / small
    atomic write / chunked-parallel large write / delete / list)."""
    if path.startswith("gs://"):
        from ..data import gcs as m
        return SimpleNamespace(
            read=m.gs_read, open_stream=m.gs_open_stream,
            write=m.gs_write, write_large=m.gs_write_large,
            delete=m.gs_delete, list_urls=m.gs_list_urls,
            stat=m.gs_stat)
    from ..data import s3 as m
    return SimpleNamespace(
        read=m.s3_read, open_stream=m.s3_open_stream,
        write=m.s3_write, write_large=m.s3_write_large,
        delete=m.s3_delete, list_urls=m.s3_list_urls,
        stat=m.s3_stat)


def _join(directory: str, *names: str) -> str:
    if is_bucket_path(directory):
        return "/".join((directory.rstrip("/"),) + names)
    return os.path.join(directory, *names)


# -- process-local "last step I wrote and verified" cache --------------------
#
# retain()'s protect scan re-verifies the newest checkpoint from the store
# on EVERY save — on a bucket that is a full ranged-GET + re-hash of
# state.npz (~244 MB for CaffeNet+momentum) per save. But in the common
# case the step under scan is the one THIS process just wrote: its digests
# were computed from the exact bytes handed to the store, and both store
# kinds commit those bytes all-or-nothing (local tmp-dir rename; bucket
# resumable/multipart finalize). The cache records that step together with
# a store FINGERPRINT of state.npz captured right after the write — local
# (size, mtime_ns), bucket (size, generation|ETag) — and retain() accepts
# the cached step as verified only while the fingerprint still matches, so
# anything that REWRITES the object (another process, a test mutating
# bytes, an overwrite-save) changes the fingerprint and falls back to the
# full read-back verify. What the cache deliberately trades away is
# detection of in-place at-rest corruption of our OWN last write during
# its keep-window (a flipped byte that updates neither mtime_ns nor
# generation); steps written by other processes keep the full at-rest
# guarantee, and every restore/rollback path still verifies for real.
_written_verified: Dict[str, Tuple[int, Dict[str, Tuple]]] = {}


def _cache_key(directory: str) -> str:
    return (directory.rstrip("/") if is_bucket_path(directory)
            else os.path.abspath(directory))


def _state_fingerprint(directory: str, step: int,
                       name: str = "state.npz") -> Optional[Tuple]:
    """Freshness token of one step file: ("local", size, mtime_ns) or
    ("bucket", size, generation|ETag). None when unreadable — the caller
    treats that as a cache miss, never as verified."""
    url = _join(directory, f"step-{int(step)}", name)
    try:
        if is_bucket_path(directory):
            size, gen = _bucket_ops(directory).stat(url, fresh=True)
            return ("bucket", int(size), gen)
        st = os.stat(url)
        return ("local", st.st_size, st.st_mtime_ns)
    except Exception:
        return None


def _record_written(directory: str, step: int,
                    files: Tuple[str, ...] = ("state.npz",)) -> None:
    fps: Dict[str, Tuple] = {}
    key = _cache_key(directory)
    for name in files:
        fp = _state_fingerprint(directory, step, name)
        if fp is None:
            _written_verified.pop(key, None)
            return
        fps[name] = fp
    _written_verified[key] = (int(step), fps)


def _written_verified_hit(directory: str, step: int) -> bool:
    """True when `step` is the one this process last wrote here AND every
    stored state file (state.npz, or all shard files of a sharded save)
    still carries the fingerprint captured at write time (nobody rewrote
    any since)."""
    cached = _written_verified.get(_cache_key(directory))
    if cached is None or cached[0] != int(step):
        return False
    fps = cached[1]
    if not isinstance(fps, dict):  # legacy single-file token (tests)
        fps = {"state.npz": fps}
    return all(_state_fingerprint(directory, step, n) == fp
               for n, fp in fps.items())


def invalidate_written_cache(directory: Optional[str] = None) -> None:
    """Drop the process-local written-and-verified record (all directories,
    or one) — forces retain() back to full store read-back verification.
    For tests and for callers that hand the directory to another writer."""
    if directory is None:
        _written_verified.clear()
    else:
        _written_verified.pop(_cache_key(directory), None)


def _bucket_step_files(directory: str) -> Dict[int, set]:
    """{step: {relative file names under step-N/}} from ONE bucket listing
    (steps, stale-orphan sweep, and retention all key off this)."""
    base = directory.rstrip("/")
    out: Dict[int, set] = {}
    for url in _bucket_ops(directory).list_urls(base):
        rel = url[len(base) + 1:]
        head, _, rest = rel.partition("/")
        if head.startswith("step-") and head[5:].isdigit():
            out.setdefault(int(head[5:]), set()).add(rest)
    return out


def _delete_step(directory: str, step: int) -> None:
    """Remove checkpoint `step-N`. Bucket: meta.json FIRST, so a reader
    racing the delete sees not-a-checkpoint rather than a torn one."""
    if not is_bucket_path(directory):
        shutil.rmtree(_join(directory, f"step-{step}"),
                      ignore_errors=True)
        return
    ops = _bucket_ops(directory)
    prefix = _join(directory, f"step-{step}")
    try:
        ops.delete(f"{prefix}/meta.json")
    except Exception as e:
        # could not decommit: leave the step WHOLE (a commit marker over
        # half-deleted state would read as corrupt); retention is
        # best-effort and the next retain re-sweeps — parity with the
        # local twin's rmtree(ignore_errors=True)
        warnings.warn(f"checkpoint retention: could not delete "
                      f"{prefix}/meta.json ({e}) — step left in place",
                      RuntimeWarning)
        return
    for url in ops.list_urls(prefix):
        try:
            ops.delete(url)
        except Exception:
            pass  # retention is best-effort; the next retain re-sweeps


def _sweep_stale_tmp(directory: str,
                     current_step: Optional[int] = None) -> None:
    """Remove leftovers of a previous writer killed mid-save: `.tmp-*`
    work directories (SIGKILL between mkdtemp and rename), and — since
    the sharded layout's multi-process path writes shard files directly
    into `step-N/` with meta.json landing last — step directories WITHOUT
    a meta.json commit marker (orphan shard files; every reader already
    treats such a step as not-a-checkpoint). The step currently being
    written is never swept. One writer per directory per process role is
    supported, so anything else meta-less is stale by definition."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    for d in entries:
        if d.startswith(".tmp-"):
            shutil.rmtree(os.path.join(directory, d), ignore_errors=True)
            continue
        if d.startswith("step-") and d.split("-", 1)[1].isdigit():
            s = int(d.split("-", 1)[1])
            if current_step is not None and s == int(current_step):
                continue
            if not os.path.exists(os.path.join(directory, d, "meta.json")):
                shutil.rmtree(os.path.join(directory, d),
                              ignore_errors=True)


def _prepare_save(tree: Any, step: int, extra: Optional[Dict[str, Any]]
                  ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """(flat-uint-viewed arrays, meta dict) — the byte-identical payload
    both store kinds write (digests over the same C-order bytes)."""
    flat = _flatten(tree)
    ext_dtypes = {}
    for key, arr in flat.items():
        if _is_extension_dtype(arr.dtype):
            ext_dtypes[key] = arr.dtype.name
            flat[key] = arr.view(_UINT_OF_SIZE[arr.dtype.itemsize])
    meta = {"step": int(step), "keys": sorted(flat.keys()),
            "digests": {k: _digest(a) for k, a in flat.items()}}
    if ext_dtypes:
        meta["ext_dtypes"] = ext_dtypes
    if extra:
        meta["extra"] = extra
    return flat, meta


def _stamp_commit(meta: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp wall-clock commit time into the manifest IMMEDIATELY before
    the meta.json write. meta.json is the commit marker, so `commit_ts`
    is the moment the step became visible to readers — the anchor the
    serving fleet's freshness metric (now - commit_ts of the serving
    step) is measured from. Stamped here rather than at snapshot time so
    an async stage-2 writer or a slow multi-process digest poll doesn't
    pre-age the step before anyone could possibly have served it."""
    meta["commit_ts"] = round(time.time(), 3)
    return meta


def save(directory: str, tree: Any, *, step: int,
         extra: Optional[Dict[str, Any]] = None) -> str:
    """Atomically write checkpoint `step-N` under directory (a local path
    or a gs://|s3:// prefix); returns its path. Records per-array SHA-256
    digests in meta.json (see module docstring) and sweeps leftovers of
    crashed earlier saves (`.tmp-*` work dirs locally; committed-but-
    orphaned objects in a bucket)."""
    if is_bucket_path(directory):
        return _save_bucket(directory, tree, step=step, extra=extra)
    os.makedirs(directory, exist_ok=True)
    _sweep_stale_tmp(directory, current_step=step)
    flat, meta = _prepare_save(tree, step, extra)
    tmp = tempfile.mkdtemp(dir=directory, prefix=".tmp-")
    try:
        np.savez(os.path.join(tmp, "state.npz"), **flat)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(_stamp_commit(meta), f)
        final = os.path.join(directory, f"step-{int(step)}")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _record_written(directory, step)
    return final


def _save_bucket(directory: str, tree: Any, *, step: int,
                 extra: Optional[Dict[str, Any]] = None) -> str:
    """Bucket save with upload-then-finalize atomicity: state.npz goes up
    through the parallel chunked writer (never visible partially — GCS
    resumable/compose and S3 multipart both materialize the object only at
    finalize), then meta.json lands LAST as the commit marker. A writer
    killed anywhere in between leaves a step directory without a readable
    meta.json, which every reader already treats as not-a-checkpoint.
    Overwriting an existing step decommits it first (meta.json deleted) so
    a crash mid-overwrite can't pair old meta with new state."""
    ops = _bucket_ops(directory)
    final = _join(directory, f"step-{int(step)}")
    # sweep orphans of crashed earlier saves: any step with state but no
    # meta never committed, and stray .part- components never composed
    # (one sweep policy shared with the sharded layout's commit paths)
    _sweep_bucket_orphans(directory, ops, _bucket_step_files(directory))
    flat, meta = _prepare_save(tree, step, extra)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    # decommit stays UNGUARDED: proceeding past a failed meta delete
    # could pair the OLD commit marker with half-new state after a crash
    ops.delete(f"{final}/meta.json")  # decommit before overwrite
    # getbuffer(): zero-copy view — getvalue() would duplicate the whole
    # serialized archive next to the flat arrays on the writer thread
    ops.write_large(f"{final}/state.npz", buf.getbuffer())
    ops.write(f"{final}/meta.json", json.dumps(_stamp_commit(meta)).encode())
    _record_written(directory, step)
    return final


# -- sharded layout: per-worker shard files + manifest commit marker ---------

def shard_file_name(i: int, n: int) -> str:
    return f"shard-{int(i):05d}-of-{int(n):05d}.npz"


def sharded_nbytes(sharded: Dict[str, Any]) -> int:
    """Total LOGICAL state bytes a sharded snapshot will persist — by
    construction identical to the monolithic layout's sum of array bytes
    (every distinct piece written exactly once, replicated leaves never
    duplicated). The BENCH ledger both layouts are compared on."""
    return sum(int(np.prod(rec["shape"])) * np.dtype(rec["dtype"]).itemsize
               for rec in sharded["leaves"].values())


def _serialize_shard(pieces: Dict[str, Tuple[Tuple[int, ...], np.ndarray]]
                     ) -> Tuple[bytes, str]:
    """One shard file's (npz bytes, sha256 hex). Keys are the flat state
    keys; each file holds at most one piece per key (the piece plan
    guarantees it), so the piece offsets live in the MANIFEST, not here."""
    buf = io.BytesIO()
    np.savez(buf, **{k: arr for k, (_, arr) in pieces.items()})
    raw = buf.getvalue()
    return raw, hashlib.sha256(raw).hexdigest()


def _sharded_meta(sharded: Dict[str, Any], step: int,
                  extra: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Manifest meta.json for a sharded save — the piece PLAN is
    deterministic from (global shape, sharding), so every process can
    build the full manifest; only the per-file digests need the writers'
    reports. Extension dtypes store as same-width uint views with the
    real name in ext_dtypes, exactly like the monolithic format."""
    n = int(sharded["n_shards"])
    files: Dict[int, Dict[str, list]] = {}
    glob: Dict[str, Dict[str, Any]] = {}
    ext_dtypes: Dict[str, str] = {}
    for key, rec in sharded["leaves"].items():
        dt = np.dtype(rec["dtype"])
        if _is_extension_dtype(dt):
            ext_dtypes[key] = dt.name
            dt = np.dtype(_UINT_OF_SIZE[dt.itemsize])
        glob[key] = {"shape": [int(s) for s in rec["shape"]],
                     "dtype": dt.name}
        for fid, offsets, shape, _ in rec["pieces"]:
            files.setdefault(int(fid), {})[key] = [
                [int(o) for o in offsets], [int(s) for s in shape]]
    meta = {"step": int(step), "keys": sorted(sharded["leaves"]),
            "format": "sharded", "global": glob,
            "shards": [{"file": shard_file_name(fid, n),
                        "entries": files[fid]}
                       for fid in sorted(files)]}
    if ext_dtypes:
        meta["ext_dtypes"] = ext_dtypes
    if extra:
        meta["extra"] = extra
    return meta


def _shard_payloads(sharded: Dict[str, Any]
                    ) -> Dict[int, Dict[str, Tuple[Tuple[int, ...],
                                                   np.ndarray]]]:
    """{file_id: {key: (offsets, uint-viewed array)}} for the pieces THIS
    process holds (arr is None for non-local pieces of a multi-host
    snapshot — those files belong to the process that owns them)."""
    out: Dict[int, Dict[str, Tuple[Tuple[int, ...], np.ndarray]]] = {}
    for key, rec in sharded["leaves"].items():
        dt = np.dtype(rec["dtype"])
        view = (np.dtype(_UINT_OF_SIZE[dt.itemsize])
                if _is_extension_dtype(dt) else None)
        for fid, offsets, shape, arr in rec["pieces"]:
            if arr is None:
                continue
            if view is not None:
                arr = arr.view(view)
            out.setdefault(int(fid), {})[key] = (tuple(offsets), arr)
    return out


def save_sharded(directory: str, sharded: Dict[str, Any], *, step: int,
                 extra: Optional[Dict[str, Any]] = None,
                 metrics=None, commit_timeout_s: float = 600.0) -> str:
    """Write checkpoint `step-N` in the SHARDED layout from a
    `parallel.mesh.fetch_state_shards` snapshot: N shard files written in
    PARALLEL (threads over the same local/bucket writers), meta.json —
    the manifest with per-shard digests — committed LAST. Single-process
    writes everything; multi-process, every process calls this with its
    own pieces and process 0 commits the manifest once every peer's
    shard-digest report has landed (`commit-<p>.json` sidecars, removed
    after commit). `metrics(scope, seconds, ok)` is the per-write
    instrumentation hook (AsyncCheckpointWriter.note_write: scope
    "shard" per file, "meta" for the commit marker)."""
    payloads = _shard_payloads(sharded)
    meta = _sharded_meta(sharded, step, extra)
    owners: Dict[int, int] = {int(k): int(v) for k, v in
                              sharded.get("owners", {}).items()}
    my_proc = int(sharded.get("process_index", 0))
    n_procs = int(sharded.get("process_count", 1))

    def timed_write(scope, fn):
        t0 = time.perf_counter()
        try:
            fn()
        except BaseException:
            if metrics is not None:
                metrics(scope, time.perf_counter() - t0, ok=False)
            raise
        if metrics is not None:
            metrics(scope, time.perf_counter() - t0, ok=True)

    files = {shard_file_name(fid, sharded["n_shards"]): pieces
             for fid, pieces in payloads.items()}
    if n_procs == 1:
        if is_bucket_path(directory):
            return _commit_sharded_bucket(directory, step, files, meta,
                                          timed_write)
        return _commit_sharded_local(directory, step, files, meta,
                                     timed_write)
    return _commit_sharded_multiproc(directory, step, files, meta,
                                     owners, my_proc, timed_write,
                                     commit_timeout_s)


def _parallel_file_writes(files: Dict[str, Dict], write_one,
                          timed_write) -> Dict[str, str]:
    """Serialize AND write every shard file on a thread pool — both the
    np.savez/CRC pass and the store I/O parallelize per file (a serial
    serialize stage would otherwise cap the O(1/n_workers) save-time
    win). Returns {file name: sha256 of the exact bytes written} for the
    manifest."""
    if not files:
        return {}

    def one(name, pieces):
        raw, digest = _serialize_shard(pieces)
        timed_write("shard", lambda: write_one(name, raw))
        return name, digest

    with ThreadPoolExecutor(min(8, len(files)),
                            thread_name_prefix="ckpt-shard") as ex:
        futs = [ex.submit(one, name, pieces)
                for name, pieces in sorted(files.items())]
        return dict(f.result() for f in futs)


def _stamp_digests(meta: Dict[str, Any], digests: Dict[str, str]) -> None:
    for rec in meta["shards"]:
        if rec["file"] not in digests:
            raise RuntimeError(
                f"sharded checkpoint step-{meta['step']}: manifest file "
                f"{rec['file']} was never written")
        rec["digest"] = digests[rec["file"]]


def _commit_sharded_local(directory: str, step: int, files, meta,
                          timed_write) -> str:
    """Local single-process sharded save: parallel serialize+write into a
    `.tmp-*` work dir, meta.json, one atomic rename — same crash story as
    the monolithic twin (a SIGKILL leaves only a swept-next-save tmp
    dir)."""
    os.makedirs(directory, exist_ok=True)
    _sweep_stale_tmp(directory, current_step=step)
    tmp = tempfile.mkdtemp(dir=directory, prefix=".tmp-")
    try:
        def write_one(name, raw):
            with open(os.path.join(tmp, name), "wb") as f:
                f.write(raw)

        _stamp_digests(meta, _parallel_file_writes(files, write_one,
                                                   timed_write))

        def write_meta():
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(_stamp_commit(meta), f)

        timed_write("meta", write_meta)
        final = os.path.join(directory, f"step-{int(step)}")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _record_written(directory, step, files=tuple(sorted(files)))
    return final


def _sweep_bucket_orphans(directory: str, ops,
                          listing: Dict[int, set]) -> None:
    """Delete stray `.part-` components and every file of a meta-less
    (uncommitted/killed) step from a bucket listing — shard files
    included. Best-effort; the next save re-sweeps."""
    for s, fs in listing.items():
        for f in fs:
            if ".part-" in f or ("meta.json" not in fs):
                try:
                    ops.delete(_join(directory, f"step-{s}", f))
                except Exception as e:
                    warnings.warn(f"checkpoint orphan sweep: could not "
                                  f"delete step-{s}/{f}: {e}",
                                  RuntimeWarning)


def _commit_sharded_bucket(directory: str, step: int, files, meta,
                           timed_write) -> str:
    """Bucket single-process sharded save: sweep orphans (meta-less steps
    lose ALL their files, shard files included), decommit + clear an
    overwritten step (stale shard files from a previous wider save must
    not pair with the new manifest), parallel shard uploads, meta last."""
    ops = _bucket_ops(directory)
    final = _join(directory, f"step-{int(step)}")
    listing = _bucket_step_files(directory)  # ONE list serves sweep+stat
    _sweep_bucket_orphans(directory, ops, listing)
    # an overwritten step's files survive the sweep only when the step
    # was COMMITTED (meta present) — a meta-less one was just reclaimed
    step_files = listing.get(int(step), set())
    existing = step_files if "meta.json" in step_files else set()
    if existing:
        # decommit FIRST (unguarded — see _save_bucket), then clear the
        # old state files so a crash mid-overwrite can never pair a new
        # manifest with leftover old shards
        ops.delete(f"{final}/meta.json")
        for f in existing:
            if f != "meta.json":
                try:
                    ops.delete(f"{final}/{f}")
                except Exception:
                    pass  # next save's sweep retries (now meta-less)

    def write_one(name, raw):
        ops.write_large(f"{final}/{name}", raw)

    _stamp_digests(meta, _parallel_file_writes(files, write_one,
                                               timed_write))
    timed_write("meta", lambda: ops.write(
        f"{final}/meta.json", json.dumps(_stamp_commit(meta)).encode()))
    _record_written(directory, step, files=tuple(sorted(files)))
    return final


def prepare_sharded_step(directory: str, step: int) -> None:
    """STAGE-1 cleanup for a MULTI-PROCESS sharded save, run by process 0
    with an EXPLICIT cross-process barrier after it (train_loop calls
    this then sync_global_devices before any process reaches stage 2, so
    no peer can have written fresh files this cleanup would delete):
    decommit an overwritten step's meta.json FIRST (a crash mid-clear
    must leave not-a-checkpoint, never old-manifest-over-new-shards),
    then clear ALL the step's remaining files — stale commit-*.json
    reports of a previous crashed save (the commit poll must never
    stamp a dead incarnation's digests into the new manifest — and
    doing this in stage 2 would race peers' FRESH reports, since
    process 0's writer systematically starts last) AND old shard files
    (a previous WIDER save's shard-*-of-M must not survive inside the
    new manifest's committed step) — and sweep meta-less orphan steps.
    Single-process saves need none of this (their commits are
    atomic)."""
    if is_bucket_path(directory):
        ops = _bucket_ops(directory)
        listing = _bucket_step_files(directory)
        step_files = sorted(listing.get(int(step), set()),
                            key=lambda f: f != "meta.json")  # meta first
        for f in step_files:
            try:
                ops.delete(_join(directory, f"step-{int(step)}", f))
            except Exception:
                if f == "meta.json":
                    raise  # cannot decommit: do not proceed to overwrite
        _sweep_bucket_orphans(directory, ops, {
            s: fs for s, fs in listing.items() if s != int(step)})
        return
    step_dir = _join(directory, f"step-{int(step)}")
    if os.path.isdir(step_dir):
        meta = os.path.join(step_dir, "meta.json")
        if os.path.exists(meta):
            os.remove(meta)  # decommit first; a failure here propagates
        shutil.rmtree(step_dir, ignore_errors=True)
    _sweep_stale_tmp(directory, current_step=step)


def _commit_sharded_multiproc(directory: str, step: int, files, meta,
                              owners, my_proc, timed_write,
                              commit_timeout_s: float) -> str:
    """Multi-process sharded save (stage 2; `prepare_sharded_step` is the
    process-0 stage-1 half): every process writes its own shard files
    DIRECTLY under step-N plus a tiny commit-<p>.json digest report;
    process 0 polls for every expected report, folds the digests into
    the manifest, commits meta.json last, and removes the reports. A
    writer killed anywhere leaves a meta-less step the NEXT save's
    stage-1 sweep reclaims. (Structural multi-host path — single-process
    runs take the atomic tmp/rename or bucket commit above; driven
    per-process by tests/test_checkpoint_stores.py.)"""
    final = _join(directory, f"step-{int(step)}")
    bucket = is_bucket_path(directory)
    if bucket:
        ops = _bucket_ops(directory)

        def write_file(name, raw):
            (ops.write_large if len(raw) > (1 << 20) else ops.write)(
                f"{final}/{name}", raw)

        def read_file(name):
            return ops.read(f"{final}/{name}")

        def delete_file(name):
            ops.delete(f"{final}/{name}")
    else:
        os.makedirs(final, exist_ok=True)

        def write_file(name, raw):
            tmp = f"{os.path.join(final, name)}.tmp-{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, os.path.join(final, name))

        def read_file(name):
            with open(os.path.join(final, name), "rb") as f:
                return f.read()

        def delete_file(name):
            os.remove(os.path.join(final, name))

    digests = _parallel_file_writes(files, write_file, timed_write)
    write_file(f"commit-{int(my_proc)}.json",
               json.dumps(digests).encode())
    if my_proc != 0:
        return final
    expected = sorted(set(owners.values()))
    all_digests: Dict[str, str] = {}
    deadline = time.monotonic() + commit_timeout_s
    for p in expected:
        while True:
            try:
                all_digests.update(json.loads(
                    read_file(f"commit-{int(p)}.json")))
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"sharded checkpoint step-{step}: worker {p} "
                        f"never reported its shard digests within "
                        f"{commit_timeout_s}s — leaving the step "
                        f"uncommitted (not-a-checkpoint)")
                time.sleep(0.2)
    _stamp_digests(meta, all_digests)
    timed_write("meta", lambda: write_file(
        "meta.json", json.dumps(_stamp_commit(meta)).encode()))
    for p in expected:
        try:
            delete_file(f"commit-{int(p)}.json")
        except Exception:
            pass  # harmless residue inside a committed step
    # fingerprint every manifest file so retain()'s protect scan costs
    # one stat per file instead of re-downloading + re-hashing the whole
    # sharded state on every save (the single-process paths' rule)
    _record_written(directory, step,
                    files=tuple(sorted(r["file"] for r in meta["shards"])))
    return final


def _list_steps(directory: str) -> List[int]:
    """All step numbers present as directories (no validity check)."""
    if is_bucket_path(directory):
        return sorted(_bucket_step_files(directory))
    if not os.path.isdir(directory):
        return []
    return sorted(int(d.split("-", 1)[1]) for d in os.listdir(directory)
                  if d.startswith("step-") and d.split("-", 1)[1].isdigit())


def _load_meta(path: str) -> Optional[Dict[str, Any]]:
    """meta.json as a dict, or None when missing/unparseable (a torn copy
    on a network FS, or an uncommitted bucket save killed before its
    meta.json landed) — the caller treats that as not-a-checkpoint.

    On a bucket, only a definitive 404 means ABSENT; a network outage
    (ConnectionError after the retry budget) or an auth/5xx failure
    PROPAGATES — a transient store outage must not be misread as "no
    checkpoints exist", which would make a health rollback hard-fail or
    a resume silently pick an older step."""
    if is_bucket_path(path):
        try:
            raw = _bucket_ops(path).read(f"{path}/meta.json")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None  # no commit marker: not-a-checkpoint
            raise
        try:
            return json.loads(raw)
        except ValueError:
            return None  # unparseable marker: not-a-checkpoint
    try:
        with open(os.path.join(path, "meta.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None


def latest_step(directory: str) -> Optional[int]:
    """Newest step whose meta.json is readable. A step directory with a
    missing/unparseable meta.json (torn copy, crashed writer on a non-atomic
    FS) is skipped with a warning instead of raising an opaque
    JSONDecodeError/FileNotFoundError later."""
    for s in reversed(_list_steps(directory)):
        path = _join(directory, f"step-{s}")
        if _load_meta(path) is not None:
            return s
        warnings.warn(f"checkpoint {path}: meta.json missing/unreadable — "
                      f"treating as not-a-checkpoint", RuntimeWarning)
    return None


def unflatten_like(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild `template`'s structure from a flat {path-key: array} map.
    Shape mismatches fail loudly with the leaf path."""
    leaves_t, _ = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for pth, leaf in leaves_t:
        key = "/".join(_path_str(p) for p in pth)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"leaf {key!r}: checkpoint shape {arr.shape} != template "
                f"{np.shape(leaf)} (device-count change? re-tile first)")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), new_leaves)


def restore(directory: str, template: Any, *, step: Optional[int] = None
            ) -> Tuple[Any, int, Dict[str, Any]]:
    """Restore into the structure of `template` (a pytree with correctly-
    shaped leaves, e.g. a freshly-built TrainState). Returns
    (tree, step, extra). Shape mismatches fail loudly with the leaf path."""
    flat, step, extra = restore_flat(directory, step)
    return unflatten_like(template, flat), step, extra


def _load_checkpoint(path: str) -> Tuple[Dict[str, np.ndarray], int,
                                         Dict[str, Any]]:
    """Load + integrity-verify one checkpoint directory. Raises
    CheckpointCorruptError on unreadable meta/state, missing keys, or a
    digest mismatch. Digestless (pre-integrity-format) checkpoints load
    with a vacuous digest check — old checkpoints must still restore."""
    meta = _load_meta(path)
    if meta is None:
        raise CheckpointVanishedError(
            f"{path}: meta.json missing/unreadable — never committed or "
            f"retention-pruned")
    if "shards" in meta:
        return _load_sharded(path, meta)
    try:
        if is_bucket_path(path):
            # one ranged-GET stream with reconnect-resume (the data
            # plane's transport): a dropped connection mid-multi-GB read
            # resumes at the break instead of failing the restore.
            # copyfileobj into ONE buffer — BytesIO(stream.read()) would
            # transiently hold TWO full copies of a multi-GB state
            stream = _bucket_ops(path).open_stream(f"{path}/state.npz")
            try:
                src = io.BytesIO()
                shutil.copyfileobj(stream, src, 1 << 20)
                src.seek(0)
            finally:
                stream.close()
        else:
            src = os.path.join(path, "state.npz")
        with np.load(src) as z:
            flat = {k: z[k] for k in z.files}
    except (ConnectionError, TimeoutError):
        # a bucket outage (or a socket timeout mid-stream) outlasting the
        # retry budget is NOT corruption: propagating keeps the fallback
        # scan from silently restoring an older step — and the serving
        # poller from cooling down a perfectly good step — during a
        # transient store failure
        raise
    except urllib.error.HTTPError as e:
        # meta committed but state unreadable: only a definitive 404
        # (upload never finalized / object deleted) is corruption — an
        # auth failure (401/403 expired token) or a 5xx that outlasted
        # the retries is store trouble and must stay loud, mirroring
        # _load_meta's non-404 rule
        if e.code == 404:
            raise CheckpointCorruptError(
                f"{path}: state.npz missing: {e}") from e
        raise
    except Exception as e:
        raise CheckpointCorruptError(f"{path}: state.npz unreadable: {e}"
                                     ) from e
    missing = set(meta.get("keys", ())) - set(flat)
    if missing:
        raise CheckpointCorruptError(
            f"{path}: state.npz missing keys {sorted(missing)[:5]}")
    for key, want in meta.get("digests", {}).items():
        if key not in flat:
            raise CheckpointCorruptError(f"{path}: digest for missing "
                                         f"key {key!r}")
        got = _digest(flat[key])
        if got != want:
            raise CheckpointCorruptError(
                f"{path}: digest mismatch on {key!r} (stored "
                f"{want[:12]}…, recomputed {got[:12]}…) — bytes were "
                f"corrupted at rest or in transit")
    for key, name in meta.get("ext_dtypes", {}).items():
        flat[key] = flat[key].view(np.dtype(name))
    return flat, int(meta["step"]), _extra_with_commit(meta)


def _extra_with_commit(meta: Dict[str, Any]) -> Dict[str, Any]:
    """The checkpoint's `extra` dict with the manifest's top-level
    `commit_ts` folded in — one returned mapping carries both the saver's
    tags and the commit instant, so restore_flat's 3-tuple signature
    stays put while freshness consumers see when the step went live."""
    extra = dict(meta.get("extra") or {})
    if "commit_ts" in meta:
        extra.setdefault("commit_ts", meta["commit_ts"])
    return extra


def _load_sharded(path: str, meta: Dict[str, Any]
                  ) -> Tuple[Dict[str, np.ndarray], int, Dict[str, Any]]:
    """Load + verify a SHARDED checkpoint: every manifest file fetched in
    parallel, its sha256 recomputed over the exact stored bytes, pieces
    reassembled into the same flat {key: array} map a monolithic restore
    returns (bit-identical — the adapt/resume/serve paths stay
    layout-blind). A missing/tampered shard is a digest mismatch ->
    CheckpointCorruptError (the fallback scan skips to the previous
    step); store trouble (ConnectionError, non-404 HTTPError) propagates,
    same rule as the monolithic loader."""
    ops = _bucket_ops(path) if is_bucket_path(path) else None

    def load_one(rec: Dict[str, Any]) -> Dict[str, np.ndarray]:
        name = rec["file"]
        try:
            if ops is not None:
                # the ranged-GET stream with reconnect-resume (the
                # monolithic loader's transport): a dropped connection
                # mid-shard resumes at the break instead of re-pulling
                # the shard from byte 0
                stream = ops.open_stream(f"{path}/{name}")
                try:
                    buf = io.BytesIO()
                    shutil.copyfileobj(stream, buf, 1 << 20)
                    raw = buf.getvalue()
                finally:
                    stream.close()
            else:
                with open(os.path.join(path, name), "rb") as f:
                    raw = f.read()
        except (ConnectionError, TimeoutError):
            raise  # store trouble, not corruption — same rule as meta
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise CheckpointCorruptError(
                    f"{path}: shard {name} missing: {e}") from e
            raise
        except OSError as e:
            raise CheckpointCorruptError(
                f"{path}: shard {name} unreadable: {e}") from e
        want = rec.get("digest")
        if want:
            got = hashlib.sha256(raw).hexdigest()
            if got != want:
                raise CheckpointCorruptError(
                    f"{path}: digest mismatch on shard {name} (stored "
                    f"{want[:12]}…, recomputed {got[:12]}…) — bytes were "
                    f"corrupted at rest or in transit")
        try:
            with np.load(io.BytesIO(raw)) as z:
                return {k: z[k] for k in z.files}
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: shard {name} unparseable: {e}") from e

    shards = meta["shards"]
    with ThreadPoolExecutor(max(1, min(8, len(shards))),
                            thread_name_prefix="ckpt-shard-read") as ex:
        loaded = list(ex.map(load_one, shards))
    glob = meta.get("global", {})
    flat: Dict[str, np.ndarray] = {}
    filled: Dict[str, int] = {}
    for rec, data in zip(shards, loaded):
        for key, (offsets, shape) in rec["entries"].items():
            if key not in glob:
                raise CheckpointCorruptError(
                    f"{path}: shard {rec['file']} carries unknown key "
                    f"{key!r}")
            if key not in data:
                raise CheckpointCorruptError(
                    f"{path}: shard {rec['file']} missing key {key!r}")
            piece = data[key]
            if tuple(piece.shape) != tuple(shape):
                raise CheckpointCorruptError(
                    f"{path}: shard {rec['file']} piece {key!r} shape "
                    f"{piece.shape} != manifest {tuple(shape)}")
            g = glob[key]
            if key not in flat:
                flat[key] = np.empty(tuple(g["shape"]),
                                     np.dtype(g["dtype"]))
                filled[key] = 0
            if piece.ndim == 0:
                flat[key] = piece
                filled[key] += 1
            else:
                flat[key][tuple(slice(o, o + s) for o, s in
                                zip(offsets, piece.shape))] = piece
                filled[key] += int(np.prod(piece.shape))
    for key in meta.get("keys", ()):
        g = glob.get(key)
        want_n = (1 if g is None or not g["shape"]
                  else int(np.prod(g["shape"])))
        if filled.get(key, 0) != want_n:
            raise CheckpointCorruptError(
                f"{path}: key {key!r} covered {filled.get(key, 0)} of "
                f"{want_n} elements across the manifest — incomplete or "
                f"overlapping shards")
    for key, name in meta.get("ext_dtypes", {}).items():
        flat[key] = flat[key].view(np.dtype(name))
    return flat, int(meta["step"]), _extra_with_commit(meta)


def restore_flat(directory: str, step: Optional[int] = None
                 ) -> Tuple[Dict[str, np.ndarray], int, Dict[str, Any]]:
    """Restore the raw flat {path-key: array} mapping without a template —
    for ELASTIC resume, where the saved leading device axis differs from
    the current topology and a structural template cannot match
    (ParallelTrainer.adapt_state re-tiles from this).

    With an explicit `step`, integrity failure raises
    CheckpointCorruptError. With step=None, falls back: the newest
    checkpoint that VERIFIES wins; torn/corrupt newer ones are skipped
    with a warning (a kill -9 mid-rename, a byte flipped at rest — resume
    proceeds from the previous step instead of dying)."""
    if step is not None:
        return _load_checkpoint(_join(directory, f"step-{int(step)}"))
    steps = _list_steps(directory)
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {directory!r}")
    last_err: Optional[Exception] = None
    for s in reversed(steps):
        path = _join(directory, f"step-{s}")
        try:
            return _load_checkpoint(path)
        except CheckpointCorruptError as e:
            warnings.warn(f"{e} — falling back to the previous checkpoint",
                          RuntimeWarning)
            last_err = e
    raise CheckpointCorruptError(
        f"no checkpoint under {directory!r} passes verification "
        f"({len(steps)} candidates)") from last_err


def verify(path: str) -> bool:
    """True when the checkpoint directory `path` is complete and its
    recorded digests match the stored bytes (vacuously true for
    pre-digest-format checkpoints that load cleanly). Store trouble
    PROPAGATES rather than reading as False — a bucket outage
    (ConnectionError after the retry budget) or an auth/5xx HTTPError
    (anything but a definitive 404, which _load_checkpoint already maps
    to corruption) — otherwise retain()'s protect scan would misread a
    transient store failure as "nothing verifies" and could delete the
    only restorable checkpoint."""
    try:
        _load_checkpoint(path)
        return True
    except ConnectionError:
        raise
    except urllib.error.HTTPError:
        raise
    except Exception:
        return False


def newest_verified_step(directory: str, skip_anomalous: bool = False
                         ) -> Optional[int]:
    """Newest step that passes `verify` — the health supervisor's rollback
    target. `skip_anomalous=True` additionally skips checkpoints tagged
    `extra["anomalous"]` (taken during an unhealthy training window: the
    state may embed the anomaly being rolled away from)."""
    found = restore_newest_verified(directory, skip_anomalous=skip_anomalous)
    return found[1] if found is not None else None


def restore_newest_verified(directory: str, skip_anomalous: bool = False
                            ) -> Optional[Tuple[Dict[str, np.ndarray], int,
                                                Dict[str, Any]]]:
    """Load the newest checkpoint that verifies (optionally skipping
    anomalous-tagged ones), as one pass: verification IS the load, so the
    rollback path pays a single read+digest of the multi-GB state instead
    of verify-then-restore doing it twice. Returns (flat, step, extra) or
    None."""
    for s in reversed(_list_steps(directory)):
        path = _join(directory, f"step-{s}")
        meta = _load_meta(path)
        if meta is None:
            continue
        if skip_anomalous and meta.get("extra", {}).get("anomalous"):
            continue
        try:
            return _load_checkpoint(path)
        except CheckpointCorruptError:
            continue
    return None


def retain(directory: str, keep: int = 3) -> None:
    """Delete all but the newest `keep` checkpoints — but NEVER the newest
    one that verifies, NOR the newest verified NON-anomalous one: when
    newer checkpoints are corrupt, or a long unhealthy window has tagged
    every recent save `anomalous`, retention must not destroy the only
    state a resume/rollback can still use. The protection re-verifies from
    the store — one extra read+hash of the newest snapshot per save, a
    full ranged-GET of state.npz on a bucket — EXCEPT in the common case
    where the step under scan is the one this process just wrote and its
    store fingerprint is unchanged (`_written_verified` above): then the
    write-time digests stand in for the read-back and the scan costs one
    stat. Steps written by other processes always get the full read-back
    verification."""
    steps = _list_steps(directory)
    if not steps:
        return
    protect = set(steps[-keep:]) if keep else set()
    # one newest-first scan finds both targets (in the common case — the
    # newest checkpoint verifies and is non-anomalous — exactly one
    # verification runs, and the written-cache reduces even that to a
    # stat): the newest verified step, and the newest verified
    # NON-anomalous one (the rollback selector's candidate)
    newest_verified = None
    for s in reversed(steps):
        path = _join(directory, f"step-{s}")
        meta = _load_meta(path)
        if meta is None:
            continue
        anomalous = bool(meta.get("extra", {}).get("anomalous"))
        if newest_verified is not None and anomalous:
            continue  # only the non-anomalous target is still open
        if _written_verified_hit(directory, s) or verify(path):
            if newest_verified is None:
                newest_verified = s
                protect.add(s)
            if not anomalous:
                protect.add(s)
                break
    for s in steps:
        if s not in protect:
            _delete_step(directory, s)


class AsyncCheckpointWriter:
    """Stage-2 writer of the two-stage async checkpoint pipeline: ONE
    background thread runs the serialize + digest + persist closure while
    the round loop keeps training. At most one snapshot is ever in flight:
    `submit` first waits out the previous write (backpressure lands on the
    next SAVE, not on every round) and re-raises its failure — a dead
    checkpoint store must be loud, not silently skipped. `wait` is the
    barrier the rollback path and the loop exit take before READING the
    store (the in-flight write may be the newest verified checkpoint, and
    reading mid-write would race the commit marker)."""

    def __init__(self, registry=None):
        self._ex = ThreadPoolExecutor(1, thread_name_prefix="ckpt-write")
        self._pending = None
        # shared-schema telemetry (obs): write outcomes and durations, and
        # the submit-side backpressure stall the round loop actually feels
        self._c_writes = self._h_write = self._h_stall = None
        if registry is not None:
            # scope labels (r8): "snapshot" = the whole stage-2 closure;
            # sharded saves additionally report every per-shard file
            # write as scope="shard" and the manifest commit as
            # scope="meta" (save_sharded's metrics hook -> note_write),
            # so podview can attribute a slow save to the worker/shard
            # that dragged it
            self._c_writes = registry.counter(
                "sparknet_checkpoint_writes_total",
                "background checkpoint writes by outcome and scope "
                "(snapshot|shard|meta)",
                labels=("outcome", "scope"))
            self._h_write = registry.histogram(
                "sparknet_checkpoint_write_seconds",
                "stage-2 persist duration by scope (snapshot|shard|meta)",
                labels=("scope",))
            self._h_stall = registry.histogram(
                "sparknet_checkpoint_submit_stall_seconds",
                "round-loop blocking wait for the previous in-flight "
                "write at submit")

    @property
    def in_flight(self) -> bool:
        return self._pending is not None and not self._pending.done()

    def submit(self, fn, *args, **kwargs) -> None:
        """Queue one write; blocks until the PREVIOUS one finished (and
        re-raises its exception, if any)."""
        t0 = time.perf_counter()
        self.wait()
        if self._h_stall is not None:
            self._h_stall.observe(time.perf_counter() - t0)

        def run():
            # the span puts stage 2 on its own `ckpt-write_0` lane in the
            # trace timeline — the cross-thread view of what the round
            # loop overlapped
            t1 = time.perf_counter()
            from ..obs import trace as _trace
            try:
                with _trace.span("checkpoint_write"):
                    fn(*args, **kwargs)
            except BaseException:
                if self._c_writes is not None:
                    self._c_writes.inc(outcome="error", scope="snapshot")
                raise
            if self._c_writes is not None:
                self._c_writes.inc(outcome="ok", scope="snapshot")
                self._h_write.observe(time.perf_counter() - t1,
                                      scope="snapshot")

        self._pending = self._ex.submit(run)

    def note_write(self, scope: str, seconds: float, ok: bool = True
                   ) -> None:
        """Per-file instrumentation hook for sharded saves (save_sharded
        `metrics=`): one count + duration per shard file (scope="shard")
        and per manifest commit (scope="meta"). Thread-safe (registry
        families lock internally); a no-op without a registry."""
        if self._c_writes is not None:
            self._c_writes.inc(outcome="ok" if ok else "error",
                               scope=scope)
            if ok:
                self._h_write.observe(seconds, scope=scope)

    def wait(self) -> None:
        """Block until the in-flight write (if any) completes; re-raise
        its failure."""
        pending, self._pending = self._pending, None
        if pending is not None:
            pending.result()

    def close(self, wait: bool = True) -> None:
        """Drain (re-raising a failed write when `wait`) and stop the
        thread. With wait=False a queued-but-unstarted write is cancelled;
        a RUNNING write always completes (never tear a half-written
        snapshot on purpose)."""
        try:
            if wait:
                self.wait()
        finally:
            self._ex.shutdown(wait=wait, cancel_futures=not wait)
