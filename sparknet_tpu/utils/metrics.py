"""Metrics registry + phase timers.

Replaces the reference's ad-hoc stdout spans (`transformInto took ...`,
`ForwardBackward took ...` at `libs/CaffeNet.scala:113-120`; `stuff took /
iters took` in the apps) with named accumulating timers and a throughput
meter (images/sec/chip — the BASELINE.md headline unit). `LatencyStats` and
`FillMeter` are the serving side's additions: request-latency quantiles and
the dynamic batcher's fill ratio (sparknet_tpu/serve surfaces both through
its /metrics status and the metrics JSONL).
"""
from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Optional


class PhaseTimers:
    """Accumulating named wall-clock spans (per-phase step breakdown)."""

    def __init__(self):
        self.total: Dict[str, float] = {}
        self.count: Dict[str, int] = {}

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.total[name] = self.total.get(name, 0.0) + dt
            self.count[name] = self.count.get(name, 0) + 1

    def mean(self, name: str) -> float:
        return self.total.get(name, 0.0) / max(self.count.get(name, 0), 1)

    def summary(self) -> Dict[str, float]:
        return {f"{k}_mean_s": round(self.mean(k), 6) for k in self.total}

    def reset(self) -> None:
        self.total.clear()
        self.count.clear()


class ThroughputMeter:
    """images/sec (/chip if n_chips given), over a sliding accumulation."""

    def __init__(self, n_chips: int = 1):
        self.n_chips = n_chips
        self.images = 0
        self.seconds = 0.0

    def add(self, n_images: int, seconds: float) -> None:
        self.images += n_images
        self.seconds += seconds

    def images_per_sec(self) -> float:
        return self.images / self.seconds if self.seconds else 0.0

    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec() / self.n_chips

    def reset(self) -> None:
        self.images = 0
        self.seconds = 0.0


class LatencyStats:
    """Sliding-window latency quantiles (p50/p99) over the last `window`
    observations. A bounded deque, not a histogram: serving windows are a
    few thousand requests, where exact order statistics are cheaper than
    tuning bucket boundaries, and the window naturally ages out a warmup
    or a transient stall instead of averaging it into eternity."""

    def __init__(self, window: int = 4096):
        self._obs: deque = deque(maxlen=max(2, window))
        self.count = 0

    def add(self, seconds: float) -> None:
        self._obs.append(float(seconds))
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        """Exact order statistic over the window (nearest-rank), or None
        with no observations."""
        if not self._obs:
            return None
        xs = sorted(self._obs)
        i = min(len(xs) - 1, max(0, int(q * len(xs))))
        return xs[i]

    def summary(self) -> Dict[str, Optional[float]]:
        out: Dict[str, Optional[float]] = {"n": self.count}
        for name, q in (("p50_ms", 0.50), ("p90_ms", 0.90),
                        ("p99_ms", 0.99)):
            v = self.quantile(q)
            out[name] = None if v is None else round(v * 1e3, 3)
        return out

    def reset(self) -> None:
        self._obs.clear()
        self.count = 0


class FillMeter:
    """Batch-fill accounting for the dynamic batcher: real examples over
    padded bucket slots. fill == 1.0 means every compiled forward ran at
    its bucket's full width; low fill at high offered load means the
    batcher is flushing early (deadline too tight or buckets too big)."""

    def __init__(self):
        self.real = 0
        self.padded = 0
        self.batches = 0

    def add(self, n_real: int, bucket: int) -> None:
        self.real += int(n_real)
        self.padded += int(bucket)
        self.batches += 1

    def ratio(self) -> float:
        return self.real / self.padded if self.padded else 0.0

    def reset(self) -> None:
        self.real = self.padded = self.batches = 0
