"""Metrics registry + phase timers.

Replaces the reference's ad-hoc stdout spans (`transformInto took ...`,
`ForwardBackward took ...` at `libs/CaffeNet.scala:113-120`; `stuff took /
iters took` in the apps) with named accumulating timers and a throughput
meter (images/sec/chip — the BASELINE.md headline unit).
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional


class PhaseTimers:
    """Accumulating named wall-clock spans (per-phase step breakdown)."""

    def __init__(self):
        self.total: Dict[str, float] = {}
        self.count: Dict[str, int] = {}

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.total[name] = self.total.get(name, 0.0) + dt
            self.count[name] = self.count.get(name, 0) + 1

    def mean(self, name: str) -> float:
        return self.total.get(name, 0.0) / max(self.count.get(name, 0), 1)

    def summary(self) -> Dict[str, float]:
        return {f"{k}_mean_s": round(self.mean(k), 6) for k in self.total}

    def reset(self) -> None:
        self.total.clear()
        self.count.clear()


class ThroughputMeter:
    """images/sec (/chip if n_chips given), over a sliding accumulation."""

    def __init__(self, n_chips: int = 1):
        self.n_chips = n_chips
        self.images = 0
        self.seconds = 0.0

    def add(self, n_images: int, seconds: float) -> None:
        self.images += n_images
        self.seconds += seconds

    def images_per_sec(self) -> float:
        return self.images / self.seconds if self.seconds else 0.0

    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec() / self.n_chips

    def reset(self) -> None:
        self.images = 0
        self.seconds = 0.0
