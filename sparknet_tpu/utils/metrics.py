"""Metrics meters + phase timers, registry-backed.

Replaces the reference's ad-hoc stdout spans (`transformInto took ...`,
`ForwardBackward took ...` at `libs/CaffeNet.scala:113-120`; `stuff took /
iters took` in the apps) with named accumulating timers and a throughput
meter (images/sec/chip — the BASELINE.md headline unit). `LatencyStats` and
`FillMeter` are the serving side's additions: request-latency quantiles and
the dynamic batcher's fill ratio.

Since the obs PR these meters are the WRITE-side convenience layer over
`sparknet_tpu.obs.MetricsRegistry`: constructed with a registry they also
register the shared-schema metrics (sparknet_*_phase_seconds_total,
sparknet_serve_request_latency_seconds, ...) and update them on every
mutation, so /metrics on the train and serve status servers render from
one source of truth. They also carry their own locks: `summary()` /
`snapshot()` readers on the HTTP thread get a CONSISTENT view of state a
worker thread is mutating (the old live-attribute reads could tear — a
sorted() over a deque being appended raises mid-iteration).

`PhaseTimers.phase(...)` additionally emits a host-side trace span
(obs.trace) — when a tracer is active every timed phase becomes a lane
entry in the Chrome trace timeline for free.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

from ..obs import trace as _trace
from ..obs.registry import MetricsRegistry


class PhaseTimers:
    """Accumulating named wall-clock spans (per-phase step breakdown).

    With a registry, each phase exit also feeds the counters
    `<prefix>_phase_seconds_total{phase=...}` and
    `<prefix>_phase_count_total{phase=...}`; an active tracer gets the
    phase as a span on the calling thread's lane."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 prefix: str = "sparknet_train"):
        self.total: Dict[str, float] = {}
        self.count: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._c_seconds = self._c_count = None
        if registry is not None:
            self._c_seconds = registry.counter(
                f"{prefix}_phase_seconds_total",
                "wall seconds accumulated per host-side phase",
                labels=("phase",))
            self._c_count = registry.counter(
                f"{prefix}_phase_count_total",
                "entries per host-side phase", labels=("phase",))

    @contextmanager
    def phase(self, name: str):
        with _trace.span(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self.total[name] = self.total.get(name, 0.0) + dt
                    self.count[name] = self.count.get(name, 0) + 1
                if self._c_seconds is not None:
                    self._c_seconds.inc(dt, phase=name)
                    self._c_count.inc(1, phase=name)

    def mean(self, name: str) -> float:
        with self._lock:
            return self.total.get(name, 0.0) / max(self.count.get(name, 0),
                                                   1)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            names = list(self.total)
        return {f"{k}_mean_s": round(self.mean(k), 6) for k in names}

    def reset(self) -> None:
        with self._lock:
            self.total.clear()
            self.count.clear()


class ThroughputMeter:
    """images/sec (/chip if n_chips given), over a sliding accumulation."""

    def __init__(self, n_chips: int = 1,
                 registry: Optional[MetricsRegistry] = None,
                 prefix: str = "sparknet_train"):
        self.n_chips = n_chips
        self.images = 0
        self.seconds = 0.0
        self._lock = threading.Lock()
        self._c_images = self._g_ips = None
        if registry is not None:
            self._c_images = registry.counter(
                f"{prefix}_images_total", "examples trained/served")
            self._g_ips = registry.gauge(
                f"{prefix}_images_per_sec_per_chip",
                "throughput over the accumulation window")

    def add(self, n_images: int, seconds: float) -> None:
        with self._lock:
            self.images += n_images
            self.seconds += seconds
        if self._c_images is not None:
            self._c_images.inc(n_images)
            self._g_ips.set(self.images_per_sec_per_chip())

    def images_per_sec(self) -> float:
        with self._lock:
            return self.images / self.seconds if self.seconds else 0.0

    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec() / self.n_chips

    def reset(self) -> None:
        with self._lock:
            self.images = 0
            self.seconds = 0.0


def _rank(xs, q: float) -> float:
    """Nearest-rank order statistic over sorted xs (non-empty)."""
    i = min(len(xs) - 1, max(0, int(q * len(xs))))
    return xs[i]


class LatencyStats:
    """Sliding-window latency quantiles (p50/p99) over the last `window`
    observations. A bounded deque, not a histogram: serving windows are a
    few thousand requests, where exact order statistics are cheaper than
    tuning bucket boundaries, and the window naturally ages out a warmup
    or a transient stall instead of averaging it into eternity. (The
    registry half DOES get a fixed-bucket histogram —
    `<name>` in seconds — because Prometheus quantiles are computed
    server-side from cumulative buckets.)"""

    def __init__(self, window: int = 4096,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "sparknet_serve_request_latency_seconds",
                 model: Optional[str] = None,
                 max_age_s: float = 300.0):
        """`model` labels the registry histogram (serve lanes sharing one
        registry across models); None keeps the unlabeled family — but
        the two modes must not mix within one registry/name. `max_age_s`
        is the on-record pruning horizon: observations older than it are
        dropped from the left at `add` time, so memory is bounded by
        BOTH the count window and the age horizon — sustained load never
        accumulates stale timestamps between `windowed()` calls."""
        self._obs: deque = deque(maxlen=max(2, window))
        # enqueue times of the SAME observations (parallel deque, same
        # maxlen, appended under the same lock): the fleet controller's
        # SLO-burn signal is a TIME-sliding p99, not a count-sliding one
        # — 4096 trickle observations can span an hour, and an autoscaler
        # acting on an hour-old tail would chase ghosts
        self._obs_t: deque = deque(maxlen=max(2, window))
        self.max_age_s = float(max_age_s)
        self._lock = threading.Lock()
        self.count = 0
        self._hist = None
        self._labels = {} if model is None else {"model": str(model)}
        if registry is not None:
            self._hist = registry.histogram(
                name, "request latency, submit to response",
                labels=tuple(self._labels))

    def add(self, seconds: float) -> None:
        now = time.monotonic()
        with self._lock:
            # prune-to-window on record: both deques stay parallel, and
            # entries older than max_age_s never outlive the next add —
            # len(self._obs) <= min(maxlen, arrivals within max_age_s)
            cutoff = now - self.max_age_s
            while self._obs_t and self._obs_t[0] < cutoff:
                self._obs_t.popleft()
                self._obs.popleft()
            self._obs.append(float(seconds))
            self._obs_t.append(now)
            self.count += 1
        if self._hist is not None:
            self._hist.observe(seconds, **self._labels)

    def quantile(self, q: float) -> Optional[float]:
        """Exact order statistic over the window (nearest-rank), or None
        with no observations."""
        with self._lock:
            xs = sorted(self._obs)
        return _rank(xs, q) if xs else None

    def windowed_quantile(self, q: float, window_s: float
                          ) -> Optional[float]:
        """Exact order statistic (SECONDS) over the observations of the
        last `window_s` seconds, or None if the window holds nothing —
        the hedging delay's input (e.g. p95 of routed latency): hedge
        timing must track the LIVE distribution, not an hour-old one."""
        cutoff = time.monotonic() - float(window_s)
        with self._lock:
            xs = sorted(v for v, t in zip(self._obs, self._obs_t)
                        if t >= cutoff)
        return _rank(xs, q) if xs else None

    def windowed(self, window_s: float) -> Dict[str, Optional[float]]:
        """p50/p99 (ms) + n over the observations of the last `window_s`
        seconds — the fleet controller's SLO-burn input. Returns
        {"n": 0, "p50_ms": None, "p99_ms": None} when the window holds
        nothing (a quiet model must read as NOT burning, never as stale-
        tail burning)."""
        cutoff = time.monotonic() - float(window_s)
        with self._lock:
            xs = sorted(v for v, t in zip(self._obs, self._obs_t)
                        if t >= cutoff)
        out: Dict[str, Optional[float]] = {"n": len(xs)}
        for name, q in (("p50_ms", 0.50), ("p99_ms", 0.99)):
            out[name] = round(_rank(xs, q) * 1e3, 3) if xs else None
        return out

    def summary(self) -> Dict[str, Optional[float]]:
        # ONE consistent copy for all three quantiles: a scrape racing the
        # worker's add() must not see p50 and p99 from different windows
        with self._lock:
            xs = sorted(self._obs)
            n = self.count
        out: Dict[str, Optional[float]] = {"n": n}  # lifetime count
        for name, q in (("p50_ms", 0.50), ("p90_ms", 0.90),
                        ("p99_ms", 0.99)):
            out[name] = round(_rank(xs, q) * 1e3, 3) if xs else None
        return out

    def reset(self) -> None:
        with self._lock:
            self._obs.clear()
            self._obs_t.clear()
            self.count = 0


class FillMeter:
    """Batch-fill accounting for the dynamic batcher: real examples over
    padded bucket slots. fill == 1.0 means every compiled forward ran at
    its bucket's full width; low fill at high offered load means the
    batcher is flushing early (deadline too tight or buckets too big).

    Also keeps the per-batch-SIZE histogram — how many formed batches
    carried exactly n real examples. That distribution is what
    `serve.buckets.derive_buckets` fits a bucket ladder to (the Orca
    lesson: schedule the queue INTO the accelerator's batch shape), so
    the meter that measures fill also records the evidence for fixing
    it. The histogram lands in /status and the serve JSONL
    (`batch_size_hist`), and in the registry as
    `<prefix>_size_batches_total{model,size}` (cardinality is bounded by
    max_batch)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 prefix: str = "sparknet_serve_batch",
                 model: Optional[str] = None):
        """`model` labels the registry families (multi-model routers share
        one registry); None keeps them unlabeled — don't mix modes within
        one registry/prefix."""
        self.real = 0
        self.padded = 0
        self.batches = 0
        self.size_counts: Dict[int, int] = {}
        # the last few formed batches as (real, bucket) pairs: the
        # router's coalesced-formation trigger reads RECENT fill, not
        # the cumulative ratio (which a long full-batch history would
        # pin near 1.0 long after the load turned to trickle)
        self._recent: deque = deque(maxlen=64)
        self._lock = threading.Lock()
        self._labels = {} if model is None else {"model": str(model)}
        self._c_rows = self._c_batches = self._g_fill = None
        self._c_sizes = None
        if registry is not None:
            lnames = tuple(self._labels)
            self._c_rows = registry.counter(
                f"{prefix}_rows_total",
                "batch rows by kind (real examples vs padding slots)",
                labels=lnames + ("kind",))
            self._c_batches = registry.counter(
                f"{prefix}es_total", "compiled forwards run",
                labels=lnames)
            self._g_fill = registry.gauge(
                f"{prefix}_fill_ratio",
                "real rows / padded bucket slots, cumulative",
                labels=lnames)
            self._c_sizes = registry.counter(
                f"{prefix}_size_batches_total",
                "formed batches by real-example count (the bucket-ladder "
                "derivation input)", labels=lnames + ("size",))

    def add(self, n_real: int, bucket: int) -> None:
        with self._lock:
            self.real += int(n_real)
            self.padded += int(bucket)
            self.batches += 1
            self.size_counts[int(n_real)] = \
                self.size_counts.get(int(n_real), 0) + 1
            self._recent.append((int(n_real), int(bucket)))
        if self._c_rows is not None:
            self._c_rows.inc(int(n_real), kind="real", **self._labels)
            self._c_rows.inc(int(bucket) - int(n_real), kind="padding",
                             **self._labels)
            self._c_batches.inc(**self._labels)
            self._g_fill.set(self.ratio(), **self._labels)
            self._c_sizes.inc(size=int(n_real), **self._labels)

    def ratio(self) -> float:
        with self._lock:
            return self.real / self.padded if self.padded else 0.0

    def recent_ratio(self, n: int = 16) -> Optional[float]:
        """Fill over the last `n` formed batches, or None with no recent
        batches: real rows over the PADDED BUCKET slots they ran in."""
        with self._lock:
            tail = list(self._recent)[-int(n):]
        real = sum(r for r, _ in tail)
        padded = sum(b for _, b in tail)
        return real / padded if padded else None

    def recent_occupancy(self, capacity: int,
                         n: int = 16) -> Optional[float]:
        """Mean real rows per recent batch as a fraction of `capacity`
        (max_batch) — the coalescing trigger (router). Bucket-relative
        fill is blind to a fragmented trickle (a single request pads
        into bucket 1 at fill 1.0); occupancy vs CAPACITY is what
        routing consecutive requests to one replica can improve."""
        with self._lock:
            tail = list(self._recent)[-int(n):]
        if not tail or capacity <= 0:
            return None
        real = sum(r for r, _ in tail)
        return min(1.0, real / (len(tail) * capacity))

    def snapshot(self) -> Tuple[int, int, int]:
        """(real, padded, batches) read consistently under the lock."""
        with self._lock:
            return self.real, self.padded, self.batches

    def size_hist(self) -> Dict[int, int]:
        """{real batch size: formed batches} — a consistent copy."""
        with self._lock:
            return dict(self.size_counts)

    def reset(self) -> None:
        with self._lock:
            self.real = self.padded = self.batches = 0
            self.size_counts.clear()
            self._recent.clear()
