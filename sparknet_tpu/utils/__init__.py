from .logger import Logger, default_logger  # noqa: F401
from .metrics import PhaseTimers, ThroughputMeter  # noqa: F401
from .config import RunConfig  # noqa: F401
from . import checkpoint  # noqa: F401
