"""Training logger: reference `libs/Logger.scala` parity plus structure.

The reference logged wall-clock-elapsed-prefixed lines to
`training_log_<millis>.txt`, flushed per line, with an optional iteration
index (`Logger.scala:5-18`). Same here, plus console echo and a JSONL twin
for machine-readable metrics (the reference's gap, SURVEY §5.5).
"""
from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any, Dict, Optional


def _json_safe(v: Any) -> Any:
    """NaN/Inf serialize as null: json.dumps would emit bare NaN/Infinity
    tokens, which are outside RFC 8259 and break jq / pandas / non-Python
    consumers of the metrics JSONL (nonfinite rounds are now ROUTINELY
    logged by the health supervisor instead of crashing the run)."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


class Logger:
    def __init__(self, path: Optional[str] = None, echo: bool = True,
                 jsonl_path: Optional[str] = None,
                 worker: Optional[int] = None):
        self.t0 = time.time()
        self.echo = echo
        # worker id stamped on every JSONL record — the key that lets
        # `sparknet-metrics` group N merged per-worker files into the pod
        # view (per-worker breakdown, round skew, straggler audit). The
        # train loop fills it in on multi-host runs when the caller
        # didn't; single-process records stay byte-identical to before.
        self.worker = worker
        self._f = open(path, "a", buffering=1) if path else None
        self._jsonl = open(jsonl_path, "a", buffering=1) if jsonl_path else None

    def log(self, message: str, i: Optional[int] = None) -> None:
        """Elapsed-seconds-prefixed line (reference `logger.log(msg, i)`)."""
        elapsed = time.time() - self.t0
        suffix = f", iteration = {i}" if i is not None else ""
        line = f"[{elapsed:.3f}s] {message}{suffix}"
        if self._f:
            self._f.write(line + "\n")
        if self.echo:
            print(line, file=sys.stderr, flush=True)

    def metrics(self, step: int, **kv: Any) -> None:
        """One JSONL record: {"step": ..., "t": ..., "ts": ..., **metrics}.

        `t` is run-relative (human diffing within one file); `ts` is
        wall-clock epoch seconds, so JSONLs from different PROCESSES — a
        trainer, its serve fleet, the checkpoint writer's events — merge
        on one timeline (`sparknet-metrics a.jsonl b.jsonl` sorts on it,
        and it matches the trace timeline's epoch-anchored microseconds).
        """
        if self._jsonl:
            now = time.time()
            rec: Dict[str, Any] = {"step": step,
                                   "t": round(now - self.t0, 3),
                                   "ts": round(now, 3)}
            if self.worker is not None:
                rec["worker"] = int(self.worker)
            rec.update({k: _json_safe(float(v) if hasattr(v, "__float__")
                                      else v)
                        for k, v in kv.items()})
            self._jsonl.write(json.dumps(rec) + "\n")

    def event(self, step: int, event: str, **kv: Any) -> None:
        """A structured lifecycle event in BOTH channels: a human line in
        the text log and an {"event": ...} record in the metrics JSONL —
        the health supervisor's audit trail (spike_skip, rollback,
        anomalous_checkpoint, ...) must be machine-recoverable next to the
        loss curve it explains."""
        detail = " ".join(f"{k}={v}" for k, v in kv.items())
        self.log(f"[{event}] {detail}" if detail else f"[{event}]", step)
        self.metrics(step, event=event, **kv)

    def close(self) -> None:
        for f in (self._f, self._jsonl):
            if f:
                f.close()


def default_logger(workdir: Optional[str] = None, name: str = "training"
                   ) -> Logger:
    """Reference naming convention: training_log_<millis>.txt under the
    framework home (`apps/CifarApp.scala:51`)."""
    if workdir is None:
        workdir = os.environ.get("SPARKNET_TPU_HOME", ".")
    os.makedirs(workdir, exist_ok=True)
    ms = int(time.time() * 1000)
    return Logger(path=os.path.join(workdir, f"{name}_log_{ms}.txt"),
                  jsonl_path=os.path.join(workdir, f"{name}_metrics_{ms}.jsonl"))
