"""Liveness heartbeat: one small JSON file, atomically replaced in place.

`tpu_pod_launch.sh watch` can only see process exit codes and VM states, so
a run that is alive-but-sick (every round classified anomalous, rollback
budget draining) and a run that is merely slow (long rounds, healthy
classifications) look identical until the log is parsed. The heartbeat file
is the machine-readable middle ground: the training loop rewrites it at the
log_every cadence with the HealthMonitor's latest view, and the serving
model manager rewrites it with the hot-reload state, BOTH in the same
schema, so one probe (`read_heartbeat` here, or `TPU_HEARTBEAT_FILE` in the
launcher's watch loop) answers "is it making healthy progress" for either
role without touching the logs.

Schema (one flat JSON object):
  t               epoch seconds of the beat (staleness = now - t)
  pid, role       writer identity; role is "train" or "serve"
  step            round index (train) / served checkpoint step (serve)
  status          "ok", or the latest anomaly classification ("spike",
                  "nonfinite", "rollback"), or a serve state ("degraded"
                  when the last swap attempt failed, "done" on exit)
  rollbacks       health rollbacks so far (train) / rejected or rolled-back
                  weight swaps (serve)
  ...             writer-specific extras (e.g. last_loss, queue_depth)

Writes are atomic (tmp file + os.replace in the same directory) so a
reader never sees a torn JSON, and throttled to `interval_s` except when
`force=True` (status CHANGES always deserve a beat — the whole point is
that "sick" shows up promptly).

`path` may also be a `gs://`/`s3://` URL: the beat becomes one small
object PUT through the same native bucket writers checkpointing uses
(single-object writes are atomic on both stores), which is what lets a
POD write per-worker heartbeats to one shared prefix with no shared
filesystem — the pod aggregator (`obs/pod.py`) reads them back from
anywhere. Bucket PUTs run on a background thread with a latest-wins
one-slot queue: the caller is the training round loop, and an object-
store stall must cost it a dict handoff, not a client timeout (the same
off-the-critical-path rule the async checkpoint writer enforces).
`flush()` drains the slot (bounded wait) so a final "done" beat lands
before process exit.
"""
from __future__ import annotations

import json
import os
import queue
import tempfile
import threading
import time
import warnings
from typing import Any, Dict, Optional


def _is_bucket(path: str) -> bool:
    return isinstance(path, str) and path.startswith(("gs://", "s3://"))


class HeartbeatWriter:
    """Throttled atomic writer of the heartbeat schema above."""

    def __init__(self, path: str, role: str = "train",
                 interval_s: float = 10.0, registry=None):
        self.path = path
        self.role = role
        self.interval_s = float(interval_s)
        self._last_t = 0.0
        self._last_status: Optional[str] = None
        self._q: Optional["queue.Queue"] = None
        if _is_bucket(path):
            # latest-wins one-slot queue + daemon writer: a beat is a
            # dict handoff on the caller's (round-loop) thread; the PUT
            # and any store stall happen over here
            self._q = queue.Queue(maxsize=1)
            threading.Thread(target=self._drain_bucket,
                             name="heartbeat-write", daemon=True).start()
        else:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
        # registry mirror (obs): a scraper that cannot reach the file —
        # Prometheus across hosts — still sees beat freshness and status
        self._c_beats = self._g_ts = None
        if registry is not None:
            self._c_beats = registry.counter(
                "sparknet_heartbeat_beats_total",
                "heartbeat file writes", labels=("role",))
            self._g_ts = registry.gauge(
                "sparknet_heartbeat_timestamp_seconds",
                "epoch seconds of the last beat (staleness = now - this)",
                labels=("role",))

    def _drain_bucket(self) -> None:
        from .checkpoint import _bucket_ops
        ops = _bucket_ops(self.path)
        while True:
            rec = self._q.get()
            try:
                ops.write(self.path, json.dumps(rec).encode())
            except Exception as e:
                # best-effort by contract: a store blip drops this beat,
                # the next one overwrites anyway
                warnings.warn(f"heartbeat bucket write failed: {e}",
                              RuntimeWarning)
            finally:
                self._q.task_done()

    def flush(self, timeout_s: float = 5.0) -> None:
        """Bounded wait for the in-flight bucket PUT (exit paths: the
        final 'done' beat should land before the process dies). Local
        writes are synchronous — nothing to flush."""
        if self._q is None:
            return
        deadline = time.monotonic() + timeout_s
        while self._q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.05)

    def beat(self, step: int, status: str = "ok", rollbacks: int = 0,
             force: bool = False, **extra: Any) -> bool:
        """Write one heartbeat; returns True when a write happened.
        Throttled to `interval_s` unless `force` or the status changed
        since the last write."""
        now = time.time()
        if (not force and status == self._last_status
                and now - self._last_t < self.interval_s):
            return False
        rec: Dict[str, Any] = {"t": round(now, 3), "pid": os.getpid(),
                               "role": self.role, "step": int(step),
                               "status": str(status),
                               "rollbacks": int(rollbacks)}
        rec.update(extra)
        if self._q is not None:
            # bucket path: hand the record to the writer thread, latest
            # wins — if a PUT is still in flight, the queued (older)
            # record is replaced rather than blocking the caller
            try:
                self._q.put_nowait(rec)
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self._q.task_done()
                except queue.Empty:
                    pass
                try:
                    self._q.put_nowait(rec)
                except queue.Full:
                    pass  # raced a concurrent beater; their rec is newer
            self._last_t = now
            self._last_status = status
            if self._c_beats is not None:
                self._c_beats.inc(role=self.role)
                self._g_ts.set(now, role=self.role)
            return True
        d = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".hb-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._last_t = now
        self._last_status = status
        if self._c_beats is not None:
            self._c_beats.inc(role=self.role)
            self._g_ts.set(now, role=self.role)
        return True


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """The current heartbeat dict, or None when the file is missing or
    torn (a torn read is impossible from HeartbeatWriter's atomic replace,
    but a foreign/partial file must not crash the prober). Accepts
    `gs://`/`s3://` URLs like the writer.

    The returned dict additionally carries `age_s` — seconds since the
    beat was written, computed at READ time — so every consumer (the
    launcher watch, the pod aggregator/podview, the elastic
    MembershipController) applies one staleness rule to one number
    instead of re-deriving it from `t` with its own clock arithmetic.
    `age_s` is None when the record has no `t` (foreign file)."""
    try:
        if _is_bucket(path):
            from .checkpoint import _bucket_ops
            hb = json.loads(_bucket_ops(path).read(path))
        else:
            with open(path) as f:
                hb = json.load(f)
    except (OSError, ValueError):
        return None
    except Exception:
        return None  # bucket client errors degrade like a missing file
    if not isinstance(hb, dict):
        return None
    try:
        hb["age_s"] = round(max(0.0, time.time() - float(hb["t"])), 3)
    except (KeyError, TypeError, ValueError):
        hb["age_s"] = None
    return hb


def staleness_s(hb: Optional[Dict[str, Any]]) -> Optional[float]:
    """Seconds since the beat was written, or None without a valid beat.
    Prefers the `age_s` read_heartbeat stamped (one clock read per probe);
    falls back to `t` for records obtained some other way."""
    if not hb:
        return None
    if hb.get("age_s") is not None:
        return float(hb["age_s"])
    if "t" not in hb:
        return None
    try:
        return max(0.0, time.time() - float(hb["t"]))
    except (TypeError, ValueError):
        return None


def worker_sort_key(w: str):
    """Numeric-first worker-id ordering ('2' < '10'; names after digits)
    — THE ordering elastic membership, τ expansion, and the pod view all
    share (one definition; divergence would silently misalign the
    membership order against the pod table)."""
    return (0, int(w)) if str(w).isdigit() else (1, str(w))
