"""Liveness heartbeat: one small JSON file, atomically replaced in place.

`tpu_pod_launch.sh watch` can only see process exit codes and VM states, so
a run that is alive-but-sick (every round classified anomalous, rollback
budget draining) and a run that is merely slow (long rounds, healthy
classifications) look identical until the log is parsed. The heartbeat file
is the machine-readable middle ground: the training loop rewrites it at the
log_every cadence with the HealthMonitor's latest view, and the serving
model manager rewrites it with the hot-reload state, BOTH in the same
schema, so one probe (`read_heartbeat` here, or `TPU_HEARTBEAT_FILE` in the
launcher's watch loop) answers "is it making healthy progress" for either
role without touching the logs.

Schema (one flat JSON object):
  t               epoch seconds of the beat (staleness = now - t)
  pid, role       writer identity; role is "train" or "serve"
  step            round index (train) / served checkpoint step (serve)
  status          "ok", or the latest anomaly classification ("spike",
                  "nonfinite", "rollback"), or a serve state ("degraded"
                  when the last swap attempt failed, "done" on exit)
  rollbacks       health rollbacks so far (train) / rejected or rolled-back
                  weight swaps (serve)
  ...             writer-specific extras (e.g. last_loss, queue_depth)

Writes are atomic (tmp file + os.replace in the same directory) so a
reader never sees a torn JSON, and throttled to `interval_s` except when
`force=True` (status CHANGES always deserve a beat — the whole point is
that "sick" shows up promptly).
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, Optional


class HeartbeatWriter:
    """Throttled atomic writer of the heartbeat schema above."""

    def __init__(self, path: str, role: str = "train",
                 interval_s: float = 10.0, registry=None):
        self.path = path
        self.role = role
        self.interval_s = float(interval_s)
        self._last_t = 0.0
        self._last_status: Optional[str] = None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # registry mirror (obs): a scraper that cannot reach the file —
        # Prometheus across hosts — still sees beat freshness and status
        self._c_beats = self._g_ts = None
        if registry is not None:
            self._c_beats = registry.counter(
                "sparknet_heartbeat_beats_total",
                "heartbeat file writes", labels=("role",))
            self._g_ts = registry.gauge(
                "sparknet_heartbeat_timestamp_seconds",
                "epoch seconds of the last beat (staleness = now - this)",
                labels=("role",))

    def beat(self, step: int, status: str = "ok", rollbacks: int = 0,
             force: bool = False, **extra: Any) -> bool:
        """Write one heartbeat; returns True when a write happened.
        Throttled to `interval_s` unless `force` or the status changed
        since the last write."""
        now = time.time()
        if (not force and status == self._last_status
                and now - self._last_t < self.interval_s):
            return False
        rec: Dict[str, Any] = {"t": round(now, 3), "pid": os.getpid(),
                               "role": self.role, "step": int(step),
                               "status": str(status),
                               "rollbacks": int(rollbacks)}
        rec.update(extra)
        d = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".hb-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._last_t = now
        self._last_status = status
        if self._c_beats is not None:
            self._c_beats.inc(role=self.role)
            self._g_ts.set(now, role=self.role)
        return True


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """The current heartbeat dict, or None when the file is missing or
    torn (a torn read is impossible from HeartbeatWriter's atomic replace,
    but a foreign/partial file must not crash the prober)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def staleness_s(hb: Optional[Dict[str, Any]]) -> Optional[float]:
    """Seconds since the beat was written, or None without a valid beat."""
    if not hb or "t" not in hb:
        return None
    return max(0.0, time.time() - float(hb["t"]))
