"""Typed run configuration.

The reference's config was scattered across four channels (positional argv,
env vars, model/solver data files, hardcoded app constants — SURVEY §5.6).
Here one dataclass covers model, solver, data, mesh, τ, eval cadence,
checkpointing; loadable from JSON and overridable from CLI key=value pairs.
Model/solver remain loadable from prototxt data files (capability parity).
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ..solver import SolverConfig
from .health import HealthConfig


@dataclass
class ElasticConfig:
    """Knobs for elastic, preemption-tolerant pod membership
    (RunConfig.elastic; driven by `parallel.elastic.MembershipController`).

    Liveness is read from the per-worker heartbeats under
    `RunConfig.pod_dir` (the pod observability surface — no new channel).
    A worker whose beat ages past `stale_after_s` becomes SUSPECT, is
    re-probed with full-jitter backoff, and is declared dead only after
    `dead_probes` consecutive stale probes — never on a single missed
    beat. The same `stale_after_s` threshold feeds the pod aggregator and
    the launcher watch so "stale" means one thing everywhere.

    On a membership change the train loop resizes at the τ boundary:
    checkpoint, rebuild the compiled round over the survivors, restore
    through the newest VERIFIED snapshot (params exact; momentum per
    `momentum_policy` — norm_rescale won the r5 A/B,
    scripts/elastic_momentum_ab.py / ELASTIC_AB_r05.json), reshard the
    data partitions, and continue. Dropping below `min_workers`
    checkpoints and raises TrainingHealthError — loud, never a hang.
    """

    enabled: bool = False
    # how many workers the pod was LAUNCHED with (worker ids 0..N-1, the
    # worker-heartbeat naming convention). None = jax.process_count().
    # A launched-but-never-beating worker is a candidate-dead from the
    # start — it goes through the normal suspect -> re-probe -> evict
    # path instead of silently shrinking the pod's definition.
    expected_workers: Optional[int] = None
    # dead-vs-slow: heartbeat age that makes a worker suspect (shared
    # with PodAggregator staleness and the launcher watch probe)
    stale_after_s: float = 60.0
    # full-jitter re-probe: suspect worker k is re-checked after
    # uniform(0, reprobe_backoff_s * 2^k); declared dead after
    # `dead_probes` consecutive stale probes (>= 1; the first stale
    # sighting is never enough on its own)
    reprobe_backoff_s: float = 2.0
    dead_probes: int = 2
    # membership checks are rate-limited to this interval (0 = every
    # round; the check is a heartbeat-prefix listing, cheap but not free)
    poll_interval_s: float = 5.0
    # below this many live workers: verified checkpoint + loud
    # TrainingHealthError (a 1-worker "pod" still trains by default)
    min_workers: int = 1
    # "adopt": a fresh heartbeat from an unknown/evicted worker id joins
    # the pod at the next τ boundary (restored from the newest verified
    # checkpoint); "deny": log-and-ignore (fixed membership after evict)
    rejoin: str = "adopt"
    # momentum reconstruction across a topology change
    # (ParallelTrainer.adapt_state policy; A/B winner norm_rescale)
    momentum_policy: str = "norm_rescale"
    # heterogeneous pods: scale each worker's local steps by the pod's
    # round-time skew — worker i runs tau_i = clip(round(tau * median_
    # round_s / round_s_i), tau_min, tau) steps of the τ-scan (the rest
    # are masked no-ops; a traced input, so adapting never recompiles)
    tau_adapt: bool = False
    tau_min: int = 1

    def __post_init__(self) -> None:
        # validated at CONSTRUCTION, not just from_dict: in-tree callers
        # build ElasticConfig directly, and a typo'd rejoin policy must
        # not silently behave as "adopt"
        if self.rejoin not in ("adopt", "deny"):
            raise ValueError(f"elastic.rejoin must be 'adopt' or 'deny', "
                             f"got {self.rejoin!r}")
        if self.dead_probes < 1:
            raise ValueError("elastic.dead_probes must be >= 1 (a single "
                             "missed beat must never evict)")

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ElasticConfig":
        known = {f.name for f in dataclasses.fields(ElasticConfig)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown elastic config keys: {sorted(unknown)}")
        return ElasticConfig(**d)


@dataclass
class RunConfig:
    # model
    model: str = "cifar10_quick"        # zoo name, or path to a .prototxt
    n_classes: int = 10
    # solver (inline or from solver_prototxt)
    solver: SolverConfig = field(default_factory=SolverConfig)
    solver_prototxt: Optional[str] = None
    # data
    data_dir: str = "data"
    subtract_mean: bool = True
    crop: Optional[int] = None
    # concurrent shard readers per host for streaming ingest (shards split
    # j::N across readers; kills the per-reader serial ceiling — a single
    # reader's tar-read/buffer-write residue caps it at ~5k img/s
    # regardless of host cores, PERF.md input-pipeline model)
    ingest_sources: int = 1
    # distribution
    n_devices: Optional[int] = None     # None = all visible
    tau: int = 10                       # local steps per sync round
    mode: str = "local_sgd"             # or "sync_sgd"
    local_batch: int = 100
    # trainer implementation for the layer-IR backend. "shard_map": the
    # replica-axis ParallelTrainer (state leaves carry a leading
    # [n_devices] axis). "named": the NamedSharding ShardedTrainer
    # (parallel/sharded.py — logical state placed by spec; prerequisite
    # for state_sharding below; parity-pinned against shard_map by
    # tests/test_sharded.py). "auto" (default): $SPARKNET_TRAINER_IMPL if
    # set (the CI matrix leg sets it to "named"), else "shard_map".
    trainer_impl: str = "auto"
    # ZeRO-1-style at-rest state sharding (trainer_impl="named" only;
    # requires tp == 1): "replicated" = exact reference semantics
    # (worker-local momentum); "momentum" = ONE momentum stored sharded
    # over the data axis (per-device optimizer-state HBM / n_data;
    # cross-worker averaged each round — the r5 A/B measured averaging
    # within noise of norm_rescale); "full" = params also stored sharded
    # at rest. PR 5's HBM gauges say when a net needs this; BENCH_r07
    # carries the per-device before/after bytes.
    state_sharding: str = "replicated"
    # loop
    max_rounds: int = 100
    eval_every: int = 5                 # rounds between evals (reference: 5/10)
    eval_batch: int = 1000
    # precision
    precision: str = "float32"          # or "bfloat16"
    # round-pipeline overlap & fuse (the r6 MFU levers; each individually
    # toggleable, each pinned bit-exact/parity by tests/test_round_pipeline):
    # h2d_prefetch extends the one-deep host prefetch to also PLACE round
    # R+1's batches on device (trainer.place_batches on the prefetch
    # thread) while round R computes — t_h2d_ms in the step-time breakdown
    # drops to ~0. donate_batches donates the [tau, global_batch, ...]
    # buffers to the compiled round (two-slot rotation: R donated while
    # R+1 places into fresh buffers), cutting peak HBM + allocator churn.
    # lrn_impl / pool_impl pick the kernel implementation in the layer
    # path: "auto" = the Pallas TPU kernels on TPU (XLA/fused elsewhere),
    # lrn "window" / pool "xla" = the XLA reduce_window lowerings as the
    # explicit fallback, "pallas" = force (raises where unsupported);
    # validated at OpsImpl construction, i.e. trainer build. ops_interpret
    # runs the Pallas kernels under the interpreter — CPU parity tests.
    # pool_impl defaults to "xla" (the r3 TPU A/B measured the kernel
    # losing 10% end to end); "auto" is the opt-in, re-measured by the
    # bench.py --mfu row pair — flip here once BENCH_r06's TPU rows say so.
    h2d_prefetch: bool = True
    donate_batches: bool = True
    lrn_impl: str = "auto"
    pool_impl: str = "xla"
    ops_interpret: bool = False
    # the r8 gather-free boundary levers (each pinned bit-exact by
    # tests/test_round_pipeline.py). fused_boundary peels the final τ
    # step out of the compiled scan so the boundary pmean (+ the ZeRO
    # momentum average/re-shard under the named trainer) traces in the
    # same region as the last optimizer update — on TPU the rolled
    # scan's loop boundary otherwise serializes the full-params
    # all-reduce behind every local step. collect_async moves the
    # deferred loss/health fetch onto a background collector thread so
    # the round loop NEVER blocks on boundary results: t_collect_ms in
    # the step-time breakdown reads ~0 (the off-thread fetch lands as
    # t_collect_bg_ms), log/JSONL content is unchanged and rows stay
    # round-ordered (the collector is a FIFO drained at every eval/
    # checkpoint/recovery boundary).
    fused_boundary: bool = True
    collect_async: bool = True
    # persistent XLA compile cache (utils/compile_cache.py): a directory
    # jax reuses compiled executables from ACROSS processes — replica
    # cold-start, elastic trainer_factory rebuilds after a resize, and
    # hot-swap retraces all skip recompilation when the cache is warm.
    # None = only $SPARKNET_COMPILE_CACHE / $JAX_COMPILATION_CACHE_DIR,
    # if set; compile events grow a cache_hit label either way
    # (sparknet_compile_events_total{what,cache_hit}).
    compile_cache_dir: Optional[str] = None
    # checkpoint. checkpoint_dir accepts a local path OR a gs://|s3://
    # prefix (native bucket checkpoints — no FUSE mount; utils/checkpoint
    # uploads through the data plane's HTTP clients). checkpoint_async
    # moves serialize+digest+persist to a background writer thread: the
    # round loop blocks only for the device->host state fetch, with at
    # most one snapshot in flight (the next save waits out the previous
    # write). False restores the fully synchronous save.
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 25          # rounds
    checkpoint_async: bool = True
    # SHARDED checkpoint layout (r8): each worker writes/reads only its
    # own NamedSharding shard in parallel (shard-k-of-n.npz files + a
    # manifest with per-shard digests in meta.json, still committed
    # LAST) instead of gathering the full state to one host — save time
    # O(1/n_workers), stage-1 blocking never materializes the full
    # state, and the state no longer has to fit one host's RAM on the
    # save side. Restores read BOTH layouts transparently (bit-identical
    # flat map), so sharded<->monolithic resume is exact in all
    # directions. "auto" (default): sharded for multi-device layer-IR
    # trainers, monolithic elsewhere (graph backend, single device);
    # "on" forces, "off" restores the pre-r8 monolithic fetch_global
    # path wholesale.
    checkpoint_sharded: str = "auto"
    resume: bool = True
    # training health supervisor: anomaly classification (spike/nonfinite),
    # skip / rollback-to-verified-checkpoint / LR-backoff recovery, and the
    # deterministic fault-injection hooks (utils/health.py)
    health: HealthConfig = field(default_factory=HealthConfig)
    # liveness heartbeat: when set, the loop atomically rewrites this JSON
    # file (utils/heartbeat.py schema: t/step/status/rollbacks) at the
    # log_every flush cadence — `tpu_pod_launch.sh watch` (with
    # TPU_HEARTBEAT_FILE pointed here) distinguishes "slow" (fresh beat,
    # status ok) from "sick" (stale beat, or spike/nonfinite/rollback
    # status) without parsing logs. The serve subsystem writes the same
    # schema with role="serve".
    heartbeat_path: Optional[str] = None
    heartbeat_every_s: float = 10.0
    # unified telemetry (sparknet_tpu.obs). telemetry=True builds a
    # per-run MetricsRegistry every meter/supervisor/writer registers
    # into and emits per-round step-time breakdown fields (t_data_ms /
    # t_h2d_ms / t_round_ms / t_collect_ms / t_ckpt_fetch_ms / t_log_ms)
    # in the metrics JSONL; False restores the pre-obs behavior (the
    # bench.py --obs "disabled" arm). status_port serves /metrics
    # (Prometheus text, same name schema as serve), /healthz and /status
    # from EVERY training process (since the pod PR — each worker is its
    # own scrape surface, the raw feed of pod aggregation; 0 = ephemeral,
    # and co-located processes on one host MUST use 0 or distinct ports —
    # the bound address lands on cfg.status_address). trace_out captures host-side
    # spans (round loop / prefetch / async checkpoint writer lanes) into
    # a Chrome-trace-event JSON loadable in Perfetto next to the
    # jax.profiler device trace.
    # status_host defaults to loopback (scrape via SSH tunnel / sidecar);
    # set "0.0.0.0" for a cross-host Prometheus to reach it directly.
    # status_address is OUTPUT, not input: run_loop writes the bound
    # (host, port) here once the server is up (port 0 resolves to the
    # ephemeral port) — leave it None in configs.
    telemetry: bool = True
    status_port: Optional[int] = None
    status_host: str = "127.0.0.1"
    status_address: Optional[Tuple[str, int]] = None
    # SLO ledger (obs/history.py): history=True runs the metrics-history
    # sampler in the training process — bounded multi-resolution rings
    # behind a /timeseries route on the status server, with optional
    # JSONL shard persistence under history_dir for `sparknet-slo`
    # retrospective reports. Off by default (zero overhead unless asked).
    history: bool = False
    history_dir: Optional[str] = None
    history_interval_s: float = 1.0
    trace_out: Optional[str] = None
    # pod-scope observability (obs/pod.py). pod_dir is a shared prefix —
    # local/NFS dir or a gs://|s3:// bucket — where EVERY worker rewrites
    # its own worker-<i>.heartbeat.json (step/status/loss plus round_s /
    # data_wait_s, the straggler-attribution inputs) at the heartbeat
    # cadence. pod_port makes process 0 additionally run a PodAggregator
    # endpoint over that prefix: merged pod /metrics, /pod/status JSON
    # naming stragglers and stale workers (0 = ephemeral; bound address
    # lands on pod_address — OUTPUT, leave None in configs). The
    # standalone `sparknet-podview` console reads either surface.
    pod_dir: Optional[str] = None
    pod_port: Optional[int] = None
    pod_address: Optional[Tuple[str, int]] = None
    # elastic pod membership (parallel/elastic.py): when enabled AND
    # pod_dir is set, the loop watches the per-worker heartbeats, evicts
    # dead workers (stale-then-reprobed, full jitter), adopts joiners,
    # and resizes the compiled round at the τ boundary through the
    # checkpoint store. None/disabled = the pre-elastic loop exactly.
    elastic: Optional[ElasticConfig] = None
    # logging. None -> $SPARKNET_TPU_HOME, else "." (the reference logged
    # to $SPARKNET_HOME/training_log_<ms>.txt); tests set the env var to a
    # tmp dir so stray default-config runs never litter the repo root
    workdir: Optional[str] = None
    # fetch/flush round metrics every K rounds (losses stay on device in
    # between). The loop's ONLY per-round host sync is the deferred loss
    # fetch; when rounds are shorter than the dispatch/fetch round trip
    # (very fast models, or a high-latency dev tunnel where a fetch costs
    # ~100 ms), K>1 amortizes that sync K-fold. Log content is identical,
    # just flushed in batches.
    log_every: int = 1
    seed: int = 0
    # jax.profiler capture: trace ONE steady-state round (start_round+1,
    # skipping the compile round) into this directory (SURVEY §5.1)
    profile_dir: Optional[str] = None

    @staticmethod
    def from_json(path: str) -> "RunConfig":
        with open(path) as f:
            d = json.load(f)
        return RunConfig.from_dict(d)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RunConfig":
        d = dict(d)
        if "solver" in d and isinstance(d["solver"], dict):
            d["solver"] = SolverConfig.from_dict(d["solver"])
        if "health" in d and isinstance(d["health"], dict):
            d["health"] = HealthConfig.from_dict(d["health"])
        if "elastic" in d and isinstance(d["elastic"], dict):
            d["elastic"] = ElasticConfig.from_dict(d["elastic"])
        known = {f.name for f in dataclasses.fields(RunConfig)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        return RunConfig(**d)

    def with_overrides(self, *pairs: str) -> "RunConfig":
        """Apply CLI 'key=value' overrides (JSON-parsed values)."""
        d = dataclasses.asdict(self)
        for p in pairs:
            k, _, v = p.partition("=")
            if not _:
                raise ValueError(f"override {p!r} is not key=value")
            try:
                d[k] = json.loads(v)
            except json.JSONDecodeError:
                d[k] = v
        return RunConfig.from_dict(d)
