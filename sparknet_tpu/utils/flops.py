"""Analytic FLOP counting for MFU reporting.

Counts the MXU work (convolutions + inner products — where essentially all
of a convnet's FLOPs live) from the compiled net's blob shapes. Elementwise
layers (ReLU/LRN/pool/softmax) are <1% of CaffeNet FLOPs and are excluded,
making the reported MFU slightly conservative.
"""
from __future__ import annotations

import numpy as np

from ..model.net import CompiledNet

#: peak dense bf16 TFLOP/s per chip by device_kind substring (public specs).
PEAK_BF16_TFLOPS = (
    ("v6", 918.0),   # Trillium
    ("v5p", 459.0),
    ("v5", 197.0),   # v5e / "TPU v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
)

#: fwd+bwd FLOPs as a multiple of forward FLOPs: backward computes both the
#: data gradient and the weight gradient, each a conv/matmul of forward cost.
TRAIN_FWD_MULT = 3.0


def forward_flops_per_image(net: CompiledNet) -> float:
    """Conv + inner-product forward FLOPs for ONE example (2·MACs)."""
    total = 0.0
    for layer in net.spec.layers:
        if layer.type == "Convolution":
            n, h, w, c_out = net.blob_shapes[layer.tops[0]]
            c_in = net.blob_shapes[layer.bottoms[0]][-1]
            k, g = layer.conv.kernel_size, layer.conv.group
            total += 2.0 * h * w * k * k * (c_in // g) * c_out
        elif layer.type == "InnerProduct":
            out_f = net.blob_shapes[layer.tops[0]][-1]
            in_f = int(np.prod(net.blob_shapes[layer.bottoms[0]][1:]))
            total += 2.0 * in_f * out_f
    return total


def train_flops_per_image(net: CompiledNet) -> float:
    return TRAIN_FWD_MULT * forward_flops_per_image(net)


def peak_bf16_flops(device_kind: str) -> float:
    """Peak dense bf16 FLOP/s for a device_kind string (e.g. 'TPU v5 lite');
    0.0 when unknown (callers then omit MFU rather than fabricate it)."""
    kind = device_kind.lower()
    for key, tflops in PEAK_BF16_TFLOPS:
        if key in kind:
            return tflops * 1e12
    return 0.0
