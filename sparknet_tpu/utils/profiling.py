"""jax.profiler capture hook (SURVEY §5.1 tracing/profiling subsystem).

The reference had per-phase wall timers only (`apps/CifarApp.scala` logged
driver-side elapsed times); PhaseTimers reproduces those. This adds the
device-level view the reference could not see: a TensorBoard-loadable XLA
trace (op-by-op device timeline, HBM usage) captured around a bounded window
of work. Use `RunConfig.profile_dir` to trace one mid-training round, or
`bench.py --profile DIR` to trace the benchmark's timed section.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into `trace_dir` for the with-block;
    no-op when trace_dir is falsy. View with TensorBoard's profile plugin
    (`tensorboard --logdir <trace_dir>`) or xprof."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
