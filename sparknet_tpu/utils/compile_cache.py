"""Persistent XLA compilation cache — cold-start-to-zero for serve + train.

Every replica cold-start, checkpoint hot-swap retrace, serve-bucket first
forward, and elastic `trainer_factory` rebuild pays a fresh XLA compile
today; PR 5's compile-event telemetry (`obs/device.py`) measures exactly
what that costs but nothing SAVES it. This module wires jax's persistent
compilation cache (`jax_compilation_cache_dir`) through one init point and
gives the telemetry the `cache_hit` signal:

  - `init_compile_cache(dir)` — point jax at a persistent on-disk cache
    (local path; a pod shares one via NFS or a per-host mirror). Resolves,
    in order: the explicit argument, `$SPARKNET_COMPILE_CACHE`, then
    whatever `jax_compilation_cache_dir` already holds (jax binds it to
    `$JAX_COMPILATION_CACHE_DIR` natively). The entry-size / min-compile-
    time floors are dropped to "cache everything": serve-bucket forwards
    on small nets compile in well under jax's default 1 s floor, and those
    are exactly the compiles a replica cold-start repays.

  - `track_compiles()` — a context manager counting the fresh XLA backend
    compiles and persistent-cache hits/misses that happen INSIDE the
    region, on this thread. `obs.device.timed_compile` and the serve
    bucket first-forward wrap their compile regions with it and stamp the
    verdict as the `cache_hit` label on `sparknet_compile_events_total`:
    a region that did no fresh XLA work (everything served from the
    persistent cache, or no XLA compile at all — e.g. a memoized spec
    compile) is a HIT; a region that built at least one executable from
    scratch is a MISS. "Zero cache_hit=false events on a warm replica
    cold-start" is then a scrapeable acceptance number (BENCH_ECON).

Counting rides `jax.monitoring`: jax records
`/jax/core/compile/backend_compile_duration` around every
compile-or-fetch and `/jax/compilation_cache/cache_{hits,misses}` when
the persistent cache is consulted, all ON THE COMPILING THREAD — so
thread-local counters attribute a region's compiles to the thread that
ran it (the serve lane's single-writer worker, the trainer's dispatch
thread) even while other lanes compile concurrently.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_listening = False
_cache_dir: Optional[str] = None
_tls = threading.local()


def _counts():
    c = getattr(_tls, "counts", None)
    if c is None:
        c = _tls.counts = {"xla": 0, "hit": 0, "miss": 0}
    return c


def _on_event(event: str, **kw) -> None:
    if event == _CACHE_HIT_EVENT:
        _counts()["hit"] += 1
    elif event == _CACHE_MISS_EVENT:
        _counts()["miss"] += 1


def _on_duration(event: str, duration: float, **kw) -> None:
    if event == _BACKEND_COMPILE_EVENT:
        _counts()["xla"] += 1


def ensure_listeners() -> None:
    """Register the jax.monitoring listeners once per process (idempotent,
    cheap). Called by init and by every track_compiles — compile counting
    works even when no persistent cache is configured."""
    global _listening
    with _lock:
        if _listening:
            return
        import jax.monitoring as mon
        mon.register_event_listener(_on_event)
        mon.register_event_duration_secs_listener(_on_duration)
        _listening = True


def init_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Initialize the persistent compilation cache (idempotent; safe to
    call from the train loop, the serve CLI, and tests alike). Returns
    the active cache directory, or None when no directory is configured
    anywhere — in which case only the compile-counting listeners are
    installed and every XLA-compiling region reads as a cache MISS
    (honest: there is no cache to hit)."""
    ensure_listeners()
    import jax

    global _cache_dir
    d = cache_dir or os.environ.get("SPARKNET_COMPILE_CACHE") or None
    if d is None:
        try:
            d = jax.config.jax_compilation_cache_dir  # env-bound option
        except AttributeError:
            d = None
    if not d:
        return _cache_dir
    d = os.path.abspath(os.path.expanduser(str(d)))
    with _lock:
        if _cache_dir is not None:
            # FIRST caller wins: the cache is process-global jax state,
            # and repointing it mid-flight would abandon every lane's
            # warm entries (reset_for_tests() exists for tests that
            # genuinely need a fresh dir)
            return _cache_dir
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # cache EVERYTHING: the default floors (1 s compile time, 4 KiB
        # entries) skip exactly the small serve-bucket executables whose
        # re-compilation a replica cold-start is made of
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax latches cache-off on the first compile that runs without a
        # dir configured; a server/CLI initializing AFTER model build
        # (any jax touch) would silently get no cache. reset_cache()
        # drops the latch so the next compile re-reads the config.
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception:
            pass  # older/newer jax without the hook: init-early still works
        _cache_dir = d
    return d


def cache_dir() -> Optional[str]:
    """The active persistent-cache directory (None = not initialized)."""
    return _cache_dir


def is_initialized() -> bool:
    return _cache_dir is not None


class track_compiles:
    """Context manager: counts this THREAD's fresh XLA backend compiles
    and persistent-cache hits/misses inside the region.

    After exit: `.xla_compiles`, `.cache_hits`, `.cache_misses`, and the
    verdict `.cache_hit` — True iff the region required no fresh XLA
    compilation (no backend compile at all, or every compile request was
    served from the persistent cache). With no cache configured, any XLA
    compile in the region is by definition a miss."""

    xla_compiles = 0
    cache_hits = 0
    cache_misses = 0

    def __enter__(self) -> "track_compiles":
        ensure_listeners()
        c = _counts()
        self._t0 = (c["xla"], c["hit"], c["miss"])
        return self

    def __exit__(self, *exc) -> bool:
        c = _counts()
        self.xla_compiles = c["xla"] - self._t0[0]
        self.cache_hits = c["hit"] - self._t0[1]
        self.cache_misses = c["miss"] - self._t0[2]
        return False

    @property
    def cache_hit(self) -> bool:
        if self.xla_compiles == 0:
            return True  # nothing was compiled fresh
        # fresh XLA work happened: a hit requires the persistent cache
        # to have actually been CONSULTED for it (hit/miss events fired)
        # with zero misses. `is_initialized()` alone is not enough — a
        # configured-but-latched-off cache (init after first compile on
        # a jax without the reset hook) would otherwise read as a hit
        # exactly when the cache silently failed.
        return (self.cache_misses == 0
                and self.cache_hits + self.cache_misses > 0)


def reset_for_tests() -> None:
    """Clear the active-dir latch so tests can re-init against their own
    tmp dirs (the jax config itself is process-global either way)."""
    global _cache_dir
    with _lock:
        _cache_dir = None
