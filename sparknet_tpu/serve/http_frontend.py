"""The HTTP/1.1 inference data plane: the network front door for
`sparknet_tpu.serve`.

Before this module requests entered through in-process
`InferenceServer.submit` and the HTTP layer was status-only; this is the
open-loop-measurable path — persistent connections, wire decode on the
accept threads, admission control, deadline-aware shedding. (The binary
frame transport in `binary_frontend.py` is the second wire behind the
same backends; `BackendAdapter` below is the seam both ride.)

Wire protocol (all under `/v1`):

  POST /v1/models/<name>/infer      one inference request for <name>
  POST /v1/infer                    same, for the sole/default model
    Content-Type: application/json
      {"inputs": {"<input>": <nested lists>}, "deadline_ms": <float?>}
    Content-Type: application/x-npz
      body = np.savez archive of per-example input arrays (exact-dtype
      path; deadline via the X-Deadline-Ms header)
    -> 200, JSON {"model":..., "step":..., "outputs": {...lists...}}
       (or an npz archive of output arrays when the request was npz or
       `Accept: application/x-npz`)
  GET /v1/models                    {"models": {name: vitals-row}}
  GET /healthz                      liveness (200/503)

Error codes (every shed is ANSWERED — a client never hangs):
  400  undecodable body / not a net input / wrong shape
  404  unknown model or route
  408  socket timed out mid-body-read (the stream is desynced — the
       reply closes the connection)
  413  body over the size cap
  429  queue at capacity (QueueFullError backpressure), the tenant's
       token bucket is empty (error_kind "tenant_limit" — per-tenant
       admission via the X-Tenant header, serve/admission.py), or the
       request's priority class (X-Priority: high|normal|low) is below
       the admission-pressure cutoff the fleet controller set
       (error_kind "priority" — low sheds first under SLO burn) — all
       + Retry-After
  503  request shed: client deadline expired before a forward
       (DeadlineExpiredError), no routable replica (NoReplicaError),
       response-wait timeout, or the server is at its connection cap
       (error_kind "over_capacity") — all + Retry-After
  500  anything else (the error text rides the JSON body)

Design rules carried from the serving core:
  - DECODE ON THE ACCEPT THREADS: JSON/npz parse and dtype coercion run
    on the per-connection handler thread (ThreadingHTTPServer), never on
    the forward worker — the worker's time is bucket forwards only.
  - KEEP-ALIVE: HTTP/1.1 + Content-Length on every response keeps
    connections persistent; the connection/request counters let tests
    assert reuse (10k rps is unreachable through per-request TCP+TLS
    handshakes).
  - CONNECTION HYGIENE: thread-per-connection means every idle
    keep-alive connection pins one OS thread — so idle connections are
    closed after `idle_timeout_s`, the live set is capped at
    `max_connections` (excess answered 503 + Connection: close, never
    silently refused), and `http_connections_active{transport}` gauges
    the live count.
  - ADMISSION CONTROL: QueueFullError maps to 429 with Retry-After;
    expired deadlines are rejected at the door (never enqueued) and shed
    from the queue by the batcher before they pad into a bucket;
    per-tenant token buckets (when configured) shed a hot tenant's flood
    BEFORE it occupies queue slots.

Transport-labeled metrics: this frontend and the binary one register the
SAME request/connection families with `transport="http"` /
`transport="binary"`, so one scrape compares the two wires per code.

`http_infer` at the bottom is the matching client (thread-cached
keep-alive connections, npz wire format) — the router's remote-replica
proxy and the bench's open-loop drivers both ride it.
"""
from __future__ import annotations

import http.client
import io
import itertools
import json
import socket
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np

from ..obs import reqtrace
from ..utils.logger import Logger
from .admission import (PriorityShedError, TenantAdmission,
                        TenantLimitError)
from .batcher import DeadlineExpiredError, QueueFullError
from .router import ModelRouter, NoReplicaError, UnknownModelError
from .server import (InferenceServer, encode_outputs, net_input_specs,
                     pop_outputs)

NPZ_CONTENT_TYPE = "application/x-npz"

# a tenant-limited request's body is drained (keep-alive survives the
# 429) only up to this size; past it the reply closes the connection —
# shedding must not buy the flood full-body socket reads
TENANT_SHED_DRAIN_BYTES = 64 << 10


class _BodyReadTimeout(Exception):
    """The connection's socket timed out (or died) mid-body-read. The
    stream is DESYNCED — unread body bytes would be parsed as the next
    request line — so the reply must close the connection. A dedicated
    type because socket.timeout aliases shift across Python versions
    (3.10: distinct from futures.TimeoutError; 3.11+: the same class),
    and the except-ladder must not confuse a half-read body with a
    response-wait timeout."""


def _encode_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    return buf.getvalue()


def _decode_npz(body: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


class BackendAdapter:
    """Normalizes an `InferenceServer` or a `ModelRouter` behind one
    resolve/submit/coerce surface. Both wire frontends (HTTP here, the
    binary frame transport in binary_frontend.py) ride this seam, so a
    request behaves identically whichever wire carried it."""

    def __init__(self, backend):
        self.backend = backend
        self.is_router = isinstance(backend, ModelRouter) or \
            hasattr(backend, "lanes")
        # per-model input dtype coercion table (JSON floats arrive as
        # float64; coerce on the TRANSPORT thread so the worker never
        # pays)
        self.specs: Dict[str, Dict[str, np.dtype]] = {}
        for name, lane in self.lanes().items():
            self.specs[name] = {
                k: np.dtype(dt)
                for k, (_, dt) in net_input_specs(lane.net).items()}

    def lanes(self) -> Dict[str, InferenceServer]:
        if self.is_router:
            return self.backend.lanes
        return {self.backend.model_name: self.backend}

    def model_names(self) -> Tuple[str, ...]:
        if self.is_router:
            return tuple(sorted(set(self.backend.lanes)
                                | set(self.backend.replicas)))
        return (self.backend.model_name,)

    def resolve(self, model: Optional[str]) -> str:
        """None -> the sole served model; ambiguous None raises."""
        if model is not None:
            return model
        names = self.model_names()
        if len(names) != 1:
            raise UnknownModelError(
                f"the default-model route is ambiguous: this endpoint "
                f"serves {list(names)}; name the model explicitly")
        return names[0]

    def submit(self, model: str, payload: Dict[str, np.ndarray],
               deadline_s: Optional[float],
               priority: Optional[str] = None,
               outputs: Optional[Tuple[str, ...]] = None,
               trace=None):
        if self.is_router:
            # the router's remote legs only speak tensors — fold the
            # outputs request back into the payload (the terminal
            # frontend, or a local lane's submit, pops it again)
            return self.backend.submit(
                model, encode_outputs(payload, outputs),
                deadline_s=deadline_s, priority=priority, trace=trace)
        if model != self.backend.model_name:
            raise UnknownModelError(model)
        return self.backend.submit(payload, deadline_s=deadline_s,
                                   priority=priority, outputs=outputs,
                                   trace=trace)

    def coerce(self, model: Optional[str],
               payload: Dict[str, np.ndarray]) -> None:
        """Cast inputs to the net's schema dtypes IN PLACE, on the
        calling (transport) thread."""
        names = self.model_names()
        specs = self.specs.get(
            model if model is not None
            else (names[0] if len(names) == 1 else ""), {})
        for k, dt in specs.items():
            if k in payload and payload[k].dtype != dt:
                payload[k] = payload[k].astype(dt)

    def step(self, model: str) -> Optional[int]:
        lane = self.lanes().get(model)
        return None if lane is None else lane.manager.step

    def cancel(self, model: str, fut) -> bool:
        """Best-effort cancel of a submitted request BY ITS FUTURE:
        reaches the lane batcher's queue entry if the request hasn't
        formed into a batch yet. Returns False when the future is not a
        queued batcher future (already formed, remote-proxied, or a
        router-chained wrapper) — the caller drops the cancel and the
        request completes normally."""
        lane = self.lanes().get(model)
        if lane is None:
            return False
        try:
            return bool(lane.batcher.cancel(fut))
        except Exception:
            return False

    def healthy(self) -> bool:
        return (self.backend.healthy()
                if hasattr(self.backend, "healthy") else True)


def register_transport_metrics(registry, transport: str):
    """The shared data-plane families, `transport`-labeled so HTTP and
    binary render side by side in one scrape. Returns (requests counter,
    connections counter, active-connections gauge, shed counter)."""
    c_req = registry.counter(
        "sparknet_serve_http_requests_total",
        "data-plane requests by status code and wire transport",
        labels=("code", "transport"))
    c_conn = registry.counter(
        "sparknet_serve_http_connections_total",
        "data-plane connections accepted (requests/connections >> 1 "
        "means keep-alive/pipelining reuse is working)",
        labels=("transport",))
    g_active = registry.gauge(
        "sparknet_serve_http_connections_active",
        "currently-open data-plane connections", labels=("transport",))
    c_shed = registry.counter(
        "sparknet_serve_shed_total",
        "requests shed before a forward, by reason (deadline = "
        "client deadline expired before batch formation)",
        labels=("model", "reason"))
    return c_req, c_conn, g_active, c_shed


class HttpFrontend:
    """HTTP/1.1 inference endpoint over an InferenceServer or a
    ModelRouter (the `backend`). Port 0 binds ephemeral; the bound
    address is `.address`."""

    transport = "http"

    def __init__(self, backend, port: int = 0, host: str = "127.0.0.1",
                 default_deadline_s: Optional[float] = None,
                 retry_after_s: float = 1.0,
                 max_body_bytes: int = 64 << 20,
                 idle_timeout_s: float = 60.0,
                 max_connections: int = 256,
                 tenants: Optional[TenantAdmission] = None,
                 logger: Optional[Logger] = None,
                 journal: Optional[Logger] = None):
        self.backend = backend
        self.adapter = BackendAdapter(backend)
        self.is_router = self.adapter.is_router
        self.default_deadline_s = default_deadline_s
        # request journal (ROADMAP 5a): one JSONL row per decoded
        # request — arrival shape, not outcome — for trace replay
        self.journal = journal
        self.retry_after_s = float(retry_after_s)
        self.max_body_bytes = int(max_body_bytes)
        self.idle_timeout_s = float(idle_timeout_s)
        self.max_connections = int(max_connections)
        self.tenants = tenants
        self.log = logger
        self.registry = backend.registry
        self._c_http, self._c_conns, self._g_active, self._c_shed = \
            register_transport_metrics(self.registry, self.transport)
        self.connections = 0
        self.rejected_over_cap = 0
        self.requests = 0
        # journal correlation ids (trace_id pairs with request_id so the
        # replay lab can key rows even for untraced requests)
        self._rids = itertools.count(1)
        self._active = 0
        self._active_lock = threading.Lock()
        self._g_active.set_fn(lambda: self._active,
                              transport=self.transport)
        owner = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # the per-connection socket timeout: an idle keep-alive
            # connection times out its blocking readline, which
            # handle_one_request treats as close_connection — the
            # pinned thread is released instead of held forever by an
            # idle-connection flood
            timeout = owner.idle_timeout_s

            def setup(self):  # one Handler instance == one connection
                super().setup()
                owner.connections += 1
                owner._c_conns.inc(transport=owner.transport)
                with owner._active_lock:
                    owner._active += 1
                    self._over_cap = owner._active > owner.max_connections

            def finish(self):
                with owner._active_lock:
                    owner._active -= 1
                super().finish()

            def do_POST(self):  # noqa: N802 (stdlib casing)
                owner._handle_post(self)

            def do_GET(self):  # noqa: N802
                owner._handle_get(self)

            def log_message(self, *a):  # data plane: no per-request logs
                pass

        self._http = ThreadingHTTPServer((host, port), Handler)
        self._http.daemon_threads = True
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        name="serve-http", daemon=True)
        self._thread.start()
        if logger is not None:
            logger.log(f"serve: HTTP data plane at "
                       f"http://{self.address[0]}:{self.address[1]}/v1")

    # -- backend normalization (adapter passthroughs) ------------------------

    def _lanes(self) -> Dict[str, InferenceServer]:
        return self.adapter.lanes()

    def _model_names(self) -> Tuple[str, ...]:
        return self.adapter.model_names()

    def _submit(self, model: Optional[str],
                payload: Dict[str, np.ndarray],
                deadline_s: Optional[float],
                priority: Optional[str] = None,
                outputs: Optional[Tuple[str, ...]] = None,
                trace=None):
        model = self.adapter.resolve(model)
        return model, self.adapter.submit(model, payload, deadline_s,
                                          priority=priority,
                                          outputs=outputs, trace=trace)

    def _step(self, model: str) -> Optional[int]:
        return self.adapter.step(model)

    # -- request handling (accept threads) -----------------------------------

    def _read_body(self, h, length: int) -> bytes:
        """Read the request body on the accept thread; a socket timeout
        (or death) mid-read leaves the keep-alive stream desynced, so it
        surfaces as the typed _BodyReadTimeout whose reply closes."""
        try:
            return h.rfile.read(length)
        except (socket.timeout, OSError) as e:
            raise _BodyReadTimeout(str(e)) from e

    def _reject_over_cap(self, h, drain_len: int = 0) -> None:
        """503 + Connection: close for a connection accepted past the
        cap — answered through the normal reply path AFTER draining the
        request body (replying before the client finishes sending would
        RST the socket and destroy the answer in flight). Answered, not
        refused: the client learns WHY and backs off; `close=True`
        releases the pinned thread immediately after."""
        if 0 <= drain_len <= self.max_body_bytes:
            try:
                h.rfile.read(drain_len)
            except (socket.timeout, OSError):
                pass  # the reply below closes either way
        self.rejected_over_cap += 1
        self._reply(h, 503, {"error": "server at connection capacity",
                             "error_kind": "over_capacity"},
                    retry_after=True, close=True)

    def _handle_post(self, h: BaseHTTPRequestHandler) -> None:
        self.requests += 1
        t0 = time.perf_counter()
        # distributed trace: accept the client's X-Trace-Id (parsed even
        # when this process is not tracing, so the journal correlates);
        # this front door MINTS a context only when tracing is on and
        # none arrived. The record finishes in _reply_bytes — the one
        # funnel every terminal path (200, typed shed, 500) flows through.
        rt = reqtrace.active()
        ctx = rec = None
        ts_hdr = h.headers.get("X-Trace-Id")
        if ts_hdr:
            ctx = reqtrace.parse_context(ts_hdr)
        if rt is not None:
            if ctx is None:
                ctx = rt.mint()
            rec = rt.begin(ctx, transport="http")
            h._spkn_rec = rec
        try:
            if getattr(h, "_over_cap", False):
                try:
                    drain = int(h.headers.get("Content-Length") or 0)
                except ValueError:
                    drain = 0
                self._reject_over_cap(h, drain)
                return
            model = self._route_model(h.path)
            if model is NOT_AN_INFER_ROUTE:
                self._reply(h, 404, {"error": f"no route {h.path!r}",
                                     "error_kind": "not_found"})
                return
            try:
                length = int(h.headers.get("Content-Length") or -1)
            except ValueError:
                length = -1
            if length < 0:
                # no (or unparsable) Content-Length: any body the client
                # sent (e.g. chunked) is still in the socket and would
                # desync the keep-alive stream — close this connection
                self._reply(h, 411, {"error": "Content-Length required",
                                     "error_kind": "bad_request"},
                            close=True)
                return
            if length > self.max_body_bytes:
                # the body must still be drained for keep-alive to
                # survive; over the cap we close instead
                self._reply(h, 413, {"error": "body too large",
                                     "error_kind": "bad_request"},
                            close=True)
                return
            reason = (self.tenants.admit(h.headers.get("X-Tenant"),
                                         h.headers.get("X-Priority"))
                      if self.tenants is not None else None)
            if rec is not None:
                rt.stage(ctx, "admission", rec["ts"],
                         rt.now_us() - rec["ts"])
            if reason is not None:
                # shed the flood before DECODING or touching a queue
                # slot ("tenant_limit" = this tenant's bucket is empty;
                # "priority" = the fleet controller tightened the door
                # and this class is below the cutoff). A small body is
                # drained so keep-alive survives the 429; past the
                # threshold we close instead — a tenant flooding huge
                # bodies must not buy full-body socket reads on pinned
                # accept threads either
                drain = length <= TENANT_SHED_DRAIN_BYTES
                if drain:
                    self._read_body(h, length)
                # label with the model the CLIENT named; a default-route
                # request belongs to "" (blaming the alphabetically
                # first model would misattribute tenant floods)
                self._c_shed.inc(model=model or "", reason=reason)
                self._reply(h, 429, {
                    "error": ("tenant rate limit exceeded"
                              if reason == "tenant_limit" else
                              "shed by priority class under admission "
                              "pressure"),
                    "error_kind": reason}, retry_after=True,
                    close=not drain)
                return
            body = self._read_body(h, length)
            ctype = (h.headers.get("Content-Type") or "").split(";")[0]
            want_npz = ctype == NPZ_CONTENT_TYPE or \
                NPZ_CONTENT_TYPE in (h.headers.get("Accept") or "")
            t_dec = rt.now_us() if rec is not None else 0.0
            payload, deadline_ms, outputs = self._decode(
                model, body, ctype, h)
            if rec is not None:
                rt.stage(ctx, "decode", t_dec, rt.now_us() - t_dec)
            deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                          else self.default_deadline_s)
            if self.journal is not None:
                try:
                    self.journal.metrics(
                        0, kind="request", transport="http",
                        model=model or "",
                        tenant=h.headers.get("X-Tenant") or "",
                        priority=h.headers.get("X-Priority") or "",
                        deadline_ms=deadline_ms,
                        request_id=next(self._rids),
                        trace_id=ctx.trace_id if ctx else None,
                        sizes={k: int(np.asarray(v).nbytes)
                               for k, v in payload.items()})
                except Exception:
                    pass  # the journal must never fail the data plane
            model, fut = self._submit(
                model, payload, deadline_s,
                priority=h.headers.get("X-Priority"), outputs=outputs,
                trace=ctx)
            if rec is not None:
                rec["model"] = model or ""
            # shed-not-hang: the batcher fails the future at the deadline
            # (DeadlineExpiredError); without one we still bound the wait
            wait_s = deadline_s + 5.0 if deadline_s is not None else 30.0
            out = fut.result(timeout=wait_s)
            # time-in-queue before forward start, stamped on the future
            # at batch formation — lets a client split its observed
            # latency into queueing vs compute
            qw = getattr(fut, "_spkn_queue_wait_s", None)
            qw_hdr = ({} if qw is None
                      else {"X-Queue-Wait-Ms": f"{qw * 1e3:.3f}"})
            if want_npz:
                step = self._step(model)
                self._reply_bytes(h, 200, _encode_npz(out),
                                  NPZ_CONTENT_TYPE,
                                  extra={"X-Model": model,
                                         "X-Model-Step":
                                         str(-1 if step is None
                                             else step), **qw_hdr})
            else:
                self._reply(h, 200, {
                    "model": model, "step": self._step(model),
                    "latency_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3),
                    "outputs": {k: np.asarray(v).tolist()
                                for k, v in out.items()}},
                    extra=qw_hdr)
        except _BodyReadTimeout:
            # half-read body: the stream is desynced — answer AND close
            self._reply(h, 408, {"error": "timed out reading the "
                                 "request body",
                                 "error_kind": "request_timeout"},
                        close=True)
        except UnknownModelError as e:
            self._reply(h, 404, {"error": str(e),
                                 "error_kind": "unknown_model"})
        except PriorityShedError as e:
            self._reply(h, 429, {"error": str(e),
                                 "error_kind": "priority"},
                        retry_after=True)
        except TenantLimitError as e:
            self._reply(h, 429, {"error": str(e),
                                 "error_kind": "tenant_limit"},
                        retry_after=True)
        except QueueFullError as e:
            self._reply(h, 429, {"error": str(e),
                                 "error_kind": "queue_full"},
                        retry_after=True)
        except DeadlineExpiredError as e:
            self._reply(h, 503, {"error": str(e),
                                 "error_kind": "deadline"},
                        retry_after=True)
        except NoReplicaError as e:
            self._reply(h, 503, {"error": str(e),
                                 "error_kind": "no_replica"},
                        retry_after=True)
        except FutureTimeoutError:
            self._reply(h, 503, {"error": "response wait timed out",
                                 "error_kind": "timeout"},
                        retry_after=True)
        except (ValueError, KeyError, TypeError) as e:
            self._reply(h, 400, {"error": str(e),
                                 "error_kind": "bad_request"})
        except Exception as e:  # the data plane must answer, not die
            self._reply(h, 500, {"error": f"{type(e).__name__}: {e}",
                                 "error_kind": "internal"})

    def _handle_get(self, h: BaseHTTPRequestHandler) -> None:
        try:
            if getattr(h, "_over_cap", False):
                self._reject_over_cap(h)
                return
            if h.path.startswith("/v1/models"):
                rows = {name: lane.model_row()
                        for name, lane in self._lanes().items()}
                for name in self._model_names():
                    rows.setdefault(name, {"remote_only": True})
                self._reply(h, 200, {"models": rows})
            elif h.path.startswith("/healthz"):
                ok = self.adapter.healthy()
                self._reply(h, 200 if ok else 503,
                            {"status": "ok" if ok else "unhealthy"})
            else:
                self._reply(h, 404, {"error": f"no route {h.path!r}",
                                     "error_kind": "not_found"})
        except Exception as e:
            self._reply(h, 500, {"error": str(e),
                                 "error_kind": "internal"})

    def _route_model(self, path: str):
        """'/v1/infer' -> None (default model); '/v1/models/<m>/infer'
        (or ':infer') -> '<m>'; anything else -> NOT_AN_INFER_ROUTE."""
        path = urlsplit(path).path
        if path == "/v1/infer":
            return None
        for sep in ("/infer", ":infer"):
            if path.startswith("/v1/models/") and path.endswith(sep):
                name = path[len("/v1/models/"):-len(sep)]
                if name and "/" not in name:
                    return name
        return NOT_AN_INFER_ROUTE

    def _decode(self, model: Optional[str], body: bytes, ctype: str,
                h: BaseHTTPRequestHandler
                ) -> Tuple[Dict[str, np.ndarray], Optional[float],
                           Optional[Tuple[str, ...]]]:
        """Wire -> per-example arrays, ON THIS (accept) THREAD. Returns
        (payload, deadline_ms, requested output blob names)."""
        hdr_deadline = h.headers.get("X-Deadline-Ms")
        deadline_ms = float(hdr_deadline) if hdr_deadline else None
        outputs: Optional[Tuple[str, ...]] = None
        if ctype in (NPZ_CONTENT_TYPE, "application/octet-stream"):
            # npz carries the outputs request as the reserved tensor key
            payload, outputs = pop_outputs(_decode_npz(body))
        else:
            d = json.loads(body)
            if not isinstance(d, dict) or \
                    not isinstance(d.get("inputs"), dict):
                raise ValueError(
                    'JSON body must be {"inputs": {<name>: array}, '
                    '"deadline_ms"?: number, "outputs"?: [names]}')
            if d.get("deadline_ms") is not None:
                deadline_ms = float(d["deadline_ms"])
            if d.get("outputs"):
                outputs = tuple(str(o) for o in d["outputs"])
            payload = {str(k): np.asarray(v)
                       for k, v in d["inputs"].items()}
        # dtype coercion per the net's input schema (JSON numbers land
        # float64/int64; the worker-side stack would cast anyway, but
        # HERE the cast runs on the accept thread)
        self.adapter.coerce(model, payload)
        return payload, deadline_ms, outputs

    # -- replies -------------------------------------------------------------

    def _reply(self, h, code: int, obj: Dict[str, Any],
               retry_after: bool = False, close: bool = False,
               extra: Optional[Dict[str, str]] = None) -> None:
        if getattr(h, "_spkn_rec", None) is not None:
            h._spkn_outcome = ("ok" if code == 200
                               else str(obj.get("error_kind") or code))
        self._reply_bytes(h, code, json.dumps(obj).encode(),
                          "application/json", retry_after=retry_after,
                          close=close, extra=extra)

    def _reply_bytes(self, h, code: int, data: bytes, ctype: str,
                     retry_after: bool = False, close: bool = False,
                     extra: Optional[Dict[str, str]] = None) -> None:
        # close this request's trace record (every POST outcome funnels
        # here) and echo the trace id so a client can go from a slow
        # response to `sparknet-trace` without guessing
        rec = getattr(h, "_spkn_rec", None)
        if rec is not None:
            h._spkn_rec = None
            rt = reqtrace.active()
            if rt is not None:
                extra = {**(extra or {}),
                         "X-Trace-Id": rec["ctx"].encoded()}
                rt.finish(rec, getattr(h, "_spkn_outcome", None)
                          or ("ok" if code == 200 else "error"))
        self._c_http.inc(code=str(code), transport=self.transport)
        try:
            h.send_response(code)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(data)))
            if extra:
                for k, v in extra.items():
                    h.send_header(k, v)
            if retry_after:
                # RFC 9110 delta-seconds (integer); sub-second backpressure
                # still says "1" — the body's error_kind carries the why
                h.send_header("Retry-After",
                              str(max(1, round(self.retry_after_s))))
            if close:
                h.send_header("Connection", "close")
                h.close_connection = True
            h.end_headers()
            h.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up first; nothing to answer

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._http.server_address[:2]

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()


class _NotAnInferRoute:
    pass


NOT_AN_INFER_ROUTE = _NotAnInferRoute()


# ---------------------------------------------------------------------------
# the matching client
# ---------------------------------------------------------------------------

_conn_cache = threading.local()
MAX_CACHED_CONNECTIONS = 8  # per thread; LRU-evicted past this


def lru_cache_get(tl: threading.local, attr: str, key, factory,
                  max_cached: int):
    """Thread-local keep-alive object cache with LRU bounding (dict
    insertion order is the LRU order; re-insertion moves to the tail).
    Shared by http_infer's connection cache and binary_infer's client
    cache — ONE copy of the cache-hygiene rules. Evictees get
    `.close()`d, exceptions swallowed (a dying socket must not fail the
    request that merely aged it out)."""
    cache = getattr(tl, attr, None)
    if cache is None:
        cache = {}
        setattr(tl, attr, cache)
    obj = cache.pop(key, None)
    if obj is None:
        obj = factory()
    cache[key] = obj
    while len(cache) > max_cached:
        oldest = next(k for k in cache if k != key)
        old = cache.pop(oldest)
        try:
            old.close()
        except Exception:
            pass
    return obj


def lru_cache_drop(tl: threading.local, attr: str, key) -> None:
    """Evict + close one cached object (ANY-transport-error hygiene:
    never re-use a stream in an unknown state)."""
    obj = getattr(tl, attr, {}).pop(key, None)
    if obj is not None:
        try:
            obj.close()
        except Exception:
            pass


def _connection(host: str, port: int, timeout: float):
    """Thread-cached keep-alive HTTPConnection (one per (host, port) per
    thread — the open-loop bench and the router's proxy both need
    connection reuse to mean anything). LRU-BOUNDED: a client sweeping
    many replicas must not accumulate one socket per address it ever
    touched."""
    conn = lru_cache_get(
        _conn_cache, "conns", (host, port),
        lambda: http.client.HTTPConnection(host, port, timeout=timeout),
        MAX_CACHED_CONNECTIONS)
    conn.timeout = timeout
    return conn


def _drop_connection(host: str, port: int) -> None:
    lru_cache_drop(_conn_cache, "conns", (host, port))


def http_infer(base_url: str, model: str,
               payload: Dict[str, np.ndarray],
               deadline_s: Optional[float] = None,
               timeout: float = 30.0,
               tenant: Optional[str] = None,
               priority: Optional[str] = None,
               outputs: Optional[Tuple[str, ...]] = None,
               trace=None) -> Dict[str, np.ndarray]:
    """POST one inference request (npz wire format, keep-alive) and
    return the output arrays. Maps the frontend's shed codes back to the
    serve exceptions, so a remote replica behaves like a local lane.

    Cache hygiene: ANY error between request and full response read —
    transport or otherwise — evicts this (host, port)'s thread-cached
    connection. A half-read reply left on a cached socket would desync
    every later request on it; better a fresh TCP handshake than a
    poisoned stream."""
    u = urlsplit(base_url if "//" in base_url else f"http://{base_url}")
    host, port = u.hostname, u.port or 80
    path = f"{u.path.rstrip('/')}/v1/models/{model}/infer"
    headers = {"Content-Type": NPZ_CONTENT_TYPE,
               "Accept": NPZ_CONTENT_TYPE}
    if deadline_s is not None:
        headers["X-Deadline-Ms"] = f"{deadline_s * 1e3:.3f}"
    if tenant is not None:
        headers["X-Tenant"] = tenant
    if priority is not None:
        headers["X-Priority"] = priority
    ctx = reqtrace.parse_context(trace) if trace is not None else None
    rt = reqtrace.active() if ctx is not None else None
    if ctx is not None:
        headers["X-Trace-Id"] = ctx.encoded()
    body = _encode_npz(encode_outputs(payload, outputs))
    t_wire = rt.now_us() if rt is not None else 0.0
    try:
        for attempt in (0, 1):
            conn = _connection(host, port, timeout)
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()  # full read keeps the conn reusable
                break
            except socket.timeout:
                _drop_connection(host, port)
                raise  # a slow server is not a stale socket: no retry
            except (ConnectionError, http.client.HTTPException,
                    OSError) as e:
                # a server-closed cached connection surfaces here: retry
                # once on a fresh socket, then give up loudly
                _drop_connection(host, port)
                if attempt:
                    raise ConnectionError(
                        f"http_infer to {base_url}: {e}") from e
            except BaseException:
                # ANY other failure mid-exchange (decode error raised by
                # a lower layer, KeyboardInterrupt, ...) leaves the
                # socket in an unknown read state: never re-use it
                _drop_connection(host, port)
                raise
        if resp.status == 200:
            try:
                return _decode_npz(data)
            except Exception:
                # the reply was fully read, but undecodable — the stream
                # itself may be desynced; drop it before raising
                _drop_connection(host, port)
                raise
        try:
            err = json.loads(data)
        except Exception:
            err = {"error": data[:200].decode("utf-8", "replace")}
        kind, msg = err.get("error_kind"), err.get("error", "")
        if resp.status == 429 and kind == "tenant_limit":
            raise TenantLimitError(msg)
        if resp.status == 429 and kind == "priority":
            raise PriorityShedError(msg)
        if resp.status == 429:
            raise QueueFullError(msg)
        if resp.status == 503 and kind == "deadline":
            raise DeadlineExpiredError(msg)
        if resp.status == 503:
            raise NoReplicaError(msg or f"replica shed ({kind})")
        if resp.status == 404:
            raise UnknownModelError(msg or model)
        raise RuntimeError(f"http_infer: {resp.status} {msg}")
    finally:
        # the client-side wire span brackets the whole exchange; the
        # assembler subtracts the matched server record to expose pure
        # network + clock-offset time on this hop
        if rt is not None:
            rt.stage(ctx, "wire:http", t_wire, rt.now_us() - t_wire,
                     kind="client")
