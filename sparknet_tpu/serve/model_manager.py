"""Serving-side weight lifecycle: load, watch, verify, hot-swap, rollback.

The manager owns the NetInterface's weights while the server owns its
traffic. It watches a `checkpoint_dir` — the SAME store layer training
writes through (`utils/checkpoint.py`: a local path or a gs://|s3://
prefix), so a pod training into a bucket and a serving fleet reading from
it need no extra copy step — and hot-swaps weights between batches:

  - a new step is loaded through `restore_flat(step=...)`, which
    re-verifies every digest (per-array for monolithic saves, per-shard
    for the r8 SHARD-MANIFEST layout training writes by default — the
    loader reassembles the exact flat map, so hot-swap is layout-blind
    and the parallel per-worker checkpoint files serve as-is): a torn
    upload or a byte flipped at rest is REJECTED
    (`CheckpointCorruptError`) and the server keeps answering from the
    current weights; the bad step goes on a cooldown so the poll loop
    doesn't re-download a corrupt 244 MB snapshot every 2 seconds.
  - the swap itself happens on the server's worker thread between
    batches, so queued requests never race a half-installed weight set.
  - after installing, an optional CANARY forward runs (zeros batch at
    the smallest bucket): nonfinite outputs roll the swap back to the
    previous weights — digests prove the bytes, the canary proves the
    bytes still run (e.g. a checkpoint from a diverged run that saved
    legal-but-poisoned values).
  - transient store trouble (an outage mid-poll) is logged and retried;
    it must degrade freshness, never availability.

Weight-swap events reuse the training heartbeat schema
(`utils/heartbeat.py`, role="serve"): step = served checkpoint step,
rollbacks = rejected/rolled-back swaps, so the same probe that watches a
training pod watches a serving process.
"""
from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..model.quant import QuantConfig, quantize_params
from ..obs import trace as obs_trace
from ..utils import checkpoint as ckpt
from ..utils.heartbeat import HeartbeatWriter
from ..utils.logger import Logger


class ServeModelError(RuntimeError):
    """A checkpoint cannot be served (missing or mis-shaped leaves that no
    known layout — bare params, replica-axis TrainState, TP column shards,
    logical NamedSharding state — explains)."""


def params_from_checkpoint_flat(flat: Dict[str, np.ndarray],
                                template: Dict[str, Dict[str, Any]],
                                tp: int = 1
                                ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Training-checkpoint flat keys -> a JaxNet params pytree.

    Accepts every layout the store holds: a full replica-axis TrainState
    (`params/<layer>/<param>` with the shard_map trainer's leading
    [n_devices] axis — post-round replicas are identical, shard 0 is THE
    value), the NamedSharding trainer's logical layout (full weights, no
    leading axis), and a bare params tree (`<layer>/<param>`, e.g. a
    checkpoint of JaxNet.params). Momentum/it keys are ignored: serving
    wants weights, not optimizer state.

    `tp` (from checkpoint `extra["tp"]`): a replica-axis TENSOR-PARALLEL
    checkpoint stores each column-sharded layer as per-device shards
    (device d = data d//tp, model d%tp — rows 0..tp-1 are data group 0's
    model ranks); such leaves are reassembled by concatenating the tp
    shards along the column dim (w: 1, b: 0). The NamedSharding trainer's
    TP checkpoints are already full logical weights, so they need no
    reassembly. Missing or shape-mismatched leaves fail loudly with the
    leaf path."""
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for lname, lp in template.items():
        out[lname] = {}
        for pname, leaf in lp.items():
            arr = None
            for key in (f"params/{lname}/{pname}", f"{lname}/{pname}"):
                if key in flat:
                    arr = np.asarray(flat[key])
                    break
            if arr is None:
                raise ServeModelError(
                    f"checkpoint has no weights for {lname}/{pname}")
            want = tuple(np.shape(leaf))
            if tuple(arr.shape) != want:
                if arr.ndim == len(want) + 1 and \
                        tuple(arr.shape[1:]) == want:
                    arr = arr[0]  # leading replica axis, replicated leaf
                elif tp > 1 and arr.ndim == len(want) + 1 \
                        and arr.shape[0] >= tp:
                    # replica-axis TP column shards: data group 0's model
                    # ranks are rows 0..tp-1; the column dim is the one
                    # whose concat restores the template shape
                    axis = 1 if pname == "w" and len(want) > 1 else 0
                    cand = np.concatenate([arr[j] for j in range(tp)],
                                          axis=axis)
                    if tuple(cand.shape) != want:
                        raise ServeModelError(
                            f"{lname}/{pname}: tp={tp} shards "
                            f"{arr.shape} do not reassemble to net "
                            f"{want}")
                    arr = cand
                else:
                    raise ServeModelError(
                        f"{lname}/{pname}: checkpoint shape {arr.shape} "
                        f"!= net {want}")
            # device-put ONCE here: leaving numpy in net.params would
            # re-transfer the full weight set host->device on every
            # forward (the jit argument path)
            out[lname][pname] = jnp.asarray(arr)
    return out


class ModelManager:
    """Owns weight load / watch / swap for one net (see module doc)."""

    def __init__(self, net, checkpoint_dir: Optional[str] = None,
                 poll_interval_s: float = 2.0,
                 canary_batch: Optional[Dict[str, np.ndarray]] = None,
                 canary_outputs: Optional[tuple] = None,
                 logger: Optional[Logger] = None,
                 heartbeat: Optional[HeartbeatWriter] = None,
                 bad_step_retry_s: float = 30.0, registry=None,
                 model: str = "default",
                 quant: Optional[QuantConfig] = None,
                 parity_batch: Optional[Dict[str, np.ndarray]] = None,
                 replica: str = "local", poll_jitter: float = 0.0,
                 rollout_gate: Optional[str] = None):
        if checkpoint_dir and not hasattr(net, "params"):
            raise ServeModelError(
                "checkpoint hot-reload needs a layer-IR JaxNet (exposes "
                ".params); serve a graph net from a weights file instead")
        if quant is not None and not (hasattr(net, "params")
                                      and hasattr(net, "set_quant")):
            raise ServeModelError(
                "quantized serving needs a layer-IR JaxNet (exposes "
                ".params/.set_quant); the graph backend serves f32")
        #: weight-only quantization at load time (model/quant.py). Every
        #: install — initial weights included — quantizes the f32 params
        #: and gates on the PARITY canary: the quantized forward of
        #: `parity_batch` must allclose the f32 forward within the
        #: calibrated tolerance, else the swap rolls back. A checkpoint
        #: whose quantization is poisoned (corrupted scale) never serves.
        self.quant = quant
        self.parity_batch = parity_batch
        self.last_parity_drift: Optional[float] = None
        self.net = net
        # the f32 SHAPE template for checkpoint extraction: once quant
        # installs a (w_q, w_scale) pytree, net.params no longer carries
        # the f32 "w" shapes a checkpoint must reassemble to — shape
        # structs cost nothing and outlive every swap
        self._f32_template = None
        if quant is not None:
            import jax
            self._f32_template = {
                lname: {pname: jax.ShapeDtypeStruct(tuple(np.shape(w)),
                                                    jnp.float32)
                        for pname, w in lp.items()}
                for lname, lp in net.params.items()}
        self.checkpoint_dir = checkpoint_dir
        self.poll_interval_s = float(poll_interval_s)
        self.canary_batch = canary_batch
        self.canary_outputs = canary_outputs
        self.log = logger
        self.heartbeat = heartbeat
        self.bad_step_retry_s = float(bad_step_retry_s)
        self.model = str(model)
        #: fleet identity — the key this replica looks itself up under in
        #: a rollout gate's approval map, and the `replica` label on the
        #: freshness gauges (provider tag for subprocess replicas,
        #: "local" for an in-process lane)
        self.replica = str(replica)
        #: ± fraction of poll_interval_s each poll's NEXT deadline is
        #: jittered by (per-instance RNG): N replicas watching one bucket
        #: must not list it in lockstep on every commit (thundering herd)
        self.poll_jitter = float(poll_jitter)
        if not 0.0 <= self.poll_jitter < 1.0:
            raise ValueError(f"poll_jitter must be in [0, 1), "
                             f"got {poll_jitter}")
        #: optional ROLLOUT.json gate path (local or gs://|s3://): when
        #: present and readable, this replica only adopts the step the
        #: fleet rollout duty approved FOR IT (fleet/rollout.py writes
        #: it); missing gate = ungated independent polling (back-compat)
        self.rollout_gate = rollout_gate
        self._rng = random.Random()
        self.step: Optional[int] = None   # served checkpoint step
        #: wall-clock commit instant (meta.json commit_ts) of the SERVING
        #: step — freshness_s = now - this. None until a stamped
        #: checkpoint installs (initial weights / pre-r12 checkpoints).
        self.commit_ts: Optional[float] = None
        #: newest COMMITTED step the poll loop has seen in the store —
        #: step lag = latest_seen - step (how far behind this replica is)
        self.latest_seen: Optional[int] = None
        self.swaps = 0                    # successful hot swaps
        self.swap_failures = 0            # rejected or rolled-back swaps
        self.last_error: Optional[str] = None
        #: monotonic time of the last REJECTED/rolled-back swap — the
        #: router's hot-swap cooldown signal (route new load away from a
        #: replica that just refused a checkpoint while it settles)
        self.last_reject_t: float = 0.0
        self._next_poll = 0.0
        self._bad: Dict[int, float] = {}  # step -> retry-not-before time
        # shared-schema telemetry (obs.MetricsRegistry): swap outcomes and
        # the step answering traffic right now (model label: router lanes
        # share one registry)
        self._c_swaps = None
        if registry is not None:
            self._c_swaps = registry.counter(
                "sparknet_serve_swaps_total",
                "weight-swap attempts by outcome",
                labels=("model", "outcome"))
            registry.gauge(
                "sparknet_serve_model_step",
                "checkpoint step currently serving (-1 = initial weights)",
                labels=("model",)
            ).set_fn(lambda: -1 if self.step is None else self.step,
                     model=self.model)
            registry.gauge(
                "sparknet_serve_model_freshness_seconds",
                "now - commit_ts of the serving step (-1 = no stamped "
                "checkpoint installed)",
                labels=("model", "replica")
            ).set_fn(lambda: (-1.0 if (f := self.freshness_s()) is None
                              else f),
                     model=self.model, replica=self.replica)
            registry.gauge(
                "sparknet_serve_model_step_lag",
                "newest committed step minus the serving step (-1 = "
                "unknown)",
                labels=("model", "replica")
            ).set_fn(lambda: (-1 if (lag := self.step_lag()) is None
                              else lag),
                     model=self.model, replica=self.replica)

    # -- lifecycle -----------------------------------------------------------

    def load_initial(self) -> Optional[int]:
        """Serve the newest VERIFIED checkpoint if the watched dir has one
        (fresh-init weights otherwise — a server may come up before its
        trainer's first save). Returns the loaded step or None. With
        quant enabled the serving weights are ALWAYS quantized — the
        initial weights too, so the compiled forwards and pad buffers
        never flip representation under traffic."""
        if not self.checkpoint_dir:
            self._quantize_initial()
            return None
        found = ckpt.restore_newest_verified(self.checkpoint_dir)
        if found is None:
            self._log("serve: no verified checkpoint under "
                      f"{self.checkpoint_dir!r} yet — serving initial "
                      f"weights")
            self._quantize_initial()
            return None
        flat, step, extra = found
        if not self._install(flat, step, extra, initial=True):
            # the newest verified checkpoint failed the install gates:
            # keep serving (quantized) initial weights; the poll loop
            # retries newer steps as they land
            self._quantize_initial()
        return self.step

    def _quantize_initial(self) -> None:
        """Quantize the fresh-init weights in place (quant mode only).
        Failing the parity gate HERE is a configuration error — there is
        no earlier good state to serve — so it raises instead of
        degrading."""
        if self.quant is None or getattr(self.net, "quant", None) is not None:
            return
        ok, why = self._quant_swap(self.net.params)
        if not ok:
            raise ServeModelError(
                f"initial weights failed the quantization parity gate: "
                f"{why} — check QuantConfig tolerances")

    def poll(self, now: Optional[float] = None) -> bool:
        """Time-gated reload check (the server calls this every idle tick
        and between batches; actual store traffic happens at most once per
        poll_interval_s, de-synchronized across replicas by poll_jitter).
        Returns True when a swap was installed."""
        if not self.checkpoint_dir:
            return False
        now = time.monotonic() if now is None else now
        if now < self._next_poll:
            return False
        self._schedule_next_poll(now)
        try:
            latest = ckpt.latest_step(self.checkpoint_dir)
        except Exception as e:
            # store outage: freshness degrades, serving does not — and a
            # transient listing error is STORE trouble, never a reason to
            # cool any step down
            self._store_error(f"poll: {e}", now=now)
            return False
        if latest is not None:
            self.latest_seen = latest
        target = latest
        if self.rollout_gate:
            held, want = self._gate_target()
            if held:
                return False  # gated: no step approved for this replica
            if want is not None:
                target = want  # may be < self.step: rollback swap-down
        if target is None or target == self.step:
            return False
        if now < self._bad.get(target, 0.0):
            return False  # known-bad step, still cooling down
        return self._try_swap(target)

    def _schedule_next_poll(self, now: float) -> None:
        j = self.poll_jitter
        scale = 1.0 + self._rng.uniform(-j, j) if j > 0.0 else 1.0
        self._next_poll = now + self.poll_interval_s * scale

    def _store_error(self, msg: str, now: Optional[float] = None) -> None:
        """Transient store trouble (outage, timeout, auth blip): count it
        under its own outcome, retry after FULL-jitter backoff — every
        replica that saw the same blip re-polls at an independent uniform
        offset instead of stampeding the store together — and never
        corrupt-step-cooldown anything (the step is probably fine)."""
        now = time.monotonic() if now is None else now
        self.last_error = msg
        if self._c_swaps is not None:
            self._c_swaps.inc(model=self.model, outcome="store_error")
        self._next_poll = now + self._rng.uniform(0.0,
                                                  self.poll_interval_s)
        self._log(f"serve: transient store error ({msg}); retrying with "
                  f"jittered backoff")

    def _gate_target(self) -> tuple:
        """(held, step) under the rollout gate: held=True means the gate
        exists but approves nothing for this replica (hold the current
        weights); step is the approved target otherwise. A missing or
        unreadable gate degrades to ungated independent polling."""
        from ..fleet.rollout import read_gate
        gate = read_gate(self.rollout_gate)
        if not gate:
            return False, None
        # per-replica approval wins; "all" is the completed-rollout (or
        # post-halt fallback) step open to EVERY replica, including ones
        # grown after the rollout finished
        want = gate.get("approved", {}).get(self.replica, gate.get("all"))
        if want is None:
            return True, None
        want = int(want)
        if want in set(int(d) for d in gate.get("denied", ())):
            return True, None  # approval raced a deny; hold
        return False, want

    # -- swap machinery ------------------------------------------------------

    def _try_swap(self, step: int) -> bool:
        # the span puts the whole fetch+verify+install+canary on the
        # serve worker's trace lane — the gap where no batch can run
        with obs_trace.span("hot_swap", step=step):
            try:
                # full integrity path: every digest is recomputed over the
                # fetched bytes (restore IS the verification — one read)
                flat, got, extra = ckpt.restore_flat(self.checkpoint_dir,
                                                     step=step)
            except ckpt.CheckpointVanishedError as e:
                # the step disappeared between listing and fetch
                # (retention pruned it while a slow rollout still had it
                # approved): NOT a rejection — raising swap_failures here
                # would read as "this replica refused the checkpoint" and
                # halt a fleet rollout over a step that is simply gone.
                # The next poll re-targets whatever is newest.
                self.last_error = f"step {step}: vanished ({e})"
                if self._c_swaps is not None:
                    self._c_swaps.inc(model=self.model, outcome="vanished")
                self._log(f"serve: checkpoint step {step} vanished before "
                          f"fetch — continuing on step {self.step}")
                return False
            except ckpt.CheckpointCorruptError as e:
                self._reject(step, f"corrupt: {e}")
                return False
            except Exception as e:
                # NOT corruption: the loader propagates store trouble
                # (ConnectionError, timeouts, non-404 HTTP) distinctly, so
                # this step must not be cooled down — it will load fine
                # once the store answers again
                self._store_error(f"load step {step}: {e}")
                return False
            return self._install(flat, got, extra)

    def _install(self, flat: Dict[str, np.ndarray], step: int,
                 extra: Dict[str, Any], initial: bool = False) -> bool:
        old_params = self.net.params
        old_quant = getattr(self.net, "quant", None)
        try:
            # tp>1 checkpoints serve fine since r7: replica-axis column
            # shards reassemble inside params_from_checkpoint_flat, and
            # the NamedSharding trainer's TP checkpoints are already full
            # logical weights — the canary still vets the result. Quant
            # mode extracts against the retained f32 shape template (the
            # live params may be a quantized pytree).
            f32_params = params_from_checkpoint_flat(
                flat, self._f32_template or self.net.params,
                tp=int(extra.get("tp", 1)))
        except ServeModelError as e:
            self._reject(step, str(e))
            return False
        if self.quant is not None:
            ok, why = self._quant_swap(f32_params)
            if not ok:
                # a quantization that fails parity NEVER serves: roll
                # back to the (quantized) weights answering traffic now
                self.net.params = old_params
                self.net.set_quant(old_quant)
                self._reject(step, f"quantization rejected: {why} — "
                                   f"swap rolled back")
                return False
        else:
            self.net.params = f32_params
        try:
            canary_ok = self._canary_ok()
        except Exception as e:
            # a canary that CRASHES (not just goes nonfinite) must also
            # roll back — leaving unvetted weights installed because the
            # vet itself failed would be strictly worse than a clean no
            canary_ok = False
            self._log(f"serve: canary forward raised: {e}")
        if not canary_ok:
            # digests matched but the forward is poisoned (a checkpoint
            # saved mid-divergence): roll back to the weights that were
            # answering traffic a moment ago
            self.net.params = old_params
            if self.quant is not None:
                self.net.set_quant(old_quant)
            self._reject(step, "canary forward failed (nonfinite "
                               "outputs or crash) — swap rolled back")
            return False
        self.step = step
        ts = extra.get("commit_ts")
        self.commit_ts = float(ts) if ts is not None else None
        if self.latest_seen is None or step > self.latest_seen:
            self.latest_seen = step
        if not initial:
            self.swaps += 1
        if self._c_swaps is not None:
            self._c_swaps.inc(model=self.model,
                              outcome="initial" if initial else "ok")
        self.last_error = None
        self._log(f"serve: weights {'loaded' if initial else 'hot-swapped'}"
                  f" from checkpoint step {step}")
        self._beat(step, "ok")
        return True

    def _quant_swap(self, f32_params) -> tuple:
        """Quantize + parity-gate + install (quant mode's install tail).
        Runs the f32 forward of `parity_batch` as the reference, installs
        the quantized pytree, and compares the quantized forward against
        it: every output blob must be finite and allclose within the
        calibrated QuantConfig tolerance. Returns (ok, why); on ok the
        net holds the quantized params. The caller owns rollback."""
        net = self.net
        try:
            net.params = f32_params
            net.set_quant(None)
            ref = net.forward(self.parity_batch,
                              blob_names=list(self.canary_outputs or ())) \
                if self.parity_batch is not None else {}
            qparams = quantize_params(f32_params, self.quant)
            net.params = qparams
            net.set_quant(self.quant)
            if self.parity_batch is None:
                return True, None
            out = net.forward(self.parity_batch,
                              blob_names=list(self.canary_outputs or ()))
        except Exception as e:
            return False, f"quantized forward raised: {e}"
        drift = 0.0
        # compare the PER-ROW blobs clients actually consume (prob,
        # features). Batch-aggregate scalars (the zoo heads' loss/
        # accuracy over the parity batch's zero labels) are label-
        # dependent and DISCONTINUOUS — an argmax flip on a near-tie
        # moves accuracy by 1/batch, which is noise, not corruption —
        # and the server's de-pad drops them from responses anyway.
        keys = [k for k in ref if np.ndim(ref[k]) >= 1] or list(ref)
        for k in keys:
            q = out.get(k)
            if q is None:
                return False, f"quantized forward lost blob {k!r}"
            r = np.asarray(ref[k], dtype=np.float32)
            q = np.asarray(q, dtype=np.float32)
            if not np.isfinite(q).all():
                return False, f"nonfinite quantized outputs in {k!r}"
            if r.size:
                drift = max(drift, float(np.max(np.abs(q - r))))
            if not np.allclose(q, r, rtol=self.quant.rtol,
                               atol=self.quant.atol):
                return False, (
                    f"parity drift vs f32 forward in {k!r}: max "
                    f"{np.max(np.abs(q - r)):.4g} exceeds rtol="
                    f"{self.quant.rtol}/atol={self.quant.atol}")
        self.last_parity_drift = drift
        return True, None

    def _canary_ok(self) -> bool:
        if self.canary_batch is None:
            return True
        out = self.net.forward(self.canary_batch,
                               blob_names=list(self.canary_outputs or ()))
        return all(np.isfinite(np.asarray(v, dtype=np.float32)).all()
                   for v in out.values())

    # -- freshness -----------------------------------------------------------

    def freshness_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds between the serving step's COMMIT (meta.json
        commit_ts, wall clock) and now — the train->serve staleness this
        replica is answering traffic at. None until a stamped checkpoint
        installs (initial weights, pre-r12 checkpoints)."""
        if self.commit_ts is None:
            return None
        now = time.time() if now is None else now
        return round(max(0.0, now - self.commit_ts), 3)

    def step_lag(self) -> Optional[int]:
        """Newest committed step seen in the store minus the serving
        step (0 = fully fresh); None before the first poll/install."""
        if self.latest_seen is None or self.step is None:
            return None
        return max(0, int(self.latest_seen) - int(self.step))

    def swap_cooldown_active(self, cooldown_s: float) -> bool:
        """True within `cooldown_s` of the last rejected/rolled-back
        swap — the replica still answers, but a router should prefer
        its peers while the bad-checkpoint dust settles."""
        return (self.last_reject_t > 0.0 and
                time.monotonic() - self.last_reject_t < cooldown_s)

    def _reject(self, step: int, why: str) -> None:
        self.swap_failures += 1
        self.last_reject_t = time.monotonic()
        if self._c_swaps is not None:
            self._c_swaps.inc(model=self.model, outcome="rejected")
        self.last_error = f"step {step}: {why}"
        self._bad[step] = time.monotonic() + self.bad_step_retry_s
        self._log(f"serve: REJECTED checkpoint step {step}: {why} — "
                  f"continuing on step {self.step}")
        self._beat(self.step or 0, "degraded")

    def _beat(self, step: int, status: str) -> None:
        if self.heartbeat is None:
            return
        try:
            self.heartbeat.beat(step, status=status,
                                rollbacks=self.swap_failures, force=True,
                                swaps=self.swaps)
        except OSError as e:  # observability must not take serving down
            self._log(f"serve: heartbeat write failed: {e}")

    def _log(self, msg: str) -> None:
        if self.log is not None:
            self.log.log(msg)
