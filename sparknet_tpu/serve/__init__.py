"""`sparknet_tpu.serve` — online inference over trained checkpoints.

The training side of this framework ends where SparkNet's did: a
checkpoint. This package is the serving side — the Clipper-style
(Crankshaw et al., NSDI 2017) adaptive-batching layer that turns those
checkpoints into a servable artifact:

  - `DynamicBatcher` (batcher.py): thread-safe request queue + the
    max-batch / max-wait-deadline batching policy, futures per request.
  - `ModelManager` (model_manager.py): NetInterface lifecycle — initial
    load from zoo / prototxt / imported graph, checkpoint_dir watching
    (local, gs://, s3://), digest-verified hot swap between batches with
    canary + rollback.
  - `InferenceServer` (server.py): the serving loop — bucket-padded jit
    forwards, de-padding, metrics (queue depth, batch fill, latency
    quantiles, img/s), /healthz-style HTTP status, heartbeat.
  - `sparknet-serve` (app.py): the console entry point.
"""
from .batcher import DynamicBatcher, QueueFullError, ServeRequest
from .model_manager import ModelManager, ServeModelError
from .server import InferenceServer, ServeConfig, zeros_batch

__all__ = [
    "DynamicBatcher", "QueueFullError", "ServeRequest",
    "ModelManager", "ServeModelError",
    "InferenceServer", "ServeConfig", "zeros_batch",
]
