"""`sparknet_tpu.serve` — online inference over trained checkpoints.

The training side of this framework ends where SparkNet's did: a
checkpoint. This package is the serving side — the Clipper-style
(Crankshaw et al., NSDI 2017) adaptive-batching layer that turns those
checkpoints into a servable artifact, plus the network data plane that
makes it reachable:

  - `DynamicBatcher` (batcher.py): thread-safe request queue + the
    max-batch / max-wait-deadline batching policy, wake-on-submit
    (no polling quantum), deadline-aware shedding, futures per request.
  - `ModelManager` (model_manager.py): NetInterface lifecycle — initial
    load from zoo / prototxt / imported graph, checkpoint_dir watching
    (local, gs://, s3://), digest-verified hot swap between batches with
    canary + rollback.
  - `InferenceServer` (server.py): the serving loop — bucket-padded jit
    forwards with pre-sized pad buffers, de-padding, metrics (queue
    depth, batch fill, latency quantiles, img/s — all `model`-labeled),
    /healthz-style HTTP status, heartbeat. Runs its own worker thread,
    or as a LANE under the router's shared pool.
  - `ModelRouter` (router.py): multi-model serving — one ModelManager +
    forward lane per model over a shared worker pool, per-model
    buckets/SLOs/metric labels, health-aware replica routing (drain on
    stale heartbeat / hot-swap cooldown, zero dropped in-flight).
  - `HttpFrontend` (http_frontend.py): the HTTP/1.1 inference endpoint —
    keep-alive with idle-timeout + connection-cap hygiene, JSON/npz
    decode on the accept threads, 429/503 + Retry-After admission
    control and deadline shedding; `http_infer` is the matching
    keep-alive client.
  - `BinaryFrontend` (binary_frontend.py + wire.py): the binary data
    plane — a `selectors` event loop (no thread-per-connection) speaking
    length-prefixed tensor frames (zero-parse `np.frombuffer` decode),
    request pipelining, and flag-gated chunked response streaming;
    `BinaryClient` / `binary_infer` are the matching clients.
  - `TenantAdmission` / `PriorityAdmission` (admission.py): per-tenant
    token buckets ahead of the 429 path on both frontends (X-Tenant
    header / frame tenant field) — one hot tenant cannot starve the
    rest; the priority-aware door adds request priority classes
    (X-Priority / frame priority field), weighted tenant budgets, and
    pressure-driven tightening — the fleet controller's fast lever
    (`sparknet_tpu.fleet`).
  - `sparknet-serve` (app.py): the console entry point.
"""
from ..model.quant import QuantConfig
from .admission import (PRIORITIES, PriorityAdmission, PriorityShedError,
                        TenantAdmission, TenantLimitError,
                        parse_priority)
from .batcher import (DeadlineExpiredError, DynamicBatcher,
                      QueueFullError, RequestCancelledError,
                      ServeRequest)
from .binary_frontend import BinaryClient, BinaryFrontend, binary_infer
from .buckets import derive_buckets, fill_ratio, size_hist_from_jsonl
from .http_frontend import BackendAdapter, HttpFrontend, http_infer
from .model_manager import ModelManager, ServeModelError
from .router import (ModelRouter, NoReplicaError, Replica, RouterConfig,
                     UnknownModelError, heartbeat_fill, heartbeat_health)
from .server import (OUTPUTS_KEY, InferenceServer, ServeConfig,
                     encode_outputs, parity_batch, pop_outputs,
                     zeros_batch)
from .wire import WireError

__all__ = [
    "DynamicBatcher", "QueueFullError", "DeadlineExpiredError",
    "RequestCancelledError", "ServeRequest",
    "ModelManager", "ServeModelError",
    "InferenceServer", "ServeConfig", "zeros_batch", "parity_batch",
    "OUTPUTS_KEY", "encode_outputs", "pop_outputs",
    "QuantConfig", "derive_buckets", "fill_ratio", "size_hist_from_jsonl",
    "ModelRouter", "RouterConfig", "Replica", "NoReplicaError",
    "UnknownModelError", "heartbeat_health", "heartbeat_fill",
    "HttpFrontend", "http_infer", "BackendAdapter",
    "BinaryFrontend", "BinaryClient", "binary_infer", "WireError",
    "TenantAdmission", "TenantLimitError",
    "PriorityAdmission", "PriorityShedError", "PRIORITIES",
    "parse_priority",
]
