"""`sparknet-serve` — the console entry point for the inference server.

Builds a net (zoo name, .prototxt path, or an imported serialized graph —
the same three model sources the training apps accept), optionally loads a
weights file, starts the dynamic-batching server with checkpoint
hot-reload, and serves until interrupted. `--http-port` additionally opens
the HTTP/1.1 inference data plane (`serve/http_frontend.py` wire format);
`--models` switches to MULTI-MODEL mode — a `ModelRouter` serving several
zoo/prototxt models over one shared worker pool, each hot-reloading its
own checkpoint dir. `--demo N` instead self-drives N synthetic requests
through the full submit->batch->forward->depad path and prints the status
JSON — the zero-infrastructure smoke ("does this model serve?") and what
the tests exercise.

`--autoscale` (with `--models`) additionally runs the fleet control
plane (`sparknet_tpu.fleet`): SLO-burn-driven admission pressure plus
replica grow/retire through the subprocess provider.

Examples:
    sparknet-serve --model lenet --checkpoint-dir gs://bkt/run1/ck \
        --outputs prob --max-batch 32 --max-wait-ms 5 --http-port 8000 \
        --status-port 8080
    sparknet-serve --models mnist=lenet,cifar=cifar10_quick \
        --router-workers 4 --http-port 8000 --demo 16
    sparknet-serve --models mnist=lenet --binary-port 9000 \
        --slo-p99-ms 50 --autoscale --fleet-max 4 --tenant-rate 100
    sparknet-serve --model net.prototxt --weights w.caffemodel \
        --crop 227 --demo 64
    sparknet-serve --graph model.pb --weights w.npz --outputs fc7 --demo 8
"""
from __future__ import annotations

import argparse
import contextlib
import json
import sys
import time
from typing import Optional

import numpy as np

from ..net_api import JaxNet
from ..utils.config import RunConfig
from ..utils.logger import Logger, default_logger
from .http_frontend import HttpFrontend
from .router import ModelRouter, RouterConfig
from .server import InferenceServer, ServeConfig, net_input_specs


def build_net(model: Optional[str], graph: Optional[str],
              weights: Optional[str], max_batch: int, n_classes: int,
              crop: Optional[int]):
    """The three model sources behind one NetInterface (mirrors
    featurizer_app's split; zoo/prototxt resolution reuses the training
    loop's resolver so the two cannot drift)."""
    if graph:
        from ..backend import GraphNet
        from ..apps.graph_common import load_graph
        net = GraphNet(load_graph(graph, None))
        if weights:
            from ..model.weights import WeightCollection
            net.set_weights(WeightCollection.load(weights))
        return net
    from ..apps.train_loop import resolve_spec
    cfg = RunConfig(model=model or "lenet", local_batch=max_batch,
                    n_classes=n_classes, crop=crop)
    net = JaxNet(resolve_spec(cfg))
    if weights:
        net.load_weights(weights)
    return net


def _demo_payload(net, seed: int = 0) -> dict:
    r = np.random.default_rng(seed)
    specs = net_input_specs(net)
    name, (shape, dtype) = next(
        (k, v) for k, v in specs.items()
        if np.issubdtype(np.dtype(v[1]), np.floating))
    return {name: r.standard_normal(shape).astype(dtype)}


def run_demo(server: InferenceServer, n: int, seed: int = 0) -> dict:
    """Drive n synthetic requests (random pixels in the net's own input
    schema) through the live server and return its status dict."""
    futures = [server.submit(_demo_payload(server.net, seed + i))
               for i in range(n)]
    for f in futures:
        f.result(timeout=60.0)
    return server.status()


def run_router_demo(router: ModelRouter, n: int, seed: int = 0) -> dict:
    """The multi-model smoke: n synthetic requests round-robined across
    every local lane, then the router status."""
    names = sorted(router.lanes)
    futures = [router.submit(
        names[i % len(names)],
        _demo_payload(router.lanes[names[i % len(names)]].net, seed + i))
        for i in range(n)]
    for f in futures:
        f.result(timeout=60.0)
    return router.status()


def parse_models_arg(spec: str):
    """--models 'name=zoo_or_prototxt[,name=...]' -> [(name, source)]."""
    out = []
    for part in spec.split(","):
        name, sep, src = part.partition("=")
        if not sep or not name or not src:
            raise SystemExit(f"--models entry {part!r} is not "
                             f"name=model_source")
        out.append((name.strip(), src.strip()))
    return out


def parse_weights_arg(spec: Optional[str]) -> dict:
    """--tenant-weights 'tenant=weight[,...]' -> {tenant: float}."""
    out = {}
    for part in (spec or "").split(","):
        if not part:
            continue
        name, sep, w = part.partition("=")
        try:
            out[name.strip()] = float(w)
        except ValueError:
            sep = ""
        if not sep or not name:
            raise SystemExit(f"--tenant-weights entry {part!r} is not "
                             f"tenant=weight")
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="lenet",
                   help="zoo builder name or .prototxt path")
    p.add_argument("--model-name", default="default",
                   help="serving name for --model (metric label + "
                   "/v1/models/<name>/infer route)")
    p.add_argument("--models", default=None, metavar="N=SRC[,N=SRC...]",
                   help="multi-model mode: comma-separated name=source "
                   "pairs served by one ModelRouter over a shared pool "
                   "(sources are zoo names / .prototxt paths)")
    p.add_argument("--router-workers", type=int, default=2,
                   help="shared pool threads in --models mode")
    p.add_argument("--graph", help="serialized graph (.pb/.json) instead "
                   "of --model")
    p.add_argument("--weights", help="initial weights (.npz/.caffemodel)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="watch this train-checkpoint dir (local or "
                   "gs://|s3://) and hot-swap verified new steps. In "
                   "--models mode: a template with {model} substituted, "
                   "e.g. gs://bkt/runs/{model}/ck")
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="seconds between checkpoint-dir polls")
    p.add_argument("--poll-jitter", type=float, default=0.1,
                   help="± fraction of --poll-interval each poll "
                   "deadline is jittered by (de-synchronizes a fleet of "
                   "replicas watching one bucket; default 0.1)")
    p.add_argument("--replica-name", default="local",
                   help="fleet identity: the rollout-gate key and the "
                   "replica label on freshness gauges (providers pass "
                   "their tag)")
    p.add_argument("--rollout-gate", default=None, metavar="PATH",
                   help="obey the fleet rollout duty's ROLLOUT.json at "
                   "this path (local or gs://|s3://): only adopt "
                   "checkpoint steps approved for --replica-name. In "
                   "--models mode: a {model} template. Missing gate = "
                   "ungated polling")
    p.add_argument("--n-classes", type=int, default=10)
    p.add_argument("--crop", type=int, default=None)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="advisory per-model p99 objective (stamped into "
                   "/status and BENCH_SERVE rows); with --history it "
                   "becomes a LIVE latency SLO the burn-rate alerter "
                   "pages on")
    p.add_argument("--slo-availability", type=float, default=None,
                   metavar="FRAC",
                   help="availability objective (e.g. 0.999) evaluated "
                   "by the burn-rate alerter (needs --history)")
    p.add_argument("--history", action="store_true",
                   help="run the SLO ledger: metrics-history sampler "
                   "(multi-resolution rings, /timeseries route) and — "
                   "when an objective is declared — the burn-rate "
                   "alerter (/slo/status, fleet page escalation)")
    p.add_argument("--history-dir", default=None, metavar="DIR",
                   help="persist append-only history shards here "
                   "(`sparknet-slo DIR` builds retrospective reports)")
    p.add_argument("--buckets", default=None,
                   help="comma-separated batch buckets (default: powers "
                   "of 2 up to max-batch)")
    p.add_argument("--buckets-from", default=None, metavar="JSONL",
                   nargs="+",
                   help="derive the bucket ladder from recorded serve "
                   "metrics JSONL(s) (batch_size_hist rows) instead of "
                   "pow2: the ladder minimizing padded slots for the "
                   "traffic the files observed (per model name when the "
                   "rows carry one)")
    p.add_argument("--buckets-k", type=int, default=4,
                   help="max rungs for --buckets-from ladders (compiled "
                   "forwards per model; default 4)")
    p.add_argument("--quant", default=None, choices=("int8",),
                   help="weight-only quantized serving: int8 per-channel "
                   "weights + bf16 activations, parity-gated against the "
                   "f32 forward at every checkpoint load")
    p.add_argument("--quant-tol", type=float, default=None,
                   help="override the quant parity tolerance (sets both "
                   "rtol and atol of the load-time allclose gate)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compile-cache directory "
                   "(default: $SPARKNET_COMPILE_CACHE / "
                   "$JAX_COMPILATION_CACHE_DIR if set) — warm replica "
                   "cold-starts skip every bucket compile")
    p.add_argument("--outputs", default=None,
                   help="comma-separated blob names to return "
                   "(default: the net's output schema)")
    p.add_argument("--no-canary", action="store_true",
                   help="skip the nonfinite canary forward on hot swaps")
    p.add_argument("--http-port", type=int, default=None,
                   help="serve the HTTP/1.1 inference data plane "
                   "(/v1/infer, /v1/models/<m>/infer) on this port "
                   "(0 = ephemeral)")
    p.add_argument("--http-host", default="127.0.0.1",
                   help='bind host for --http-port ("0.0.0.0" for '
                   "cross-host clients)")
    p.add_argument("--binary-port", type=int, default=None,
                   help="serve the binary frame data plane (length-"
                   "prefixed tensor frames over a selectors event loop; "
                   "serve/wire.py format) on this port (0 = ephemeral)")
    p.add_argument("--binary-host", default="127.0.0.1",
                   help='bind host for --binary-port ("0.0.0.0" for '
                   "cross-host clients)")
    p.add_argument("--no-shm", action="store_true",
                   help="disable the spkn-shm shared-memory transport "
                   "on the binary frontend (same-host peers then send "
                   "tensor payloads inline over the socket)")
    p.add_argument("--request-journal", default=None, metavar="PATH",
                   help="journal every data-plane request as JSONL "
                   "(ts, model, tenant, priority, tensor sizes, "
                   "deadline_ms, transport) — the raw material for "
                   "trace-replay benchmarks; off by default")
    p.add_argument("--hedge", action="store_true",
                   help="hedge slow requests (--models router only): "
                   "after an adaptive delay (the model's live routed-"
                   "latency quantile) re-issue an in-flight request to "
                   "a second healthy replica; first answer wins, the "
                   "loser is cancelled best-effort")
    p.add_argument("--hedge-budget", type=float, default=0.05,
                   help="max fraction of routed requests that may "
                   "hedge (default 0.05); hedging also disables "
                   "itself under admission pressure")
    p.add_argument("--coalesce", action="store_true",
                   help="coalesced batch formation (--models router "
                   "only): when every replica of a model reports "
                   "under-filled batches, focus consecutive requests "
                   "on ONE replica per formation window (rotating for "
                   "fairness) so batches actually fill")
    p.add_argument("--io-threads", type=int, default=2,
                   help="event-loop io threads for --binary-port")
    p.add_argument("--tenant-rate", type=float, default=None,
                   help="per-tenant admission: token-bucket refill rate "
                   "(requests/sec) keyed on the X-Tenant header / "
                   "binary-frame tenant field, shed 429 "
                   "error_kind=tenant_limit ahead of the queue; shared "
                   "across both data planes (default: off)")
    p.add_argument("--tenant-burst", type=float, default=None,
                   help="per-tenant bucket depth for --tenant-rate "
                   "(default: 2x the rate)")
    p.add_argument("--tenant-weights", default=None,
                   metavar="T=W[,T=W...]",
                   help="per-tenant budget weights for --tenant-rate "
                   "(scales that tenant's rate AND burst; unnamed "
                   "tenants get weight 1)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the fleet control plane (sparknet_tpu."
                   "fleet): per-model SLO burn (windowed p99 vs "
                   "--slo-p99-ms) + queue/shed pressure drive admission "
                   "tightening (low priority sheds first), replica "
                   "grow/retire through the subprocess provider, and "
                   "shared-pool resizing. Requires --models (the "
                   "controller acts on a ModelRouter)")
    p.add_argument("--fleet-min", type=int, default=1,
                   help="min replicas per model for --autoscale "
                   "(local lane included; default 1)")
    p.add_argument("--fleet-max", type=int, default=4,
                   help="max replicas per model for --autoscale "
                   "(default 4)")
    p.add_argument("--fleet-interval", type=float, default=1.0,
                   help="control-loop cadence seconds (default 1.0)")
    p.add_argument("--fleet-window", type=float, default=30.0,
                   help="sliding window seconds for the SLO-burn p99 "
                   "(default 30)")
    p.add_argument("--fleet-provider", default="subprocess",
                   choices=("subprocess", "none"),
                   help="where grown replicas come from: 'subprocess' "
                   "spawns sparknet-serve children over spkn:// on "
                   "this host; 'none' keeps only the admission + pool "
                   "levers")
    p.add_argument("--pool-max", type=int, default=None,
                   help="with --autoscale: let the controller grow the "
                   "router's shared worker pool up to this many "
                   "threads (default: --router-workers, i.e. lever "
                   "off)")
    p.add_argument("--heartbeat-every", type=float, default=10.0,
                   help="seconds between heartbeat writes for "
                   "--heartbeat (fleet children beat fast so the "
                   "staleness rule sees a kill promptly)")
    p.add_argument("--status-port", type=int, default=None,
                   help="serve /healthz and /metrics on this port "
                   "(0 = ephemeral)")
    p.add_argument("--heartbeat", default=None,
                   help="write the utils/heartbeat.py liveness file here")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="capture host-side spans (serve worker lane: "
                   "forwards, hot swaps) as Chrome-trace-event JSON — "
                   "merges on one timeline with a trainer's --trace-out")
    p.add_argument("--request-trace", default=None, metavar="DIR",
                   help="distributed per-REQUEST tracing: capture "
                   "tail-sampled request spans (admission, queue, batch "
                   "formation, forward, wire hops) as JSONL shards in "
                   "DIR; every shed/error and everything beyond the "
                   "live p95 is kept. Assemble shards from all "
                   "processes with `sparknet-trace DIR ...`")
    p.add_argument("--trace-head-sample", type=float, default=0.01,
                   metavar="P",
                   help="with --request-trace: ALSO head-sample this "
                   "fraction of ordinary requests (default 0.01) so "
                   "healthy-path traces exist to compare tails against")
    p.add_argument("--workdir", default=None,
                   help="log/JSONL directory (default $SPARKNET_TPU_HOME)")
    p.add_argument("--demo", type=int, default=None, metavar="N",
                   help="self-drive N synthetic requests, print status "
                   "JSON, exit (smoke mode)")
    args = p.parse_args(argv)

    log = default_logger(args.workdir, name="serving")
    buckets = (tuple(int(b) for b in args.buckets.split(","))
               if args.buckets else None)
    derived: dict = {}
    if args.buckets_from:
        from .buckets import derive_buckets, size_hist_from_jsonl
        hists = size_hist_from_jsonl(args.buckets_from)
        merged: dict = {}
        for h in hists.values():
            for s, n in h.items():
                merged[s] = merged.get(s, 0) + n
        derived = {name: derive_buckets(h, args.max_batch,
                                        k=args.buckets_k)
                   for name, h in hists.items()}
        derived[None] = derive_buckets(merged, args.max_batch,
                                       k=args.buckets_k)
        log.log(f"bucket ladders derived from "
                f"{len(args.buckets_from)} JSONL(s): "
                + "; ".join(f"{n or 'merged'}={list(b)}"
                            for n, b in sorted(
                                derived.items(),
                                key=lambda kv: str(kv[0]))))
    outputs = tuple(args.outputs.split(",")) if args.outputs else None
    if args.quant_tol is not None and not args.quant:
        p.error("--quant-tol requires --quant (no parity gate exists "
                "on the f32 path)")
    quant = args.quant
    if quant and args.quant_tol is not None:
        from ..model.quant import QuantConfig
        quant = QuantConfig(mode=args.quant, rtol=args.quant_tol,
                            atol=args.quant_tol)

    def lane_cfg(name: str, checkpoint_dir: Optional[str]) -> ServeConfig:
        # explicit --buckets wins; then the model's derived ladder, then
        # the merged-traffic ladder, then pow2
        lane_buckets = buckets or derived.get(name) or derived.get(None)
        gate = (args.rollout_gate.replace("{model}", name)
                if args.rollout_gate else None)
        return ServeConfig(
            model_name=name, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms, buckets=lane_buckets,
            slo_p99_ms=args.slo_p99_ms,
            slo_availability=args.slo_availability, outputs=outputs,
            checkpoint_dir=checkpoint_dir,
            poll_interval_s=args.poll_interval,
            poll_jitter=args.poll_jitter,
            replica_name=args.replica_name, rollout_gate=gate,
            canary=not args.no_canary, quant=quant,
            compile_cache_dir=args.compile_cache)

    from ..obs import trace as obs_trace

    if args.autoscale and not args.models:
        p.error("--autoscale requires --models (the fleet controller "
                "acts on a ModelRouter)")
    if args.tenant_weights and not args.tenant_rate:
        p.error("--tenant-weights requires --tenant-rate (weights "
                "scale the per-tenant budget)")

    # ONE admission door shared by both data planes (a tenant's budget
    # is a property of the tenant, not of the wire it arrived on) AND
    # by the fleet controller (its fast lever sets the pressure). The
    # priority-aware door runs whenever tenant budgets or the
    # controller ask for it.
    tenants = None
    if args.tenant_rate or args.autoscale:
        from .admission import PriorityAdmission
        tenants = PriorityAdmission(
            args.tenant_rate, args.tenant_burst,
            weights=parse_weights_arg(args.tenant_weights))

    # request journal (off by default): one JSONL row per data-plane
    # request, shared by both frontends — echo off, this is a data file
    journal = (Logger(jsonl_path=args.request_journal, echo=False)
               if args.request_journal else None)

    def make_frontends(backend):
        """The data planes the flags asked for: HTTP and/or binary."""
        from .binary_frontend import BinaryFrontend
        fes = []
        if args.http_port is not None:
            fes.append(HttpFrontend(backend, args.http_port,
                                    args.http_host, tenants=tenants,
                                    logger=log, journal=journal))
        if args.binary_port is not None:
            fes.append(BinaryFrontend(backend, args.binary_port,
                                      args.binary_host,
                                      io_threads=args.io_threads,
                                      tenants=tenants, logger=log,
                                      enable_shm=not args.no_shm,
                                      journal=journal))
        return fes

    def make_fleet(router, sources):
        """The --autoscale control plane over the router."""
        from ..fleet import (FleetConfig, FleetController,
                             SubprocessReplicaProvider)
        provider = None
        if args.fleet_provider == "subprocess":
            # grown children join the continuous-learning loop: same
            # checkpoint store + rollout gate as the local lanes, each
            # under its own provider tag (--replica-name)
            provider = SubprocessReplicaProvider(
                dict(sources), max_batch=args.max_batch,
                outputs=outputs or ("prob",),
                compile_cache_dir=args.compile_cache,
                checkpoint_dir=args.checkpoint_dir,
                poll_interval_s=args.poll_interval,
                poll_jitter=args.poll_jitter,
                rollout_gate=args.rollout_gate)
        cfg = FleetConfig(interval_s=args.fleet_interval,
                          window_s=args.fleet_window,
                          min_replicas=args.fleet_min,
                          max_replicas=args.fleet_max,
                          pool_max=args.pool_max,
                          slo_p99_ms=args.slo_p99_ms)
        return FleetController(router, provider=provider, cfg=cfg,
                               admission=tenants, logger=log)

    with contextlib.ExitStack() as _traces:
        if args.trace_out:
            _traces.enter_context(obs_trace.tracing(args.trace_out))
        if args.request_trace:
            from ..obs import reqtrace
            _traces.enter_context(reqtrace.request_tracing(
                args.request_trace,
                head_sample=args.trace_head_sample))
        if args.models:
            router = ModelRouter(
                RouterConfig(workers=args.router_workers,
                             status_port=args.status_port,
                             heartbeat_path=args.heartbeat,
                             heartbeat_every_s=args.heartbeat_every,
                             hedge=args.hedge,
                             hedge_budget=args.hedge_budget,
                             coalesce=args.coalesce,
                             history=args.history,
                             history_dir=args.history_dir),
                logger=log)
            if tenants is not None:
                # hedging reads the admission door's pressure: a
                # saturated fleet must not pay for duplicate requests
                router.attach_admission(tenants)
            sources = parse_models_arg(args.models)
            for name, src in sources:
                ck = (args.checkpoint_dir.format(model=name)
                      if args.checkpoint_dir else None)
                router.add_model(
                    name,
                    build_net(src, None, None, args.max_batch,
                              args.n_classes, args.crop),
                    cfg=lane_cfg(name, ck))
            fleet = make_fleet(router, sources) if args.autoscale \
                else None
            with router:
                frontends = make_frontends(router)
                if fleet is not None:
                    if router.alerter is not None:
                        # the ledger's firing pages become the fleet's
                        # fast admission-pressure input
                        fleet.attach_alerter(router.alerter)
                    fleet.start()
                try:
                    _serve_until_done(router.status, args, log,
                                      run_fn=lambda:
                                      run_router_demo(router, args.demo))
                finally:
                    if fleet is not None:
                        fleet.stop()
                    for fe in frontends:
                        fe.stop()
            return

        net = build_net(args.model, args.graph, args.weights,
                        args.max_batch, args.n_classes, args.crop)
        cfg = lane_cfg(args.model_name, args.checkpoint_dir)
        cfg.status_port = args.status_port
        cfg.heartbeat_path = args.heartbeat
        cfg.heartbeat_every_s = args.heartbeat_every
        cfg.history = args.history
        cfg.history_dir = args.history_dir
        server = InferenceServer(net, cfg, logger=log)
        with server:
            frontends = make_frontends(server)
            try:
                _serve_until_done(server.status, args, log,
                                  run_fn=lambda:
                                  run_demo(server, args.demo))
            finally:
                for fe in frontends:
                    fe.stop()


def _serve_until_done(status_fn, args, log: Logger, run_fn) -> None:
    if args.demo is not None:
        print(json.dumps(run_fn()))
        return
    log.log("serving; Ctrl-C to stop")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        log.log("interrupted; draining")
        print(json.dumps(status_fn()), file=sys.stderr)


if __name__ == "__main__":
    main()
