"""Traffic-derived batch-bucket ladders.

The server pads every formed batch up to a configured bucket so the jit
cache holds exactly `len(buckets)` compiled forwards. The default ladder
is powers of two up to max_batch — a blind guess. But `FillMeter` already
records exactly what batch sizes traffic forms (`batch_size_hist` in the
serve JSONL / /status); `derive_buckets` turns that histogram into the
ladder that MINIMIZES padded slots for the observed distribution (Orca,
OSDI'22: schedule the queue *into* the accelerator's batch shape — here
the dual: shape the compiled forwards to the queue the traffic forms).

Exact DP: the optimal <=k-rung ladder's rungs sit ON observed sizes (any
rung between two observed sizes can drop to the lower one without cost),
so candidates are the distinct observed sizes plus the mandatory top rung
`max_batch` (a full batch must always have a bucket). Minimizing
`sum_s count[s] * rung(s)` — total padded slots, the denominator of the
fill ratio — over m distinct sizes and k rungs is O(m^2 k); m <= max_batch
makes this instant.

Workflow (offline first, per the bucket-ladder acceptance):

    sparknet-serve --model lenet ... --workdir run/          # records
    sparknet-serve --model lenet ... --buckets-from run/serving_*.jsonl

The second invocation reads the recorded `batch_size_hist` rows and
serves on the fitted ladder; `bench.py --econ` A/Bs the two ladders on a
skewed synthetic trace and pins `bucket_compiles == len(buckets)` after
full traffic (the ladder changes shape, never the compile-churn
guarantee).
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Mapping, Optional, Tuple


def padded_slots(sizes: Mapping[int, int],
                 buckets: Tuple[int, ...]) -> int:
    """Total padded bucket slots the ladder spends on this traffic
    histogram (each size rides the smallest rung >= it). The fill ratio
    of a ladder on a histogram is sum(s*n)/padded_slots."""
    bs = sorted(buckets)
    total = 0
    for s, n in sizes.items():
        rung = next((b for b in bs if b >= s), None)
        if rung is None:
            raise ValueError(f"batch size {s} exceeds the largest bucket "
                             f"{bs[-1]}")
        total += rung * int(n)
    return total


def fill_ratio(sizes: Mapping[int, int], buckets: Tuple[int, ...]) -> float:
    real = sum(int(s) * int(n) for s, n in sizes.items())
    padded = padded_slots(sizes, buckets)
    return real / padded if padded else 0.0


def derive_buckets(sizes: Mapping[int, int], max_batch: int,
                   k: int = 4) -> Tuple[int, ...]:
    """Fit a <=k-rung bucket ladder to an observed batch-size histogram.

    `sizes`: {real batch size: count} (FillMeter.size_hist(), or the
    JSONL aggregation below — string keys tolerated). Sizes above
    max_batch are clipped to it (the batcher never forms them, but a
    histogram from a previous config might carry them). Returns a sorted
    tuple whose last rung is always max_batch, minimizing total padded
    slots exactly. An empty histogram falls back to (max_batch,) — with
    no evidence, one full-width bucket spends the fewest compiles."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1 (got {max_batch})")
    if k < 1:
        raise ValueError(f"bucket ladder needs k >= 1 rungs (got {k})")
    hist: Dict[int, int] = {}
    for s, n in sizes.items():
        s, n = int(s), int(n)
        if n <= 0 or s <= 0:
            continue
        hist[min(s, max_batch)] = hist.get(min(s, max_batch), 0) + n
    # the mandatory top rung rides the DP as a (possibly zero-count) size
    hist.setdefault(max_batch, 0)
    ss = sorted(hist)                       # s_0 < ... < s_{m-1}
    counts = [hist[s] for s in ss]
    m = len(ss)
    k = min(k, m)
    csum = [0]
    for n in counts:
        csum.append(csum[-1] + n)           # csum[i] = sum(counts[:i])

    def seg(a: int, b: int) -> int:         # sizes a..b ride rung ss[b]
        return ss[b] * (csum[b + 1] - csum[a])

    # dp[j][i] = min padded slots covering sizes ss[0..i] with exactly
    # j+1 rungs, the top rung AT ss[i]; parent[j][i] = the previous
    # rung's index (-1 = this rung covers from the bottom)
    INF = float("inf")
    dp = [[INF] * m for _ in range(k)]
    parent = [[-1] * m for _ in range(k)]
    for i in range(m):
        dp[0][i] = seg(0, i)
    for j in range(1, k):
        for i in range(j, m):
            best, arg = dp[j - 1][i], -2    # -2 = unused extra rung
            for p in range(j - 1, i):
                c = dp[j - 1][p] + seg(p + 1, i)
                if c < best:
                    best, arg = c, p
            dp[j][i] = best
            parent[j][i] = arg
    # backtrack from (k-1, m-1): the top rung is always ss[m-1]==max_batch
    rungs, j, i = [m - 1], k - 1, m - 1
    while j > 0:
        p = parent[j][i]
        if p == -2:                          # the extra rung bought nothing
            j -= 1
            continue
        if p == -1:
            break
        rungs.append(p)
        j, i = j - 1, p
    return tuple(sorted(ss[r] for r in set(rungs)))


def size_hist_from_jsonl(paths: Iterable[str],
                         model: Optional[str] = None
                         ) -> Dict[str, Dict[int, int]]:
    """Aggregate `batch_size_hist` records from serve metrics JSONLs:
    {model: {size: count}}. The hist rows are CUMULATIVE per process, so
    per file only the LAST row per model counts; multiple files (several
    replicas/processes) sum. `model` filters to one model (still keyed
    in the result)."""
    out: Dict[str, Dict[int, int]] = {}
    for path in paths:
        last: Dict[str, Dict] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                hist = rec.get("batch_size_hist")
                if not isinstance(hist, dict):
                    continue
                name = str(rec.get("model", "default"))
                if model is not None and name != model:
                    continue
                last[name] = hist
        for name, hist in last.items():
            agg = out.setdefault(name, {})
            for s, n in hist.items():
                try:
                    agg[int(s)] = agg.get(int(s), 0) + int(n)
                except (TypeError, ValueError):
                    continue
    return out
