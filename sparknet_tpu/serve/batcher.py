"""Dynamic batching: a thread-safe request queue + the batch-forming policy.

The policy is the adaptive-batching core of Clipper (Crankshaw et al.,
NSDI 2017): a batch closes when EITHER it reaches `max_batch` examples OR
the OLDEST queued request has waited `max_wait_s` — so under saturating
load batches run full (throughput mode: the jit forward amortizes over
max_batch rows) and under trickle load no request waits longer than the
deadline plus one forward (latency mode). The deadline is keyed on the
oldest request, not the newest: a steady trickle cannot starve the head
of the queue by perpetually resetting the timer.

The consumer is WOKEN ON SUBMIT: `next_batch` parks on a condition
variable with no polling quantum — an idle worker sleeps until the next
`submit` notifies it (or until `wake_at`, the caller's periodic-duty
alarm for hot-reload polls and heartbeats). The old `poll_s` idle tick
put up to one poll interval of pure quantization into a lone request's
latency; now a lone request's latency is bounded by `max_wait_s` plus
one forward, full stop (pinned in tests).

Requests may carry a client DEADLINE (`submit(deadline_s=...)`). Batch
formation is deadline-aware twice over: the batch closes early when a
queued request's deadline would expire before the oldest-request timer
(serve it while the answer still matters), and a request whose deadline
has ALREADY expired is shed at formation — its future fails with
`DeadlineExpiredError` and it never pads into a bucket, so dead requests
never occupy forward slots (Orca's lesson: schedule the queue into the
accelerator's batch shape, and the batch shape is too precious for
corpses). Shed demand is counted per reason on
`sparknet_serve_shed_total{model,reason}`.

One consumer (the server's worker thread, or one router pool thread at a
time under the lane lock) calls `next_batch`; any number of producer
threads call `submit` and block on the returned
`concurrent.futures.Future`. Padding to shape buckets is the SERVER's
concern — the batcher only promises len(batch) <= max_batch, so a batch
never spans buckets.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class QueueFullError(RuntimeError):
    """Backpressure signal: the request queue is at capacity. Callers
    (an RPC frontend, a bench client) should shed or retry — unbounded
    queueing would just convert overload into unbounded latency. The
    HTTP frontend maps this to 429 + Retry-After."""


class RequestCancelledError(RuntimeError):
    """The request was cancelled (hedging's losing leg, or an explicit
    client CANCEL frame) while still queued — it never formed into a
    batch. Cancellation is BEST-EFFORT: a request that already formed
    cannot be cancelled and completes normally (the wire maps this to
    the 499 `cancelled` error kind)."""


class DeadlineExpiredError(RuntimeError):
    """The request's client deadline passed before a forward could run;
    it was shed instead of padded into a bucket. The HTTP frontend maps
    this to 503 + Retry-After (the answer would have been dead on
    arrival — better an immediate, honest shed than a late response)."""


@dataclass
class ServeRequest:
    """One queued inference request: per-example input arrays (no batch
    dim), the future its response lands on, its enqueue time (the
    latency clock starts at submit, not at batch formation), and an
    optional absolute client deadline on the same perf_counter clock."""

    payload: Dict[str, np.ndarray]
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.perf_counter)
    id: int = 0
    deadline: Optional[float] = None
    # admission class the request arrived under ("high"/"normal"/"low");
    # the fleet controller reads the queue's low-priority share so
    # scavenger (batch-tenant) backlog never reads as online demand
    priority: str = "normal"
    # per-request named output blobs (the featurizer route): None =
    # the lane's configured outputs / default per-row blobs
    outputs: Optional[Tuple[str, ...]] = None
    # distributed-trace context (obs/reqtrace.TraceContext) riding the
    # request through batch formation: None = untraced (the common case;
    # the worker's span emission is gated on this plus one global check)
    trace: Optional[Any] = None


class DynamicBatcher:
    """Thread-safe queue + max-batch/max-wait batch former (one consumer).

    `model` labels every metric family this batcher registers (the
    multi-model router shares ONE registry across lanes — per-model
    labels are what keep the lanes' demand distinguishable). `on_submit`
    is an optional callback fired after each accepted enqueue, OUTSIDE
    the queue lock — the router's pool scheduler hangs its wake-up on
    it."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.005,
                 max_queue: int = 1024, registry=None,
                 model: str = "default",
                 on_submit: Optional[Callable[[], None]] = None):
        assert max_batch >= 1 and max_queue >= max_batch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.model = str(model)
        self.on_submit = on_submit
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._ids = itertools.count()
        self._closed = False
        self.shed = 0  # lifetime shed count (all reasons)
        # shared-schema telemetry (obs.MetricsRegistry): accepted vs shed
        # demand, and the live queue depth as a scrape-time gauge
        self._c_submitted = self._c_rejected = self._c_shed = None
        if registry is not None:
            self._c_submitted = registry.counter(
                "sparknet_serve_submitted_total", "requests accepted",
                labels=("model",))
            self._c_rejected = registry.counter(
                "sparknet_serve_queue_rejected_total",
                "requests shed by backpressure (queue at capacity)",
                labels=("model",))
            self._c_shed = registry.counter(
                "sparknet_serve_shed_total",
                "requests shed before a forward, by reason (deadline = "
                "client deadline expired before batch formation)",
                labels=("model", "reason"))
            registry.gauge(
                "sparknet_serve_queue_depth",
                "requests queued, not yet formed into a batch",
                labels=("model",)
            ).set_fn(self.depth, model=self.model)

    def depth(self) -> int:
        return len(self._q)  # len(deque) is atomic; hot path, no lock

    def low_depth(self) -> int:
        """Queued requests in the "low" class (scavenger/batch tenants).
        Scanned under the lock at the fleet controller's tick cadence —
        never on the submit hot path."""
        with self._lock:
            return sum(1 for r in self._q if r.priority == "low")

    def submit(self, payload: Dict[str, Any],
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None,
               outputs: Optional[Tuple[str, ...]] = None,
               trace: Optional[Any] = None) -> Future:
        """Enqueue one request; returns its response future. Raises
        QueueFullError at capacity and RuntimeError after close().
        `deadline_s` (relative seconds) is the client's answer-by bound:
        a request that cannot be formed into a batch before it expires
        is shed with DeadlineExpiredError instead of riding a bucket
        slot. An ALREADY-expired deadline returns a pre-failed future
        without touching the queue. `priority` tags the queued request
        with its admission class (low-share telemetry); `outputs` pins
        per-request named blobs for the forming forward; `trace` is the
        request's distributed-trace context (rides to the worker)."""
        req = ServeRequest(payload={k: np.asarray(v)
                                    for k, v in payload.items()},
                           priority=(priority or "normal"),
                           outputs=(tuple(outputs) if outputs else None),
                           trace=trace)
        if deadline_s is not None:
            req.deadline = req.t_enqueue + float(deadline_s)
            if deadline_s <= 0:
                self._shed([req], "deadline")
                return req.future
        with self._nonempty:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._q) >= self.max_queue:
                if self._c_rejected is not None:
                    self._c_rejected.inc(model=self.model)
                raise QueueFullError(
                    f"request queue at capacity ({self.max_queue})")
            req.id = next(self._ids)
            self._q.append(req)
            self._nonempty.notify()
        if self._c_submitted is not None:
            self._c_submitted.inc(model=self.model)
        if self.on_submit is not None:
            self.on_submit()
        return req.future

    def cancel(self, future: Future) -> bool:
        """Best-effort cancel of a QUEUED request by its future: remove
        it from the queue and fail the future with
        RequestCancelledError. Returns True iff the request was still
        queued — False means it already formed into a batch (or was
        never here) and will complete normally; the caller drops the
        cancel, exactly-once delivery is preserved by the future's
        first-resolution-wins semantics."""
        hit: Optional[ServeRequest] = None
        with self._nonempty:
            for r in self._q:
                if r.future is future:
                    hit = r
                    break
            if hit is not None:
                self._q.remove(hit)
        if hit is None:
            return False
        if not hit.future.done():
            hit.future.set_exception(RequestCancelledError(
                "request cancelled while queued (never formed into a "
                "batch)"))
        with self._lock:
            self.shed += 1
        if self._c_shed is not None:
            self._c_shed.inc(1, model=self.model, reason="cancelled")
        return True

    def _pop_expired_locked(self, now: float) -> List[ServeRequest]:
        """Remove every queued request whose deadline has passed (caller
        holds the lock; futures are resolved OUTSIDE it)."""
        if not any(r.deadline is not None and r.deadline <= now
                   for r in self._q):
            return []
        keep, dead = [], []
        for r in self._q:
            (dead if r.deadline is not None and r.deadline <= now
             else keep).append(r)
        self._q.clear()
        self._q.extend(keep)
        return dead

    def _shed(self, reqs: List[ServeRequest], reason: str) -> None:
        """Fail shed requests' futures + count them. Callers hold no
        lock (set_exception may run waiter callbacks); the counter add
        takes the queue lock once — submit() sheds pre-expired requests
        on N producer threads concurrently with the consumer's
        formation sheds, and a bare += would lose counts."""
        if not reqs:
            return
        for r in reqs:
            if not r.future.done():
                waited = time.perf_counter() - r.t_enqueue
                r.future.set_exception(DeadlineExpiredError(
                    f"deadline expired before batch formation "
                    f"(waited {waited * 1e3:.1f} ms)"))
        with self._lock:
            self.shed += len(reqs)
        if self._c_shed is not None:
            self._c_shed.inc(len(reqs), model=self.model, reason=reason)

    def next_batch(self, wake_at: Optional[float] = None,
                   poll_s: Optional[float] = None
                   ) -> Optional[List[ServeRequest]]:
        """Form the next batch. Parks on the condition variable until a
        submit arrives (wake-on-submit — no polling quantum); `wake_at`
        (absolute perf_counter time) is the caller's periodic-duty alarm:
        with an empty queue the call returns None at `wake_at` so the
        worker can run hot-reload polls and heartbeats, then park again.
        `wake_at=None` blocks until work or close(). `poll_s` is the
        legacy relative form of the same alarm.

        Once a first request exists, the batch is held open until
        max_batch is reached, the OLDEST request's deadline
        (t_enqueue + max_wait_s) expires, or a queued request's CLIENT
        deadline would expire (close early and serve it while the answer
        matters). Requests whose client deadline already passed are shed
        here — before padding — and never returned. Returns None after
        close()."""
        if poll_s is not None and wake_at is None:
            wake_at = time.perf_counter() + float(poll_s)
        shed: List[ServeRequest] = []
        batch: List[ServeRequest] = []
        with self._nonempty:
            while not self._q and not self._closed:
                now = time.perf_counter()
                if wake_at is not None and now >= wake_at:
                    break
                self._nonempty.wait(
                    timeout=None if wake_at is None else wake_at - now)
            if self._q:
                close_at = self._q[0].t_enqueue + self.max_wait_s
                while len(self._q) < self.max_batch and not self._closed:
                    now = time.perf_counter()
                    # deadline-aware close: only the first max_batch
                    # requests can make THIS batch, so only their client
                    # deadlines may close it early — a hair EARLY
                    # (1 ms), so the request is served on the near side
                    # of its deadline instead of shed exactly at it
                    eff = min([close_at] + [
                        r.deadline - 1e-3 for r in
                        itertools.islice(self._q, self.max_batch)
                        if r.deadline is not None])
                    if eff - now <= 0:
                        break
                    self._nonempty.wait(timeout=eff - now)
                # shed the dead BEFORE they pad into a bucket
                shed = self._pop_expired_locked(time.perf_counter())
                n = min(len(self._q), self.max_batch)
                batch = [self._q.popleft() for _ in range(n)]
        self._shed(shed, "deadline")
        return batch or None

    def close(self) -> None:
        """Stop accepting requests and fail everything still queued (the
        server drains in-flight batches separately; queued-but-unformed
        requests must not hang their clients forever)."""
        with self._nonempty:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            self._nonempty.notify_all()
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("server shut down before this request "
                                 "was served"))
