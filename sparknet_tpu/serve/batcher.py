"""Dynamic batching: a thread-safe request queue + the batch-forming policy.

The policy is the adaptive-batching core of Clipper (Crankshaw et al.,
NSDI 2017): a batch closes when EITHER it reaches `max_batch` examples OR
the OLDEST queued request has waited `max_wait_s` — so under saturating
load batches run full (throughput mode: the jit forward amortizes over
max_batch rows) and under trickle load no request waits longer than the
deadline plus one forward (latency mode). The deadline is keyed on the
oldest request, not the newest: a steady trickle cannot starve the head
of the queue by perpetually resetting the timer.

One consumer (the server's worker thread) calls `next_batch`; any number
of producer threads call `submit` and block on the returned
`concurrent.futures.Future`. Padding to shape buckets is the SERVER's
concern — the batcher only promises len(batch) <= max_batch, so a batch
never spans buckets.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


class QueueFullError(RuntimeError):
    """Backpressure signal: the request queue is at capacity. Callers
    (an RPC frontend, a bench client) should shed or retry — unbounded
    queueing would just convert overload into unbounded latency."""


@dataclass
class ServeRequest:
    """One queued inference request: per-example input arrays (no batch
    dim), the future its response lands on, and its enqueue time (the
    latency clock starts at submit, not at batch formation)."""

    payload: Dict[str, np.ndarray]
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.perf_counter)
    id: int = 0


class DynamicBatcher:
    """Thread-safe queue + max-batch/max-wait batch former (one consumer)."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.005,
                 max_queue: int = 1024, registry=None):
        assert max_batch >= 1 and max_queue >= max_batch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._ids = itertools.count()
        self._closed = False
        # shared-schema telemetry (obs.MetricsRegistry): accepted vs shed
        # demand, and the live queue depth as a scrape-time gauge
        self._c_submitted = self._c_rejected = None
        if registry is not None:
            self._c_submitted = registry.counter(
                "sparknet_serve_submitted_total", "requests accepted")
            self._c_rejected = registry.counter(
                "sparknet_serve_queue_rejected_total",
                "requests shed by backpressure (queue at capacity)")
            registry.gauge(
                "sparknet_serve_queue_depth",
                "requests queued, not yet formed into a batch"
            ).set_fn(self.depth)

    def depth(self) -> int:
        return len(self._q)  # len(deque) is atomic; hot path, no lock

    def submit(self, payload: Dict[str, Any]) -> Future:
        """Enqueue one request; returns its response future. Raises
        QueueFullError at capacity and RuntimeError after close()."""
        req = ServeRequest(payload={k: np.asarray(v)
                                    for k, v in payload.items()})
        with self._nonempty:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._q) >= self.max_queue:
                if self._c_rejected is not None:
                    self._c_rejected.inc()
                raise QueueFullError(
                    f"request queue at capacity ({self.max_queue})")
            req.id = next(self._ids)
            self._q.append(req)
            self._nonempty.notify()
        if self._c_submitted is not None:
            self._c_submitted.inc()
        return req.future

    def next_batch(self, poll_s: float = 0.05
                   ) -> Optional[List[ServeRequest]]:
        """Form the next batch. Blocks up to `poll_s` for the FIRST
        request (returning None on an idle tick — the server uses these
        ticks for hot-reload polls and heartbeats), then holds the batch
        open until max_batch is reached or the oldest request's deadline
        (t_enqueue + max_wait_s) expires. Returns None after close()."""
        with self._nonempty:
            if not self._q:
                self._nonempty.wait(timeout=poll_s)
                if not self._q:
                    return None
            deadline = self._q[0].t_enqueue + self.max_wait_s
            while len(self._q) < self.max_batch and not self._closed:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._nonempty.wait(timeout=remaining)
            n = min(len(self._q), self.max_batch)
            return [self._q.popleft() for _ in range(n)]

    def close(self) -> None:
        """Stop accepting requests and fail everything still queued (the
        server drains in-flight batches separately; queued-but-unformed
        requests must not hang their clients forever)."""
        with self._nonempty:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            self._nonempty.notify_all()
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("server shut down before this request "
                                 "was served"))
