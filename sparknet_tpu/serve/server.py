"""The serving loop: bucket-padded jit forwards over dynamically formed
batches, hot-reload between batches, metrics + /healthz.

Shape buckets: requests are padded to the smallest configured bucket size
>= the formed batch (default: powers of two up to max_batch), so the jit
cache holds exactly len(buckets) compiled forwards — an arbitrary batch
size would compile a fresh XLA program per distinct size and the server
would spend its first hour tracing. Padding rows are zeros; de-padding
slices each request's own row back out. Within one compiled bucket the
padding is bitwise-lossless for these nets (every layer is row-independent
across the batch — conv/fc/relu/pool/lrn/softmax; tests pin this).
Across DIFFERENT buckets XLA may re-associate reductions, so outputs are
allclose-but-not-bitwise between e.g. the 1-bucket and 8-bucket of the
same example — same contract training accepts for different batch shapes.

Pad/de-pad is PRE-SIZED: each bucket owns one cached host buffer per net
input (allocated on first use, reused every batch), and request rows are
stacked straight into it — the per-batch Python cost is one buffer fill
per input, not an alloc-stack-alloc-pad-alloc-concat chain per request.
Safe because `net.forward` copies host->device synchronously before
returning, and exactly one thread drives a lane at a time (below).

One worker owns the net: batch forwards, weight swaps (between batches,
via ModelManager), and the canary all run on it, so no lock guards the
params. In the classic single-model deployment that worker is the lane's
own thread (`start()`); under the multi-model router the lane has NO
thread of its own — router pool threads call `serve_tick()` one at a
time under `lane_lock` (same single-writer guarantee, pooled across
models). The worker parks in the batcher's wake-on-submit wait; periodic
duties (hot-reload poll, heartbeat) run on their own cadence via the
`wake_at` alarm, not a fixed idle poll. Request futures are resolved from
the serving thread; client threads only enqueue and wait.

Requests are dicts of PER-EXAMPLE arrays (no batch dim). Missing net
inputs are zero-filled (nets from the zoo carry label-consuming loss/
accuracy heads; an inference client has no labels). An optional
`ImagePreprocessor` decodes raw request pixels batch-at-a-time with
`train=False` (deterministic center crop + mean subtract — the same
`data/preprocess.py` path eval uses, so served pixels match eval pixels).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..model.quant import QuantConfig
from ..obs import (MetricsRegistry, StatusServer, register_build_info,
                   trace as obs_trace)
from ..obs import device as obs_device
from ..obs import reqtrace
from ..utils.compile_cache import init_compile_cache, track_compiles
from ..utils.heartbeat import HeartbeatWriter
from ..utils.logger import Logger
from ..utils.metrics import FillMeter, LatencyStats
from .batcher import DynamicBatcher, ServeRequest
from .model_manager import ModelManager


def net_input_specs(net) -> Dict[str, Tuple[Tuple[int, ...], str]]:
    """{input name: (per-example device-layout shape, dtype)} for either
    backend (JaxNet wraps a CompiledNet; GraphNet exposes introspection
    methods — the NetInterface split featurizer_app already bridges)."""
    if hasattr(net, "net"):  # JaxNet
        dtypes = {i.name: i.dtype for i in net.net.spec.inputs}
        return {name: (tuple(shape[1:]), dtypes.get(name, "float32"))
                for name, shape in net.net.input_shapes.items()}
    shapes, dtypes = net.input_shapes(), net.input_dtypes()
    return {name: (tuple(shapes[name][1:]),
                   dtypes.get(name, "float32")) for name in shapes}


def zeros_batch(net, n: int, float_dtype=None) -> Dict[str, np.ndarray]:
    """An all-zeros batch of n examples in the net's input schema — the
    canary forward's food, and the source of padding for absent inputs.
    `float_dtype` overrides the schema dtype for FLOATING inputs (the
    quantized serve path feeds bf16 activation buffers — half the
    host->device bytes; int/label inputs keep their schema dtype)."""
    out = {}
    for name, (shape, dtype) in net_input_specs(net).items():
        dt = np.dtype(dtype)
        if float_dtype is not None and np.issubdtype(dt, np.floating):
            dt = np.dtype(float_dtype)
        out[name] = np.zeros((n,) + shape, dtype=dt)
    return out


def parity_batch(net, n: int, seed: int = 0) -> Dict[str, np.ndarray]:
    """A deterministic RANDOM batch in the net's input schema — the
    quant parity canary's food. Zeros would vet only the bias path (a
    conv of zeros never touches w, so a corrupted weight SCALE would
    sail through); standard-normal pixels exercise every quantized
    weight."""
    r = np.random.default_rng(seed)
    out = {}
    for name, (shape, dtype) in net_input_specs(net).items():
        dt = np.dtype(dtype)
        if np.issubdtype(dt, np.floating):
            out[name] = r.standard_normal((n,) + shape).astype(dt)
        else:
            out[name] = np.zeros((n,) + shape, dtype=dt)
    return out


# Reserved payload key carrying a request's named output blobs across
# transports that only speak tensors (the binary wire, npz POST bodies).
# Encoded as a uint8 view of the comma-joined names so it rides the
# existing frame format — no wire VERSION bump, and a proxy hop that
# doesn't understand it forwards it untouched (the terminal frontend
# pops it before the tensors reach the net).
OUTPUTS_KEY = "__outputs__"


def encode_outputs(payload: Dict[str, Any],
                   outputs: Optional[Tuple[str, ...]]) -> Dict[str, Any]:
    """Return payload with the outputs request folded in as a tensor
    field (no-op when outputs is empty). Does not mutate the input."""
    if not outputs:
        return payload
    names = ",".join(outputs)
    out = dict(payload)
    out[OUTPUTS_KEY] = np.frombuffer(names.encode("utf-8"), dtype=np.uint8)
    return out


def pop_outputs(payload: Dict[str, Any]) -> Tuple[Dict[str, Any],
                                                  Optional[Tuple[str, ...]]]:
    """Split a payload into (tensors, requested output names). The
    inverse of encode_outputs; payloads without the key pass through."""
    if OUTPUTS_KEY not in payload:
        return payload, None
    out = dict(payload)
    raw = np.asarray(out.pop(OUTPUTS_KEY), dtype=np.uint8)
    names = raw.tobytes().decode("utf-8", errors="replace")
    parsed = tuple(n for n in (s.strip() for s in names.split(",")) if n)
    return out, (parsed or None)


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to max_batch (max_batch itself always included)."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(out)


@dataclass
class ServeConfig:
    """Knobs for the inference server (the `sparknet-serve` CLI mirrors
    these 1:1)."""

    # identity: labels every serve metric family this lane registers
    # (the router shares one registry across models) and names the model
    # in /status, heartbeats, and the HTTP data plane's URL space
    model_name: str = "default"
    # batching policy
    max_batch: int = 8
    max_wait_ms: float = 5.0            # oldest-request deadline
    # batch-size buckets (None -> powers of 2 up to max_batch; or a
    # traffic-derived ladder from serve.buckets.derive_buckets /
    # `sparknet-serve --buckets-from`). Validated at CONSTRUCTION
    # (__post_init__, the ElasticConfig rule): strictly increasing,
    # positive, and the top rung must cover a full max_batch batch —
    # a bad ladder used to surface as a StopIteration inside the first
    # forward's bucket pick, long after the config typo that caused it.
    buckets: Optional[Tuple[int, ...]] = None
    max_queue: int = 1024               # backpressure threshold
    # weight-only quantized serving (model/quant.py): None = the f32
    # path exactly as before; "int8" (or a QuantConfig) = weights are
    # quantized per output channel at ModelManager load time, forwards
    # run int8-weight x bf16-activation, and every install is gated on
    # an allclose parity canary against the f32 forward — a bad
    # quantization (e.g. a corrupted scale) never serves.
    quant: Optional[Any] = None
    # persistent XLA compile cache (utils/compile_cache.py): directory
    # for jax's compilation cache, so replica cold-starts / hot-swap
    # retraces / bucket first-forwards re-use executables across
    # PROCESSES. None = only $SPARKNET_COMPILE_CACHE /
    # $JAX_COMPILATION_CACHE_DIR, if set.
    compile_cache_dir: Optional[str] = None
    # per-model latency objective (ms). Advisory: stamped into /status
    # and BENCH_SERVE rows (p99 <= slo at the sustainable rate is the
    # open-loop acceptance); nothing enforces it at runtime.
    slo_p99_ms: Optional[float] = None
    # response content: blob names to return (None -> the net's output
    # schema, e.g. prob/accuracy/loss for zoo nets — pass ("prob",) to
    # skip the label-dependent heads)
    outputs: Optional[Tuple[str, ...]] = None
    # checkpoint hot-reload
    checkpoint_dir: Optional[str] = None
    poll_interval_s: float = 2.0
    # ± fraction of poll_interval_s each poll deadline is jittered by: a
    # fleet of replicas watching one bucket must not list it in lockstep
    # on every commit (thundering herd)
    poll_jitter: float = 0.1
    canary: bool = True                 # nonfinite-canary gate on swaps
    # fleet identity: the key this replica looks itself up under in the
    # rollout gate and the `replica` label on the freshness gauges
    # (providers pass their tag; a standalone server stays "local")
    replica_name: str = "local"
    # rollout gate path (fleet/rollout.py ROLLOUT.json): when set, this
    # replica only adopts checkpoint steps the fleet rollout duty
    # approved for it; missing gate = ungated independent polling
    rollout_gate: Optional[str] = None
    # observability. status_port serves /metrics (Prometheus text from
    # the shared obs registry — the SAME metric-name schema the training
    # process exports), /healthz and /status (the JSON vitals dict).
    # registry: pass a MetricsRegistry to share one registry across
    # co-located components; None = a fresh per-server instance.
    status_port: Optional[int] = None   # None = no HTTP; 0 = ephemeral
    status_host: str = "127.0.0.1"      # "0.0.0.0" for cross-host scrapes
    # SLO ledger (obs/history.py + obs/slo.py). history=True runs the
    # metrics-history sampler thread (multi-resolution rings, the
    # /timeseries route) and — when an objective is declared — the
    # burn-rate alerter (/slo/status, slo section in /status, fleet
    # page escalation). history_dir persists append-only JSONL shards
    # `sparknet-slo` reports from (None = rings only, no disk).
    history: bool = False
    history_dir: Optional[str] = None
    history_interval_s: float = 1.0
    # availability objective (fraction of requests answered "ok", e.g.
    # 0.999); pairs with slo_p99_ms (the latency objective) to form this
    # lane's SloSpec. slo_spec overrides both with a full obs.slo.SloSpec
    # (custom burn windows).
    slo_availability: Optional[float] = None
    slo_spec: Optional[Any] = None
    heartbeat_path: Optional[str] = None
    heartbeat_every_s: float = 10.0
    metrics_every_batches: int = 50     # JSONL cadence (0 = off)
    # DEPRECATED (wake-on-submit): the worker no longer idle-polls; it
    # parks in the batcher's condition wait and wakes on submit, with
    # periodic duties (reload poll, heartbeat) alarmed at their own
    # cadence. Kept so old configs still construct; only healthz's
    # freshness bound still glances at it.
    idle_poll_s: float = 0.05
    registry: Optional[MetricsRegistry] = None

    def __post_init__(self) -> None:
        # fail at construction, not at the first _pick_bucket next() —
        # the ElasticConfig/OpsImpl rule
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 "
                             f"(got {self.max_batch})")
        if self.buckets is not None:
            b = tuple(int(x) for x in self.buckets)
            if not b:
                raise ValueError("buckets must be None or non-empty")
            if any(x <= 0 for x in b):
                raise ValueError(f"buckets must be positive (got {b})")
            if any(y <= x for x, y in zip(b, b[1:])):
                raise ValueError(
                    f"buckets must be strictly increasing — sorted, no "
                    f"duplicates (got {b})")
            if b[-1] < self.max_batch:
                raise ValueError(
                    f"largest bucket {b[-1]} < max_batch "
                    f"{self.max_batch}: a full batch would have no "
                    f"bucket")
            self.buckets = b
        if self.history_interval_s <= 0:
            raise ValueError(f"history_interval_s must be > 0 "
                             f"(got {self.history_interval_s})")
        if self.slo_availability is not None \
                and not 0.0 < self.slo_availability < 1.0:
            raise ValueError(f"slo_availability must be in (0, 1) "
                             f"(got {self.slo_availability})")
        # "int8" / dict / QuantConfig -> QuantConfig (validates knobs)
        self.quant = QuantConfig.coerce(self.quant)


class InferenceServer:
    """Dynamic-batching inference over one NetInterface net (module doc)."""

    def __init__(self, net, cfg: Optional[ServeConfig] = None,
                 preprocessor=None, logger: Optional[Logger] = None):
        self.net = net
        self.cfg = cfg = cfg if cfg is not None else ServeConfig()
        self.model_name = cfg.model_name
        self.preprocessor = preprocessor
        self.log = logger
        # persistent compile cache: process-global, so first-server-wins
        # on the directory; a replica cold-start with a warm cache dir
        # re-uses every bucket executable instead of recompiling them.
        # Called UNCONDITIONALLY (the train loop's rule): with no knob,
        # $SPARKNET_COMPILE_CACHE / $JAX_COMPILATION_CACHE_DIR still
        # apply — and get the cache-everything floors dropped
        init_compile_cache(cfg.compile_cache_dir)
        self.buckets = tuple(sorted(cfg.buckets or
                                    default_buckets(cfg.max_batch)))
        assert self.buckets[-1] >= cfg.max_batch, (
            f"largest bucket {self.buckets[-1]} < max_batch "
            f"{cfg.max_batch}: a full batch would have no bucket")
        # quantized serving: bf16 activation buffers (half the H2D bytes;
        # the schema dtype otherwise). The pad-buffer cache below is
        # keyed by dtype as well as bucket so a quant<->f32 transition
        # can never alias buffers of the wrong dtype.
        self.quant = cfg.quant
        self._float_dtype = None
        if self.quant is not None and self.quant.act == "bfloat16":
            import ml_dtypes
            self._float_dtype = np.dtype(ml_dtypes.bfloat16)
        # the shared-schema registry: every serve component registers into
        # it and /metrics renders it (one exporter for train AND serve);
        # under the router ALL lanes share one registry and the `model`
        # label keeps their families apart
        self.registry = cfg.registry or MetricsRegistry()
        register_build_info(self.registry)
        self._c_requests = self.registry.counter(
            "sparknet_serve_requests_total", "served requests by outcome",
            labels=("model", "outcome"))
        # jit-cache churn as a first-class metric: the FIRST forward of
        # each batch bucket is the one that builds that bucket's compiled
        # executable — count and time it. Steady state == len(buckets)
        # per model; growth past that means compile cliffs are back in
        # the tail.
        self._c_bucket_compiles = self.registry.counter(
            "sparknet_serve_bucket_compiles_total",
            "first forward per batch bucket (jit-cache entries built)",
            labels=("model",))
        self._h_bucket_compile = self.registry.histogram(
            "sparknet_serve_bucket_compile_seconds",
            "wall time of each bucket's first (compiling) forward",
            labels=("model",), buckets=obs_device.COMPILE_BUCKETS)
        self._compiled_buckets: set = set()
        self.batcher = DynamicBatcher(cfg.max_batch,
                                      max_wait_s=cfg.max_wait_ms / 1e3,
                                      max_queue=cfg.max_queue,
                                      registry=self.registry,
                                      model=cfg.model_name)
        hb = (HeartbeatWriter(cfg.heartbeat_path, role="serve",
                              interval_s=cfg.heartbeat_every_s,
                              registry=self.registry)
              if cfg.heartbeat_path else None)
        self.heartbeat = hb
        self.manager = ModelManager(
            net, checkpoint_dir=cfg.checkpoint_dir,
            poll_interval_s=cfg.poll_interval_s,
            canary_batch=(zeros_batch(net, self.buckets[0],
                                      float_dtype=self._float_dtype)
                          if cfg.canary else None),
            canary_outputs=cfg.outputs, logger=logger, heartbeat=hb,
            registry=self.registry, model=cfg.model_name,
            quant=self.quant,
            parity_batch=(parity_batch(net, self.buckets[0])
                          if self.quant is not None else None),
            replica=cfg.replica_name, poll_jitter=cfg.poll_jitter,
            rollout_gate=cfg.rollout_gate)
        # meters: worker-thread-written, internally locked — status() and
        # the HTTP scrape read consistent snapshots, never torn state
        self.latency = LatencyStats(registry=self.registry,
                                    model=cfg.model_name)
        self.fill = FillMeter(registry=self.registry,
                              model=cfg.model_name)
        self.requests_ok = 0
        self.requests_failed = 0
        self.batch_log: List[Tuple[int, int]] = []  # (n_real, bucket)
        self._t0 = time.time()
        self._images = 0
        # pre-sized pad buffers: {(bucket, float dtype): {input: zeros
        # host array}} plus the set of inputs a previous batch wrote real
        # rows into (those must be re-zeroed before a batch that doesn't
        # carry them). Keyed by DTYPE as well as bucket: the quantized
        # path fills bf16 activation buffers, and those must never alias
        # the f32 buffers a non-quant forward of the same bucket owns.
        self._bucket_buf: Dict[tuple, Dict[str, np.ndarray]] = {}
        self._bucket_dirty: Dict[tuple, set] = {}
        # router integration: exactly one thread may drive serve_tick at
        # a time (the lane's own worker, or one pool thread)
        self.lane_lock = threading.Lock()
        # periodic-duty cadence: the worker must surface at least this
        # often for reload polls / heartbeats / liveness ticks even with
        # an empty queue. Bounded by 1 s so /healthz freshness works.
        duties = [1.0]
        if cfg.checkpoint_dir:
            duties.append(cfg.poll_interval_s)
        if hb is not None:
            duties.append(cfg.heartbeat_every_s)
        self._duty_s = max(min(duties), 1e-3)
        self._worker: Optional[threading.Thread] = None
        self._http = None
        # SLO ledger handles (started with the server when cfg.history)
        self.history = None
        self.alerter = None
        # per-example input schema, resolved lazily at the submit door
        # (shape validation); None until the first submit
        self._input_specs = None
        self._running = False
        self._last_tick = 0.0

    # -- client API ----------------------------------------------------------

    def submit(self, payload: Dict[str, Any],
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None,
               outputs: Optional[Tuple[str, ...]] = None,
               trace=None):
        """Enqueue one example (dict of per-example arrays); returns a
        Future resolving to {blob name: per-example array}. `deadline_s`
        threads the client's answer-by bound into batch formation: an
        expired request is shed (DeadlineExpiredError) instead of
        occupying a bucket slot. `outputs` names the blobs THIS request
        wants (the featurizer's embedding route) — validated here
        against the net's blob table because the forward's name filter
        silently drops unknowns, and a typo should be a loud error, not
        an empty response. `priority` tags the queued request so fleet
        signals can tell scavenger backlog from online demand."""
        payload, inline = pop_outputs(payload)
        if outputs is None:
            outputs = inline
        if outputs:
            known = self._known_blobs()
            if known is not None:
                bad = [o for o in outputs if o not in known]
                if bad:
                    raise ValueError(
                        f"unknown output blob(s) {bad!r} "
                        f"(net has {sorted(known)})")
        self._validate_payload(payload)
        return self.batcher.submit(payload, deadline_s=deadline_s,
                                   priority=priority, outputs=outputs,
                                   trace=trace)

    def _validate_payload(self, payload: Dict[str, Any]) -> None:
        """Reject a mis-shaped or unknown-field example AT THE DOOR with
        a ValueError (the frontends' typed-400 ladder), before it can
        enter a batch. Previously a wrong per-example shape survived to
        the pre-sized pad path, where `np.stack(rows, out=buf[:n])` blew
        up the WHOLE signature group with an opaque "Output array is the
        wrong shape" — a client bug surfacing as a server-side 500.
        Skipped when a preprocessor is configured: raw pixel shapes
        legitimately differ from the net's input schema until decode."""
        if self.preprocessor is not None:
            return
        specs = self._input_specs
        if specs is None:
            try:
                specs = net_input_specs(self.net)
            except Exception:
                specs = {}  # net without introspection: can't validate
            self._input_specs = specs
        if not specs:
            return
        for k, v in payload.items():
            spec = specs.get(k)
            if spec is None:
                raise ValueError(
                    f"request field {k!r} is not a net input "
                    f"(net has {sorted(specs)})")
            shape = tuple(np.shape(v))
            if shape != spec[0]:
                raise ValueError(
                    f"request field {k!r} has per-example shape "
                    f"{shape}, net input wants {spec[0]}")

    def _known_blobs(self) -> Optional[set]:
        """The net's nameable blobs, or None when the backend can't
        enumerate them (then unknown names fall back to the forward's
        silent-drop behavior)."""
        inner = getattr(self.net, "net", None)
        shapes = getattr(inner, "blob_shapes", None)
        if isinstance(shapes, dict) and shapes:
            return set(shapes)
        return None

    def infer(self, payload: Dict[str, Any], timeout: float = 30.0
              ) -> Dict[str, np.ndarray]:
        """Synchronous convenience wrapper over submit(). The timeout IS
        the request deadline: a request this client will have abandoned
        is shed from the queue (DeadlineExpiredError) rather than riding
        a bucket slot to produce an answer nobody reads. The wait itself
        gets a small grace past the deadline so the shed lands as its
        honest exception — worker truly wedged, a bare futures
        TimeoutError still bounds the hang."""
        fut = self.submit(payload, deadline_s=timeout)
        return fut.result(timeout=timeout + 5.0)

    # -- lifecycle -----------------------------------------------------------

    def start(self, thread: bool = True) -> "InferenceServer":
        """Load initial weights and begin serving. `thread=False` skips
        spawning the lane's own worker (router mode: the ModelRouter's
        shared pool drives `serve_tick` instead)."""
        assert self._worker is None and not self._running, "already started"
        self.manager.load_initial()
        self._running = True
        self._last_tick = time.monotonic()
        if thread:
            self._worker = threading.Thread(target=self._run,
                                            name="serve-worker",
                                            daemon=True)
            self._worker.start()
        if self.cfg.status_port is not None:
            self._start_http(self.cfg.status_port)
        if self.cfg.history:
            self._start_history()
        return self

    def _start_history(self) -> None:
        """The SLO ledger: history sampler (+ alerter when an objective
        is declared), attached to the status server when one is up."""
        from ..obs.history import HistoryConfig, MetricsHistory
        from ..obs.slo import SloSpec, BurnRateAlerter
        self.history = MetricsHistory(
            self.registry,
            HistoryConfig(sample_interval_s=self.cfg.history_interval_s,
                          persist_dir=self.cfg.history_dir),
            logger=self.log)
        spec = self.cfg.slo_spec
        if spec is None and (self.cfg.slo_p99_ms is not None
                             or self.cfg.slo_availability is not None):
            spec = SloSpec(model=self.model_name,
                           latency_ms=self.cfg.slo_p99_ms,
                           availability=self.cfg.slo_availability)
        if spec is not None:
            self.alerter = BurnRateAlerter(self.history, [spec],
                                           logger=self.log).attach()
        if self._http is not None:
            self.history.attach_http(self._http)
            if self.alerter is not None:
                self.alerter.attach_http(self._http)
        self.history.start()

    def stop(self, drain_s: float = 5.0) -> None:
        """Stop accepting work, serve what's already queued (bounded by
        drain_s), then stop the worker."""
        deadline = time.monotonic() + drain_s
        while self.batcher.depth() and time.monotonic() < deadline:
            time.sleep(0.01)
        self._running = False
        self.batcher.close()
        if self._worker is not None:
            self._worker.join(timeout=max(drain_s, 1.0))
            self._worker = None
        # one final metrics row with the worker quiesced: a short-lived
        # server (demo, bench arm) whose traffic never reached the
        # metrics cadence still leaves its batch_size_hist on disk —
        # the --buckets-from input must survive the process
        # (metrics_every_batches=0 keeps meaning "JSONL off")
        if self.log is not None and self.fill.batches and \
                self.cfg.metrics_every_batches:
            self._log_metrics_row()
        if self.history is not None:
            self.history.stop()
            self.history = None
            self.alerter = None
        if self._http is not None:
            self._http.stop()
            self._http = None
        if self.heartbeat is not None:
            try:
                self.heartbeat.beat(self.manager.step or 0, status="done",
                                    rollbacks=self.manager.swap_failures,
                                    force=True)
            except OSError:
                pass

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- status --------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The /status JSON: serving vitals in one flat dict. Every field
        comes from a locked snapshot (FillMeter.snapshot, LatencyStats.
        summary) or a single-writer attribute — the HTTP thread reading
        while the worker mutates sees one consistent moment, not a mix."""
        dt = max(time.time() - self._t0, 1e-9)
        m = self.manager
        real, padded, batches = self.fill.snapshot()
        out = {
            "role": "serve",
            "model": self.model_name,
            "uptime_s": round(dt, 1),
            "queue_depth": self.batcher.depth(),
            "requests_ok": self.requests_ok,
            "requests_failed": self.requests_failed,
            "requests_shed": self.batcher.shed,
            "images_per_sec": round(self._images / dt, 2),
            "batches": batches,
            "batch_fill_ratio": round(real / padded if padded else 0.0, 4),
            "buckets": list(self.buckets),
            "bucket_compiles": len(self._compiled_buckets),
            # formed-batch size distribution (string keys: JSON object),
            # the input `serve.buckets.derive_buckets` fits a ladder to
            "batch_size_hist": {str(s): c for s, c
                                in sorted(self.fill.size_hist().items())},
            "quant": None if self.quant is None else self.quant.mode,
            "model_step": m.step,
            "replica": m.replica,
            # train->serve freshness: age of the serving step's commit
            # (None until a commit_ts-stamped checkpoint installs) and
            # how many committed steps this replica trails by.
            # _log_metrics_row lifts the numeric fields into the JSONL
            # stream, which is what the sparknet-metrics freshness view
            # aggregates.
            "freshness_s": m.freshness_s(),
            "model_step_lag": m.step_lag(),
            "latest_step_seen": m.latest_seen,
            "swaps": m.swaps,
            "swap_failures": m.swap_failures,
            "last_error": m.last_error,
        }
        if self.cfg.slo_p99_ms is not None:
            out["slo_p99_ms"] = self.cfg.slo_p99_ms
        if self.alerter is not None:
            # the ledger's live slice: firing alerts + budget left
            out["slo"] = self.alerter.summary()
        out.update(self.latency.summary())
        # recent worst captured requests (trace_id, total ms, dominant
        # stage): "p99 is burning" -> the exact trace in two steps. Reads
        # a locked snapshot; absent entirely when tracing is off.
        rt = reqtrace.active()
        if rt is not None:
            ex = rt.exemplars().get(self.model_name)
            if ex:
                out["slow_requests"] = ex
            out["reqtrace"] = rt.stats()
        # per-model rows for the pod view (PodAggregator._collect_http
        # lifts this into WorkerView.models; the router emits one row per
        # lane here, a single-model server exactly one)
        out["models"] = {self.model_name: self.model_row()}
        return out

    def reset_counters(self) -> None:
        """Zero the windowed serving metrics (latency, fill, throughput
        clock) — between load levels in a bench, or after warmup. Model/
        request totals (swaps, requests_ok) are lifetime counters and
        keep counting."""
        self.latency.reset()
        self.fill.reset()
        self._images = 0
        self._t0 = time.time()

    def healthy(self) -> bool:
        """Liveness: the serving thread (own worker, or the router pool)
        ticked recently (a wedged forward or a dead thread must flip
        /healthz to 503, not hang it)."""
        alive = (self._worker.is_alive() if self._worker is not None
                 else self._running)
        fresh = (time.monotonic() - self._last_tick) < max(
            3 * self._duty_s, 10 * self.cfg.idle_poll_s, 2.0)
        return alive and fresh

    # -- worker loop ---------------------------------------------------------

    def _run(self) -> None:
        while self._running:
            with self.lane_lock:
                self.serve_tick()

    def serve_tick(self, wake_at: Optional[float] = None) -> bool:
        """One worker iteration: park for a batch (wake-on-submit; surface
        at `wake_at` — default: now + the duty cadence — for periodic
        duties), serve it, then run duties. Callers other than the lane's
        own thread MUST hold `lane_lock`. Returns True when a batch was
        served (the router's pool uses this to distinguish progress from
        an idle tick)."""
        self._last_tick = time.monotonic()
        if wake_at is None:
            wake_at = time.perf_counter() + self._duty_s
        reqs = self.batcher.next_batch(wake_at=wake_at)
        if reqs:
            # a formed batch has already waited out its deadline:
            # serve it FIRST — a multi-second checkpoint download
            # must never sit between batch formation and its forward
            self._serve_batch(reqs)
        self.duty_tick()
        return bool(reqs)

    def duty_tick(self) -> None:
        """Hot-reload + heartbeat: ride the gaps AFTER serving / on idle
        ticks — a swap never interleaves with a forward (single driving
        thread per lane), and NOTHING the poll raises may kill that
        thread: a dead worker strands every queued future while submit()
        keeps accepting work."""
        self._last_tick = time.monotonic()
        try:
            self.manager.poll()
        except Exception as e:
            self.manager.last_error = f"poll: {e}"
            self._log(f"serve: reload poll crashed ({e}); serving "
                      f"continues on step {self.manager.step}")
        self._beat()

    def _beat(self) -> None:
        if self.heartbeat is None:
            return
        try:
            self.heartbeat.beat(
                self.manager.step or 0,
                status="degraded" if self.manager.last_error else "ok",
                rollbacks=self.manager.swap_failures,
                queue_depth=self.batcher.depth(),
                batch_fill=round(self.fill.ratio(), 4),
                models={self.model_name: self.model_row()})
        except OSError:
            pass  # observability must not take serving down

    def model_row(self) -> Dict[str, Any]:
        """The compact per-model vitals row (heartbeats, /pod/status):
        enough for `sparknet-podview` to attribute per-model stragglers
        without shipping the whole status dict."""
        lat = self.latency.summary()
        row = {"step": self.manager.step,
                # staleness without a /metrics scrape: the rollout duty
                # reads adoption (model_step) from heartbeat rows, and
                # sparknet-podview renders freshness per replica
                "model_step": self.manager.step,
                "freshness_s": self.manager.freshness_s(),
                "step_lag": self.manager.step_lag(),
                "queue_depth": self.batcher.depth(),
                "requests_ok": self.requests_ok,
                "requests_failed": self.requests_failed,
                "requests_shed": self.batcher.shed,
                "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
                "batch_fill": round(self.fill.ratio(), 4),
                "recent_occupancy": self.fill_signal(),
                "swaps": self.manager.swaps,
                "swap_failures": self.manager.swap_failures}
        rt = reqtrace.active()
        if rt is not None:
            worst = rt.worst(self.model_name)
            if worst is not None:
                row["slow_request"] = worst
        if self.alerter is not None:
            s = self.alerter.summary()
            # a router-shared alerter carries every lane's alerts: keep
            # only THIS model's on its row
            row["slo_firing"] = [
                f for f in s["firing"]
                if f.startswith(f"{self.model_name}:")]
            br = s["budget_remaining"].get(self.model_name)
            if br is not None:
                row["slo_budget_remaining"] = round(br, 4)
        return row

    def fill_signal(self) -> Optional[float]:
        """Recent batch occupancy vs max_batch in [0,1] (None until a
        batch forms) — the router's coalesced-formation trigger. NOT
        bucket-relative fill: a fragmented trickle pads into bucket 1
        at fill 1.0, while its occupancy is 1/max_batch."""
        occ = self.fill.recent_occupancy(self.cfg.max_batch)
        return None if occ is None else round(occ, 4)

    def _serve_batch(self, reqs: List[ServeRequest]) -> None:
        # heterogeneous traffic: group by input signature so one
        # mis-shaped request fails ITS group, not the whole batch (and
        # stacked arrays are always rectangular)
        groups: Dict[tuple, List[ServeRequest]] = {}
        for r in reqs:
            sig = tuple(sorted((k, v.shape, str(v.dtype))
                               for k, v in r.payload.items()))
            groups.setdefault(sig, []).append(r)
        for group in groups.values():
            self._forward_group(group)

    def _forward_group(self, reqs: List[ServeRequest]) -> None:
        with obs_trace.span("forward", n=len(reqs)):
            self._forward_group_inner(reqs)

    @staticmethod
    def _wire_dtype(v):
        """bf16 blobs (the quantized forward's outputs) -> f32 for the
        response; everything else passes through untouched."""
        arr = np.asarray(v)
        if str(arr.dtype) == "bfloat16":
            return arr.astype(np.float32)
        return arr

    def _bucket_batch(self, reqs: List[ServeRequest], bucket: int
                      ) -> Dict[str, np.ndarray]:
        """Fill this bucket's cached buffers with the group's rows: one
        pre-sized buffer per input, request rows stacked straight into
        it, the pad tail re-zeroed. Inputs absent from the request stay
        zero (re-zeroed only when a previous batch dirtied them)."""
        n = len(reqs)
        key = (bucket, str(self._float_dtype))
        buf = self._bucket_buf.get(key)
        if buf is None:
            buf = self._bucket_buf[key] = zeros_batch(
                self.net, bucket, float_dtype=self._float_dtype)
            self._bucket_dirty[key] = set()
        payload = reqs[0].payload
        if self.preprocessor is not None:
            # batch-level decode, eval semantics: center crop + mean
            # subtract are deterministic, so per-request and batched
            # decode agree (the parity test's precondition)
            payload = self.preprocessor.convert_batch(
                {k: np.stack([r.payload[k] for r in reqs])
                 for k in payload}, train=False)
        dirty = self._bucket_dirty[key]
        for k in dirty - set(payload):
            buf[k][:] = 0  # stale rows from a batch that carried k
        dirty.intersection_update(payload)
        for k in payload:
            dst = buf.get(k)
            if dst is None:
                raise ValueError(
                    f"request field {k!r} is not a net input "
                    f"(net has {sorted(buf)})")
            if self.preprocessor is not None:
                dst[:n] = payload[k]
            else:
                rows = [r.payload[k] for r in reqs]
                try:
                    np.stack(rows, out=dst[:n])
                except TypeError:
                    # unusual-dtype payload (e.g. int rows for a float
                    # input): stack on the side, let assignment cast —
                    # the slow path the old concat always paid
                    dst[:n] = np.stack(rows)
                except ValueError as e:
                    # belt-and-suspenders: the submit door validates
                    # shapes, so this is only reachable for payloads
                    # that bypassed it — name the field and the schema
                    # instead of numpy's bare "Output array is the
                    # wrong shape"
                    raise ValueError(
                        f"request field {k!r} rows (shape "
                        f"{np.shape(rows[0])}) do not match net input "
                        f"shape {dst.shape[1:]}") from e
            dst[n:] = 0
            dirty.add(k)
        return buf

    def _forward_group_inner(self, reqs: List[ServeRequest]) -> None:
        n = len(reqs)
        bucket = next(b for b in self.buckets if b >= n)
        # queue-wait: submit -> forward start, stamped on each future so
        # the frontends can surface it on the wire (RESPONSE meta /
        # X-Queue-Wait-Ms) — the split that tells a hedging tuner
        # whether the tail is queueing or compute
        t_form = time.perf_counter()
        for r in reqs:
            r.future._spkn_queue_wait_s = t_form - r.t_enqueue
        # distributed-trace stages: one global None-check when tracing is
        # off; per-request rows only for requests carrying a context.
        # bucket/batch_n attrs are SHARED by every coalesced request in
        # the group — the trace shows who a request formed with.
        rt = reqtrace.active()
        traced = ([r for r in reqs if r.trace is not None]
                  if rt is not None else ())
        for r in traced:
            rt.stage(r.trace, "queue", rt.to_us(r.t_enqueue),
                     (t_form - r.t_enqueue) * 1e6,
                     bucket=bucket, batch_n=n)
        try:
            full = self._bucket_batch(reqs, bucket)
            # per-request named blobs (the featurizer route) widen the
            # forward's fetch set; each request still receives only the
            # names IT asked for below
            extra = set()
            for r in reqs:
                if r.outputs:
                    extra.update(r.outputs)
            t_fwd0 = time.perf_counter()
            for r in traced:
                rt.stage(r.trace, "form", rt.to_us(t_form),
                         (t_fwd0 - t_form) * 1e6,
                         bucket=bucket, batch_n=n)
            with track_compiles() as tc:
                out = self.net.forward(
                    full,
                    blob_names=list(set(self.cfg.outputs or ()) | extra))
            t_fwd1 = time.perf_counter()
            if bucket not in self._compiled_buckets:
                # this forward traced+compiled the bucket's executable;
                # cache_hit says whether the persistent compile cache
                # served it (warm replica cold-start) or XLA built it
                # fresh (utils/compile_cache.py region verdict)
                self._compiled_buckets.add(bucket)
                dt = time.perf_counter() - t_fwd0
                self._c_bucket_compiles.inc(model=self.model_name)
                self._h_bucket_compile.observe(dt, model=self.model_name)
                obs_device.note_compile("serve_bucket", dt,
                                        cache_hit=tc.cache_hit)
            # de-pad: slice each request's own row out of per-row blobs;
            # batch-AGGREGATE blobs (the zoo heads' scalar loss/accuracy
            # — averaged over padding, meaningless per request) are
            # dropped unless cfg.outputs names them explicitly
            want = set(self.cfg.outputs) if self.cfg.outputs else None
            # responses are always f32 on the wire: the quantized path
            # computes in bf16, but npz does not round-trip bf16 and
            # clients should not need ml_dtypes to read a probability
            fields = [(k, self._wire_dtype(v), getattr(v, "ndim", 0) >= 1
                       and v.shape[0] == bucket)
                      for k, v in out.items()]
            # lane defaults: cfg.outputs if configured, else every
            # per-row blob — exactly the pre-outputs-route contract
            if want is not None:
                default = [f for f in fields if f[0] in want]
            else:
                default = [f for f in fields if f[2]]
            now = time.perf_counter()
            # emitted BEFORE set_result: resolving the future runs the
            # frontend's completion callback, which finishes the trace
            # record and drains this request's parked spans
            for r in traced:
                rt.stage(r.trace, "forward", rt.to_us(t_fwd0),
                         (t_fwd1 - t_fwd0) * 1e6,
                         bucket=bucket, batch_n=n)
                rt.stage(r.trace, "depad", rt.to_us(t_fwd1),
                         (now - t_fwd1) * 1e6)
            for i, r in enumerate(reqs):
                sel = ([f for f in fields if f[0] in r.outputs]
                       if r.outputs else default)
                r.future.set_result({k: (v[i] if per_row else v)
                                     for k, v, per_row in sel})
                self.latency.add(now - r.t_enqueue)
            self.requests_ok += n
            self._c_requests.inc(n, model=self.model_name, outcome="ok")
        except Exception as e:
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            self.requests_failed += n
            self._c_requests.inc(n, model=self.model_name,
                                 outcome="failed")
            self._log(f"serve: batch of {n} failed: {e}")
        self._images += n
        self.fill.add(n, bucket)
        self.batch_log.append((n, bucket))
        if len(self.batch_log) > 10000:
            del self.batch_log[:5000]
        if self.cfg.metrics_every_batches and self.log is not None and \
                self.fill.batches % self.cfg.metrics_every_batches == 0:
            self._log_metrics_row()

    def _log_metrics_row(self) -> None:
        st = self.status()
        self.log.metrics(self.fill.batches, model=self.model_name,
                         # cumulative; offline readers (sparknet-metrics,
                         # --buckets-from) take the LAST row per model
                         batch_size_hist=st["batch_size_hist"], **{
                             k: v for k, v in st.items()
                             if isinstance(v, (int, float))
                             and v is not None})

    def _log(self, msg: str) -> None:
        if self.log is not None:
            self.log.log(msg)

    # -- status HTTP (shared obs.StatusServer) -------------------------------

    @property
    def status_address(self) -> Optional[Tuple[str, int]]:
        """(host, port) of the status HTTP server, once started."""
        return None if self._http is None else self._http.address

    def _start_http(self, port: int) -> None:
        # the SAME server class the training process runs: /metrics is
        # Prometheus text from the shared registry (one metric-name
        # schema for both roles); the old JSON vitals live at /status
        self._http = StatusServer(
            port, self.registry, host=self.cfg.status_host,
            healthz=lambda: (self.healthy(),
                             {"model_step": self.manager.step,
                              "queue_depth": self.batcher.depth()}),
            status=self.status)
        self._log(f"serve: status at http://{self._http.address[0]}:"
                  f"{self._http.address[1]}/healthz")
