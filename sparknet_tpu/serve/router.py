"""Multi-model serving: one ModelManager + forward lane per model over a
shared worker pool, with health-aware replica routing.

The single-model `InferenceServer` owns one net and one worker thread.
Serving a fleet of models that way costs one idle thread (and one idle
accelerator context) per cold model; the router instead owns N LANES
(each an `InferenceServer` started with `thread=False` — batcher +
ModelManager + bucket-compiled forwards, but no thread) and K POOL
threads that drive whichever lanes have queued work. Exactly one pool
thread drives a lane at a time (`lane_lock`), preserving the lane's
single-writer params contract: a hot swap still never interleaves with a
forward. All lanes share ONE MetricsRegistry; the `model` label keeps
their families apart, so `/metrics` is one exposition for the whole
router and `sparknet-podview` can attribute per-model stragglers.

REPLICAS: each model maps to a replica set — the local lane and/or
remote replicas (other pod workers' HTTP frontends, discovered from the
same /pod/status + heartbeat plumbing the pod aggregator runs on).
Routing is round-robin over HEALTHY replicas, where healthy means: not
draining (an operator `drain()` or a stale heartbeat — the shared
`stale_after_s` rule), and not in hot-swap cooldown (a replica that just
REJECTED a checkpoint gets `swap_cooldown_s` of reduced load while the
bad-step dust settles). Draining only gates NEW routing: everything
already queued on a replica is served to completion, so a drain drops
zero in-flight responses (the chaos bar). When no replica is healthy the
router degrades in order: any non-draining replica (serve stale rather
than refuse), then `NoReplicaError` — which the HTTP frontend maps to
503 + Retry-After, never a hang.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future, InvalidStateError, ThreadPoolExecutor
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import MetricsRegistry, StatusServer, register_build_info
from ..obs import reqtrace
from ..utils.heartbeat import HeartbeatWriter, read_heartbeat, staleness_s
from ..utils.logger import Logger
from ..utils.metrics import LatencyStats
from .batcher import RequestCancelledError
from .server import InferenceServer, ServeConfig


class UnknownModelError(KeyError):
    """Request names a model this router does not serve (HTTP 404)."""


class NoReplicaError(RuntimeError):
    """Every replica of the model is draining or dead (HTTP 503 +
    Retry-After — load shedding, never a hang)."""


def heartbeat_health(path: str, stale_after_s: float = 60.0,
                     min_refresh_s: float = 1.0) -> Callable[[], bool]:
    """A replica health probe over the pod heartbeat plumbing: fresh beat
    with a non-terminal status == healthy. Reads are cached
    `min_refresh_s` so a busy router doesn't hammer the file/bucket."""
    state = {"t": 0.0, "ok": False}
    lock = threading.Lock()

    def probe() -> bool:
        with lock:
            now = time.monotonic()
            if now - state["t"] >= min_refresh_s:
                hb = read_heartbeat(path)
                age = staleness_s(hb)
                state["ok"] = bool(
                    hb is not None and hb.get("status") != "done"
                    and age is not None and age <= stale_after_s)
                state["t"] = now
            return state["ok"]
    return probe


def heartbeat_fill(path: str, model: str, min_refresh_s: float = 1.0
                   ) -> Callable[[], Optional[float]]:
    """A replica batch-fill probe over the same heartbeat rows health
    rides on: reads `models[model].recent_occupancy` (falling back to
    `batch_fill` for older replicas) from the beat, cached
    `min_refresh_s` — the coalescing trigger's remote signal."""
    state = {"t": 0.0, "fill": None}
    lock = threading.Lock()

    def probe() -> Optional[float]:
        with lock:
            now = time.monotonic()
            if now - state["t"] >= min_refresh_s:
                hb = read_heartbeat(path)
                row = ((hb or {}).get("models") or {}).get(model) or {}
                # prefer the occupancy signal (capacity-relative, what
                # coalescing improves); older replicas only beat the
                # bucket-relative cumulative fill
                fill = row.get("recent_occupancy")
                if fill is None:
                    fill = row.get("batch_fill")
                state["fill"] = float(fill) if fill is not None else None
                state["t"] = now
            return state["fill"]
    return probe


class Replica:
    """One serving copy of a model: the local lane, or a remote frontend
    address. `health_fn` (remote) answers "is it alive" — typically
    `heartbeat_health` over the replica's pod heartbeat. `transport`
    picks the remote wire: "http" (http_infer) or "binary" (the
    length-prefixed frame protocol via binary_infer — cross-replica
    proxy hops drop the npz/JSON re-encode tax)."""

    def __init__(self, name: str, lane: Optional[InferenceServer] = None,
                 url: Optional[str] = None,
                 health_fn: Optional[Callable[[], bool]] = None,
                 transport: str = "http",
                 fill_fn: Optional[Callable[[], Optional[float]]] = None):
        assert (lane is None) != (url is None), \
            "a replica is exactly one of: local lane, remote url"
        assert transport in ("http", "binary"), transport
        self.name = name
        self.lane = lane
        self.url = url.rstrip("/") if url else None
        self.transport = transport
        self.health_fn = health_fn
        # batch-fill signal for coalesced formation: local lanes read
        # their FillMeter's recent window; remotes read batch_fill out
        # of the same cached heartbeat rows health rides on. None =
        # no signal (this replica neither triggers nor vetoes)
        self.fill_fn = fill_fn
        self._draining = False
        self._fail_t = 0.0  # monotonic time of the last transport error

    def fill_signal(self) -> Optional[float]:
        """Recent batch occupancy in [0,1], or None with no signal."""
        if self.lane is not None:
            return self.lane.fill_signal()
        if self.fill_fn is not None:
            try:
                return self.fill_fn()
            except Exception:
                return None
        return None

    def note_failure(self) -> None:
        """A proxy hop to this replica just failed at the transport
        level (connection refused/reset). The router demotes it for
        `conn_fail_cooldown_s` — faster than the heartbeat can go
        stale — so a just-died replica stops eating round-robin turns
        within one failed request, not one staleness window."""
        self._fail_t = time.monotonic()

    def recently_failed(self, cooldown_s: float) -> bool:
        return (time.monotonic() - self._fail_t) < cooldown_s

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Stop routing NEW requests here; in-flight work still completes
        (a drain must drop zero responses)."""
        self._draining = True

    def undrain(self) -> None:
        self._draining = False

    def as_dict(self) -> Dict[str, Any]:
        return {"replica": self.name,
                "kind": "local" if self.lane is not None else "remote",
                "draining": self._draining,
                **({"url": self.url, "transport": self.transport}
                   if self.url else {})}


@dataclass
class RouterConfig:
    """Knobs for the multi-model router (the `sparknet-serve --models`
    CLI mirrors these)."""

    workers: int = 2                    # shared pool threads (initial;
    #                                     set_pool_size resizes live)
    # a replica that just REJECTED a checkpoint swap is deprioritized
    # for this long (its peers absorb the load while it settles)
    swap_cooldown_s: float = 3.0
    # staleness rule for remote-replica heartbeats (the same threshold
    # the pod aggregator and elastic controller use)
    stale_after_s: float = 60.0
    # heartbeat probe read-cache (heartbeat_health min_refresh_s): a
    # busy router must not hammer the file/bucket per request, but the
    # fleet tests/controller need sub-second demotion
    health_refresh_s: float = 1.0
    # a replica whose proxy hop just FAILED at the transport level is
    # demoted for this long (note_failure): the fast complement of the
    # heartbeat staleness rule
    conn_fail_cooldown_s: float = 1.0
    # -- request hedging (Dean & Barroso's tied requests, on the
    # pipelined wire): after an adaptive delay a still-unanswered
    # request is re-issued to a SECOND healthy replica; first answer
    # wins, the loser is cancelled best-effort (batcher removal locally,
    # a CANCEL frame remotely). Needs >= 2 replicas to do anything.
    hedge: bool = False
    # the adaptive delay: this quantile of the model's live windowed
    # routed latency (requests slower than p95 are, by construction, the
    # tail worth re-issuing), floored at hedge_min_delay_ms (also the
    # delay used before the window has any signal)
    hedge_quantile: float = 0.95
    hedge_window_s: float = 30.0
    hedge_min_delay_ms: float = 2.0
    # hedges are capped at this fraction of routed requests so hedging
    # can't melt an overloaded fleet — and they are disabled entirely
    # while admission pressure is nonzero (attach_admission): an
    # overload signal means extra copies are the LAST thing to add
    hedge_budget: float = 0.05
    # spkn-shm on binary proxy hops: None = the client's loopback
    # autodetect (shared-memory transport to colocated replicas, inline
    # to remote ones), True/False force it — the bench A/B arms pin the
    # transport per arm with this
    proxy_shm: Optional[bool] = None
    # -- coalesced batch formation: when every replica reporting a fill
    # signal shows recent fill below the threshold, route consecutive
    # requests to ONE focus replica per formation window (rotated per
    # window for fairness) instead of round-robin spraying a trickle
    # into N fragmented batches
    coalesce: bool = False
    coalesce_window_ms: float = 25.0
    coalesce_fill_threshold: float = 0.5
    # observability (shared across all lanes)
    status_port: Optional[int] = None   # None = no HTTP; 0 = ephemeral
    status_host: str = "127.0.0.1"
    heartbeat_path: Optional[str] = None
    heartbeat_every_s: float = 10.0
    registry: Optional[MetricsRegistry] = None
    # SLO ledger over the SHARED registry: one history sampler for every
    # lane (the /timeseries route), one burn-rate alerter holding a
    # SloSpec per lane that declares an objective (slo_p99_ms /
    # slo_availability on its ServeConfig) — /slo/status, the slo status
    # section, and the fleet controller's page escalation
    history: bool = False
    history_dir: Optional[str] = None
    history_interval_s: float = 1.0


class ModelRouter:
    """N model lanes + replica sets over K shared worker threads."""

    def __init__(self, cfg: Optional[RouterConfig] = None,
                 logger: Optional[Logger] = None):
        self.cfg = cfg = cfg if cfg is not None else RouterConfig()
        assert cfg.workers >= 1
        self.log = logger
        self.registry = cfg.registry or MetricsRegistry()
        register_build_info(self.registry)
        self.lanes: Dict[str, InferenceServer] = {}
        self.replicas: Dict[str, List[Replica]] = {}
        # round-robin state: index (into the FULL replica list) of the
        # last replica picked, per model. _pick scans forward from it,
        # skipping unroutable replicas — so a drained-then-undrained
        # replica deterministically re-enters the rotation at its own
        # position and resumes its fair share (a count-modulo over the
        # FILTERED list could park on a parity that starves a flapping
        # replica forever; tests pin both properties)
        self._rr: Dict[str, int] = {}
        self._rr_lock = threading.Lock()
        self._order: List[str] = []             # lane rotation order
        self._rot = 0
        self._wakeup = threading.Condition()
        # shared worker pool, resizable live (the fleet controller's
        # in-process lever): thread idx -> thread; a thread retires when
        # its idx >= _pool_target
        self._pool: Dict[int, threading.Thread] = {}
        self._pool_target = 0
        self._pool_lock = threading.Lock()
        # per-model END-TO-END latency from the router's vantage (submit
        # -> future resolution, local lane or remote proxy alike): the
        # fleet controller's SLO-burn signal must cover whichever
        # replica served, not just the local lane's forwards
        self.latency: Dict[str, LatencyStats] = {}
        # remote proxying must not block router callers: a small executor
        # carries the HTTP round-trips (bounded by pool size + margin)
        self._proxy: Optional[ThreadPoolExecutor] = None
        self._running = False
        self._http = None
        # SLO ledger handles (started with the router when cfg.history)
        self.history = None
        self.alerter = None
        self.fleet = None  # FleetController attaches here (attach_fleet)
        # PriorityAdmission attaches here (attach_admission): its
        # .pressure gates hedging — no extra copies under overload
        self.admission = None
        # hedging: pending (fire_t, seq, entry) heap drained by one
        # scheduler thread; per-model [routed, hedged] counts enforce
        # the budget
        self._hedge_heap: List[Any] = []
        self._hedge_cv = threading.Condition()
        self._hedge_seq = itertools.count()
        self._hedge_counts: Dict[str, List[int]] = {}
        self._hedge_thread: Optional[threading.Thread] = None
        # coalesced formation: per-model {"until", "focus", "active"}
        self._co: Dict[str, Dict[str, Any]] = {}
        self._co_lock = threading.Lock()
        self.heartbeat = (HeartbeatWriter(cfg.heartbeat_path, role="serve",
                                          interval_s=cfg.heartbeat_every_s,
                                          registry=self.registry)
                          if cfg.heartbeat_path else None)
        self._c_routed = self.registry.counter(
            "sparknet_serve_routed_total",
            "requests routed, by model and chosen replica",
            labels=("model", "replica"))
        self._c_drains = self.registry.counter(
            "sparknet_serve_replica_drains_total",
            "replica drain events", labels=("model", "replica"))
        self._g_healthy = self.registry.gauge(
            "sparknet_serve_replica_healthy",
            "1 = replica currently routable (not draining/stale/cooling)",
            labels=("model", "replica"))
        self._c_failovers = self.registry.counter(
            "sparknet_serve_replica_failovers_total",
            "proxy hops that failed at the transport level and were "
            "retried on another replica", labels=("model", "replica"))
        self._c_hedged = self.registry.counter(
            "sparknet_serve_hedged_total",
            "hedged requests by which leg answered first "
            "(won=primary|hedge)", labels=("model", "won"))
        self._c_hedge_cancelled = self.registry.counter(
            "sparknet_serve_hedge_cancelled_total",
            "hedge losers confirmed cancelled before forming into a "
            "batch (a cancel that lost the race is just dropped)",
            labels=("model",))
        self._c_coalesced = self.registry.counter(
            "sparknet_serve_coalesced_total",
            "requests routed by coalesced formation (focus replica "
            "instead of round-robin)", labels=("model",))
        self.registry.gauge(
            "sparknet_serve_pool_workers",
            "live shared-pool worker threads (set_pool_size resizes)"
        ).set_fn(self.pool_size)

    # -- assembly ------------------------------------------------------------

    def add_model(self, name: str, net,
                  cfg: Optional[ServeConfig] = None, preprocessor=None
                  ) -> InferenceServer:
        """Add a locally-served model: builds its lane (forced onto the
        router's shared registry, named `name`) and registers it as the
        model's first replica. Call before start()."""
        assert name not in self.lanes, f"model {name!r} already added"
        cfg = replace(cfg if cfg is not None else ServeConfig(),
                      model_name=name, registry=self.registry,
                      status_port=None, heartbeat_path=None)
        lane = InferenceServer(net, cfg, preprocessor=preprocessor,
                               logger=self.log)
        lane.batcher.on_submit = self._wake
        self.lanes[name] = lane
        self._order.append(name)
        self.replicas.setdefault(name, []).append(
            Replica(f"local:{name}", lane=lane))
        self._rr.setdefault(name, -1)
        self._ensure_latency(name)
        return lane

    def _ensure_latency(self, model: str) -> LatencyStats:
        if model not in self.latency:
            self.latency[model] = LatencyStats(
                registry=self.registry,
                name="sparknet_serve_routed_latency_seconds",
                model=model)
        return self.latency[model]

    def add_remote_replica(self, model: str, url: str,
                           health_fn: Optional[Callable[[], bool]] = None,
                           heartbeat_path: Optional[str] = None,
                           transport: Optional[str] = None
                           ) -> Replica:
        """Register another pod worker's frontend as a replica of
        `model`. `url` is an HTTP base URL, or `spkn://host:port` for
        the binary frame transport (`transport` overrides; the scheme
        decides otherwise). Health comes from `health_fn`, or from
        `heartbeat_path` through the shared staleness rule; with
        neither, the replica is trusted until drained."""
        fill_fn = None
        if health_fn is None and heartbeat_path is not None:
            health_fn = heartbeat_health(heartbeat_path,
                                         self.cfg.stale_after_s,
                                         self.cfg.health_refresh_s)
        if heartbeat_path is not None:
            fill_fn = heartbeat_fill(heartbeat_path, model,
                                     self.cfg.health_refresh_s)
        if transport is None:
            transport = "binary" if url.startswith("spkn://") else "http"
        rep = Replica(f"remote:{url}", url=url, health_fn=health_fn,
                      transport=transport, fill_fn=fill_fn)
        self.replicas.setdefault(model, []).append(rep)
        self._rr.setdefault(model, -1)
        self._ensure_latency(model)
        return rep

    def remove_replica(self, model: str, replica: str) -> Replica:
        """Unregister a replica (by name or url) — the fleet
        controller's retire step, AFTER a drain has gated new routing
        and the grace window let in-flight work finish. Raises
        UnknownModelError when nothing matches."""
        reps = self.replicas.get(model, [])
        for i, r in enumerate(reps):
            if r.name == replica or r.url == replica:
                if r.lane is not None:
                    raise ValueError(
                        f"{model}/{r.name}: the local lane cannot be "
                        f"removed (drain it instead)")
                del reps[i]
                if self.log is not None:
                    self.log.log(f"serve: removed replica "
                                 f"{model}/{r.name}")
                return r
        raise UnknownModelError(f"{model}/{replica}")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ModelRouter":
        assert not self._running, "already started"
        assert self.lanes or any(self.replicas.values()), "no models"
        self._running = True
        for lane in self.lanes.values():
            lane.start(thread=False)
        self._proxy = ThreadPoolExecutor(
            max_workers=max(4, 2 * self.cfg.workers),
            thread_name_prefix="serve-proxy")
        self.set_pool_size(self.cfg.workers)
        if self.cfg.hedge:
            self._hedge_thread = threading.Thread(
                target=self._hedge_run, name="serve-hedge", daemon=True)
            self._hedge_thread.start()
        if self.cfg.status_port is not None:
            self._http = StatusServer(
                self.cfg.status_port, self.registry,
                host=self.cfg.status_host,
                healthz=self._healthz, status=self.status,
                routes={"/fleet/status": self._fleet_status})
        if self.cfg.history:
            self._start_history()
        return self

    def _start_history(self) -> None:
        """One SLO ledger for the whole router: the shared registry's
        `model` labels keep lanes apart, so a single history + alerter
        covers every lane (specs from each lane's declared objectives)."""
        from ..obs.history import HistoryConfig, MetricsHistory
        from ..obs.slo import SloSpec, BurnRateAlerter
        self.history = MetricsHistory(
            self.registry,
            HistoryConfig(sample_interval_s=self.cfg.history_interval_s,
                          persist_dir=self.cfg.history_dir),
            logger=self.log)
        specs = []
        for name, lane in sorted(self.lanes.items()):
            if lane.cfg.slo_spec is not None:
                specs.append(lane.cfg.slo_spec)
            elif lane.cfg.slo_p99_ms is not None or \
                    lane.cfg.slo_availability is not None:
                specs.append(SloSpec(
                    model=name, latency_ms=lane.cfg.slo_p99_ms,
                    availability=lane.cfg.slo_availability))
        if specs:
            self.alerter = BurnRateAlerter(self.history, specs,
                                           logger=self.log).attach()
            for name in (s.model for s in specs):
                lane = self.lanes.get(name)
                if lane is not None:
                    lane.alerter = self.alerter  # model_row slo fields
        if self._http is not None:
            self.history.attach_http(self._http)
            if self.alerter is not None:
                self.alerter.attach_http(self._http)
        self.history.start()

    def attach_fleet(self, controller) -> None:
        """Bind a FleetController: /fleet/status starts answering with
        its view (the route itself is always registered)."""
        self.fleet = controller

    def attach_admission(self, admission) -> None:
        """Bind the PriorityAdmission whose `.pressure` gates hedging:
        under any admission pressure the fleet is already shedding, and
        a hedge is an extra copy of exactly the load being shed."""
        self.admission = admission

    def _pressure(self) -> float:
        adm = self.admission
        try:
            return float(getattr(adm, "pressure", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def _fleet_status(self) -> Dict[str, Any]:
        if self.fleet is None:
            return {"enabled": False}
        return self.fleet.status()

    # -- pool sizing (the fleet controller's in-process lever) ---------------

    def pool_size(self) -> int:
        return sum(t.is_alive() for t in self._pool.values())

    def set_pool_size(self, n: int) -> int:
        """Resize the shared worker pool LIVE, within [1, ...]. Growth
        spawns threads immediately; shrink is cooperative — a thread
        whose idx falls past the target retires at its next sweep (mid-
        forward work always completes; a shrink never drops a batch).
        Returns the new target."""
        n = max(1, int(n))
        with self._pool_lock:
            self._pool_target = n
            if self._running:
                for i in range(n):
                    t = self._pool.get(i)
                    if t is None or not t.is_alive():
                        t = threading.Thread(target=self._pool_run,
                                             args=(i,),
                                             name=f"serve-pool-{i}",
                                             daemon=True)
                        self._pool[i] = t
                        t.start()
        self._wake()  # retiring threads notice the new target promptly
        return n

    def stop(self, drain_s: float = 5.0) -> None:
        """Drain queued work (bounded), then stop lanes and the pool."""
        deadline = time.monotonic() + drain_s
        while any(l.batcher.depth() for l in self.lanes.values()) and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        self._running = False
        for lane in self.lanes.values():
            lane._running = False
            lane.batcher.close()
        with self._wakeup:
            self._wakeup.notify_all()
        with self._hedge_cv:
            self._hedge_cv.notify_all()
        if self._hedge_thread is not None:
            self._hedge_thread.join(timeout=2.0)
            self._hedge_thread = None
        with self._pool_lock:
            # snapshot under the lock: a racing set_pool_size (a fleet
            # controller not yet stopped) must not mutate the dict
            # mid-iteration
            pool, self._pool = list(self._pool.values()), {}
        for t in pool:
            t.join(timeout=max(drain_s, 1.0))
        if self._proxy is not None:
            self._proxy.shutdown(wait=False)
            self._proxy = None
        if self.history is not None:
            self.history.stop()
            self.history = None
            self.alerter = None
        if self._http is not None:
            self._http.stop()
            self._http = None
        if self.heartbeat is not None:
            try:
                self.heartbeat.beat(self._max_step(), status="done",
                                    rollbacks=self._swap_failures(),
                                    force=True,
                                    models=self._model_rows())
            except OSError:
                pass

    def __enter__(self) -> "ModelRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- routing -------------------------------------------------------------

    def _replica_routable(self, rep: Replica) -> bool:
        if rep.draining:
            return False
        if rep.lane is not None:
            return rep.lane._running and not \
                rep.lane.manager.swap_cooldown_active(
                    self.cfg.swap_cooldown_s)
        if rep.recently_failed(self.cfg.conn_fail_cooldown_s):
            return False  # transport just refused/reset: demote fast
        if rep.health_fn is not None:
            try:
                return bool(rep.health_fn())
            except Exception:
                return False  # a broken probe reads as unhealthy
        return True

    def _update_replica_gauges(self) -> None:
        """Refresh sparknet_serve_replica_healthy for EVERY replica —
        called from the pool's duty cadence, not per request: gauge
        writes stay off the routing hot path, and idle models' replicas
        still report fresh health. (Not a scrape-time set_fn: a remote
        health probe may read a heartbeat file/bucket, which must never
        run under the registry lock.)"""
        for model, reps in self.replicas.items():
            for r in reps:
                self._g_healthy.set(
                    1.0 if self._replica_routable(r) else 0.0,
                    model=model, replica=r.name)

    def _pick(self, model: str,
              exclude: Optional[Replica] = None) -> Replica:
        """Next replica by deterministic rotation: scan the FULL replica
        list forward from the last pick, skipping unroutable entries —
        each routable replica gets consecutive turns regardless of how
        the routable subset flaps between picks (a count-modulo over the
        filtered list can alias against a flapping replica's phase and
        starve it; regression-tested). `exclude` skips one replica (the
        failover retry must not re-pick the replica that just refused)."""
        reps = self.replicas.get(model)
        if not reps:
            raise UnknownModelError(model)
        reps = list(reps)  # snapshot: the fleet controller may
        #                    add/remove replicas concurrently

        def scan(ok) -> Optional[Replica]:
            # probes FIRST, lock SECOND: a heartbeat health_fn may read
            # a file or a gs:// object — that I/O must never run under
            # the shared rotation lock, or one stalling replica's probe
            # serializes routing for every model
            flags = [r is not exclude and ok(r) for r in reps]
            if not any(flags):
                return None
            with self._rr_lock:
                start = self._rr.get(model, -1)
                n = len(reps)
                for i in range(1, n + 1):
                    j = (start + i) % n
                    if flags[j]:
                        self._rr[model] = j
                        return reps[j]
            return None

        if self.cfg.coalesce and exclude is None and len(reps) > 1:
            rep = self._coalesce_pick(model, reps)
            if rep is not None:
                self._c_coalesced.inc(model=model)
                return rep

        rep = scan(self._replica_routable)
        if rep is None:
            # degrade before refusing: a cooling-down or stale-beat
            # replica that is NOT draining may still answer (freshness
            # degrades, availability does not)
            rep = scan(lambda r: not r.draining
                       and (r.lane is None or r.lane._running))
        if rep is None:
            raise NoReplicaError(
                f"model {model!r}: every replica is draining or down")
        return rep

    def _coalesce_pick(self, model: str,
                       reps: List[Replica]) -> Optional[Replica]:
        """Coalesced formation: when every replica REPORTING a fill
        signal shows recent fill under the threshold (and at least one
        reports), consecutive requests inside one formation window all
        go to a single FOCUS replica — a trickle that round-robin would
        fragment into N under-filled batches forms one fuller batch
        instead. The focus rotates to the next routable replica every
        window, so over W windows each replica leads ~W/n of them
        (fairness; pinned in tests). Returns None when coalescing is
        inactive this window — the caller falls through to round-robin."""
        now = time.monotonic()
        with self._co_lock:
            st = self._co.setdefault(
                model, {"until": 0.0, "focus": -1, "active": False})
            if now >= st["until"]:
                st["until"] = now + self.cfg.coalesce_window_ms / 1e3
                fills = [f for f in (r.fill_signal() for r in reps)
                         if f is not None]
                st["active"] = bool(fills) and all(
                    f < self.cfg.coalesce_fill_threshold for f in fills)
                if st["active"]:
                    # rotate focus to the NEXT routable replica (probe
                    # outside any hot lock is the _pick rule; this lock
                    # is coalescing-private and probes are cached)
                    n = len(reps)
                    for i in range(1, n + 1):
                        j = (st["focus"] + i) % n
                        if self._replica_routable(reps[j]):
                            st["focus"] = j
                            break
                    else:
                        st["active"] = False
            if not st["active"]:
                return None
            rep = reps[st["focus"] % len(reps)]
        # re-check outside the window decision: a focus replica that
        # went unroutable MID-window falls back to round-robin rather
        # than eating requests it cannot serve
        return rep if self._replica_routable(rep) else None

    def _issue(self, rep: Replica, model: str, payload: Dict[str, Any],
               deadline_s: Optional[float],
               priority: Optional[str] = None, trace=None
               ) -> Tuple[Future, Callable[[], None]]:
        """Issue one request LEG on a specific replica -> (future,
        cancel_fn). cancel_fn is best-effort and idempotent: locally it
        pulls the request out of the lane's batcher queue (a no-op once
        it formed into a batch); remotely over the binary wire it sends
        a CANCEL frame on the leg's request id (http legs have no cancel
        — the loser just completes unobserved). A confirmed cancel
        resolves the leg future with RequestCancelledError either way,
        which is what the hedge accounting counts."""
        if rep.lane is not None:
            fut = rep.lane.submit(payload, deadline_s=deadline_s,
                                  priority=priority, trace=trace)
            lane = rep.lane
            return fut, (lambda: (lane.batcher.cancel(fut), None)[1])
        proxy = self._proxy
        if proxy is None or not self._running:
            # racing stop() (or called before start): a typed shed,
            # not an AttributeError surfacing as a 500
            raise NoReplicaError(
                f"model {model!r}: router is not running")
        fut = Future()
        cancel_box: Dict[str, Any] = {}
        proxy.submit(self._proxy_call, rep, model, payload,
                     deadline_s, fut, False, cancel_box, priority, trace)

        def cancel() -> None:
            fn = cancel_box.get("cancel")
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass  # best-effort: a dead socket drops the cancel
        return fut, cancel

    def submit(self, model: str, payload: Dict[str, Any],
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None,
               _exclude: Optional[Replica] = None,
               trace=None) -> Future:
        """Route one request; returns its response future. Raises
        UnknownModelError / NoReplicaError synchronously; QueueFullError
        propagates from the chosen local lane (backpressure is
        per-replica — the caller may retry, which re-routes). Served
        requests feed the per-model `self.latency` window (the fleet
        controller's SLO-burn signal) whichever replica answered.

        With hedging enabled (and >= 2 replicas, no admission pressure)
        the returned future is an OUTER future: if the primary leg has
        not answered within the adaptive delay, a second leg is issued
        to another replica and the first answer wins — the loser's
        cancel is best-effort and exactly-once delivery is the outer
        future's first-resolution-wins."""
        rep = self._pick(model, exclude=_exclude)
        self._c_routed.inc(model=model, replica=rep.name)
        # trace context: a router fronted directly (no HTTP/binary front
        # door, e.g. sparknet-batch or embedding use) MINTS the context
        # and owns the request record; when a frontend minted it, the
        # router is a pass-through hop and must NOT start a second
        # record (record owner = minter — one request row per process
        # per request)
        rt = reqtrace.active()
        ctx = (reqtrace.parse_context(trace) if trace is not None
               else None)
        rec = None
        if rt is not None and ctx is None:
            ctx = rt.mint()
            rec = rt.begin(ctx, transport="router", model=model)
        hedging = (self.cfg.hedge and _exclude is None
                   and (priority or "normal").lower() != "low"
                   and len(self.replicas.get(model, ())) >= 2)
        # each LEG gets a child context (fresh span id, same trace id):
        # the wire span a leg emits then matches exactly one server-side
        # record, so assembly tells hedge duplicates apart. The leg tag
        # is only set when hedging can engage — a plain child otherwise.
        leg = (ctx.child(leg="primary") if hedging
               else ctx.child()) if ctx is not None else None
        fut, cancel = self._issue(rep, model, payload, deadline_s,
                                  priority, trace=leg)
        ret = fut
        # low-priority (scavenger/batch) requests never hedge: a hedge
        # duplicates exactly the load the admission stack exists to
        # shed, and a scavenger's tail is free to be long
        if hedging:
            counts = self._hedge_counts.setdefault(model, [0, 0])
            counts[0] += 1
            ret = self._hedge_arm(model, payload, deadline_s, rep,
                                  fut, cancel, priority, trace=ctx)
        t0 = time.perf_counter()
        lat = self._ensure_latency(model)

        def _done(f: Future) -> None:
            exc = f.exception()
            if exc is None:
                lat.add(time.perf_counter() - t0)
            if rec is not None:
                rt.finish_exc(rec, exc) if exc is not None \
                    else rt.finish(rec, "ok")
        ret.add_done_callback(_done)
        return ret

    # -- hedging (tail-at-scale tied requests) --------------------------------

    def _hedge_arm(self, model: str, payload: Dict[str, Any],
                   deadline_s: Optional[float], rep: Replica,
                   fut: Future, cancel: Callable[[], None],
                   priority: Optional[str] = None,
                   trace=None) -> Future:
        """Wrap the primary leg in an OUTER future and schedule the
        hedge decision. At fire time (adaptive delay past submit) an
        unanswered request gets a second leg on another replica; the
        first leg to complete resolves the outer future (exactly-once:
        the winner is chosen under one lock) and the loser is cancelled
        best-effort. The loser's confirmed cancellation — its future
        resolving with RequestCancelledError — feeds
        hedge_cancelled_total; a cancel that lost the race to batch
        formation just means two computed answers, one delivered."""
        outer: Future = Future()
        lock = threading.Lock()
        state: Dict[str, Any] = {"won": None, "hedged": False}
        cancels: Dict[str, Optional[Callable[[], None]]] = {
            "primary": cancel, "hedge": None}

        def leg_done(which: str, f: Future) -> None:
            loser_cancel = None
            with lock:
                won = state["won"] is None
                if won:
                    state["won"] = which
                    other = "hedge" if which == "primary" else "primary"
                    loser_cancel = cancels.get(other)
                hedged = state["hedged"]
            if not won:
                # the losing leg: meter a CONFIRMED cancellation
                if isinstance(f.exception(), RequestCancelledError):
                    self._c_hedge_cancelled.inc(model=model)
                return
            self._chain_once(f, outer)
            if hedged:
                self._c_hedged.inc(model=model, won=which)
            if loser_cancel is not None:
                loser_cancel()

        fut.add_done_callback(lambda f: leg_done("primary", f))

        def fire() -> None:
            if outer.done() or not self._running:
                return
            if self._pressure() > 0:
                return  # the fleet is shedding: no extra copies
            counts = self._hedge_counts.setdefault(model, [0, 0])
            if counts[1] + 1 > self.cfg.hedge_budget * counts[0]:
                return  # budget-capped: hedges can't melt the fleet
            try:
                rep2 = self._pick(model, exclude=rep)
            except Exception:
                return  # hedge target draining/down: primary stands alone
            try:
                # the hedge leg's child context is tagged leg=hedge —
                # the trace shows exactly which copy of the work each
                # span belongs to, and which leg won
                leg2 = (trace.child(leg="hedge")
                        if trace is not None else None)
                fut2, cancel2 = self._issue(rep2, model, payload,
                                            deadline_s, priority,
                                            trace=leg2)
            except Exception:
                return  # a refused hedge leg must never hurt the primary
            counts[1] += 1
            self._c_routed.inc(model=model, replica=rep2.name)
            with lock:
                state["hedged"] = True
                cancels["hedge"] = cancel2
                won = state["won"]
            if won is not None:
                cancel2()  # primary won while the leg was being issued
            fut2.add_done_callback(lambda f: leg_done("hedge", f))

        lat = self._ensure_latency(model)
        delay = lat.windowed_quantile(self.cfg.hedge_quantile,
                                      self.cfg.hedge_window_s)
        delay = max(delay or 0.0, self.cfg.hedge_min_delay_ms / 1e3)
        self._hedge_schedule(time.monotonic() + delay, fire)
        return outer

    def _hedge_schedule(self, fire_t: float,
                        fn: Callable[[], None]) -> None:
        with self._hedge_cv:
            heapq.heappush(self._hedge_heap,
                           (fire_t, next(self._hedge_seq), fn))
            self._hedge_cv.notify()

    def _hedge_run(self) -> None:
        """The one scheduler thread: pops due hedge decisions off the
        time heap. Decisions are cheap (a pick + an issue), so one
        thread keeps up with any request rate the pool itself survives."""
        while True:
            with self._hedge_cv:
                if not self._running:
                    return
                now = time.monotonic()
                if not self._hedge_heap:
                    self._hedge_cv.wait(timeout=0.5)
                    continue
                fire_t = self._hedge_heap[0][0]
                if fire_t > now:
                    self._hedge_cv.wait(timeout=min(fire_t - now, 0.5))
                    continue
                _, _, fn = heapq.heappop(self._hedge_heap)
            try:
                fn()
            except Exception:
                pass  # a failed hedge decision never takes routing down

    @staticmethod
    def _chain_once(src: Future, dst: Future) -> None:
        """_chain, tolerant of a concurrently-resolved destination (the
        hedging first-wins path)."""
        try:
            exc = src.exception()
            if exc is not None:
                dst.set_exception(exc)
            else:
                dst.set_result(src.result())
        except InvalidStateError:
            pass

    def infer(self, model: str, payload: Dict[str, Any],
              timeout: float = 30.0) -> Dict[str, Any]:
        """The timeout IS the request deadline (InferenceServer.infer
        semantics): the wait gets a small grace past it so the shed
        lands as its honest DeadlineExpiredError — the batcher (or a
        remote replica's 503) resolves the future moments after expiry,
        and a bare futures TimeoutError still bounds a wedged worker."""
        fut = self.submit(model, payload, deadline_s=timeout)
        return fut.result(timeout=timeout + 5.0)

    def _proxy_call(self, rep: Replica, model: str,
                    payload: Dict[str, Any],
                    deadline_s: Optional[float], fut: Future,
                    retried: bool = False,
                    cancel_box: Optional[Dict[str, Any]] = None,
                    priority: Optional[str] = None,
                    trace=None) -> None:
        try:
            if rep.transport == "binary":
                from .binary_frontend import binary_infer  # cycle guard
                out = binary_infer(rep.url, model, payload,
                                   deadline_s=deadline_s,
                                   priority=priority,
                                   cancel_box=cancel_box,
                                   use_shm=self.cfg.proxy_shm,
                                   trace=trace)
            else:
                from .http_frontend import http_infer  # cycle guard
                out = http_infer(rep.url, model, payload,
                                 deadline_s=deadline_s,
                                 priority=priority, trace=trace)
            fut.set_result(out)
        except RequestCancelledError as e:
            fut.set_exception(e)  # a hedge loser's confirmed cancel —
            #                       never a failover (nothing failed)
        except ConnectionError as e:
            # the replica refused/reset at the transport level (a kill
            # -9'd process does this long before its heartbeat goes
            # stale): demote it and fail the request OVER to another
            # replica, once — the detection window of a dying replica
            # costs a retry, not a dropped response. (Timeouts do NOT
            # failover: a slow server already did the work.)
            rep.note_failure()
            if retried or not self._running:
                fut.set_exception(e)
                return
            self._c_failovers.inc(model=model, replica=rep.name)
            try:
                rep2 = self._pick(model, exclude=rep)
            except Exception:
                fut.set_exception(e)  # nowhere to fail over to
                return
            self._c_routed.inc(model=model, replica=rep2.name)
            if rep2.lane is not None:
                try:
                    f2 = rep2.lane.submit(payload, deadline_s=deadline_s,
                                          priority=priority, trace=trace)
                except Exception as e2:
                    fut.set_exception(e2)
                    return
                f2.add_done_callback(lambda f: self._chain(f, fut))
            else:
                self._proxy_call(rep2, model, payload, deadline_s, fut,
                                 retried=True, cancel_box=cancel_box,
                                 priority=priority, trace=trace)
        except Exception as e:
            fut.set_exception(e)

    @staticmethod
    def _chain(src: Future, dst: Future) -> None:
        if dst.done():
            return
        exc = src.exception()
        if exc is not None:
            dst.set_exception(exc)
        else:
            dst.set_result(src.result())

    def drain(self, model: str, replica: str) -> Replica:
        """Operator drain by replica name (or bare 'local:<model>' /
        url). In-flight and already-queued work still completes."""
        for r in self.replicas.get(model, []):
            if r.name == replica or r.url == replica:
                r.drain()
                self._c_drains.inc(model=model, replica=r.name)
                if self.log is not None:
                    self.log.log(f"serve: draining {model}/{r.name}")
                return r
        raise UnknownModelError(f"{model}/{replica}")

    # -- the shared worker pool ----------------------------------------------

    def _wake(self) -> None:
        with self._wakeup:
            self._wakeup.notify_all()

    def _rotation(self) -> List[str]:
        """Lane order rotated per call: under contention every lane gets
        first-look equally often (no fixed-priority starvation)."""
        self._rot = (self._rot + 1) % max(len(self._order), 1)
        return self._order[self._rot:] + self._order[:self._rot]

    def _pool_run(self, idx: int = 0) -> None:
        duty = min([l._duty_s for l in self.lanes.values()] or [1.0])
        next_duty = 0.0
        while self._running and idx < self._pool_target:
            progressed = False
            for name in self._rotation():
                lane = self.lanes[name]
                if not lane.batcher.depth():
                    continue
                if not lane.lane_lock.acquire(blocking=False):
                    continue  # another pool thread is driving this lane
                try:
                    progressed |= bool(
                        lane.serve_tick(wake_at=time.perf_counter()))
                finally:
                    lane.lane_lock.release()
            # periodic duties run on their own TIME-GATED cadence, not
            # only on idle sweeps: under sustained traffic to one lane
            # the others must still hot-reload poll / tick liveness, and
            # the router heartbeat must keep beating (a busy router that
            # reads as dead gets drained by its peers — exactly wrong)
            now = time.monotonic()
            if now >= next_duty:
                next_duty = now + duty
                for name in self._rotation():
                    lane = self.lanes[name]
                    if lane.lane_lock.acquire(blocking=False):
                        try:
                            lane.duty_tick()
                        finally:
                            lane.lane_lock.release()
                self._update_replica_gauges()
                self._beat()
            if progressed:
                continue
            # no progress this sweep: park until a submit notifies or
            # the duty alarm. With queued work owned by ANOTHER pool
            # thread (its lane_lock held through the batch-open park and
            # forward), a short bounded nap paces the recheck — nothing
            # notifies on lock release, and spinning on try-acquire
            # would burn a core for the whole busy period
            with self._wakeup:
                if not self._running:
                    break
                busy = any(l.batcher.depth()
                           for l in self.lanes.values())
                self._wakeup.wait(timeout=0.002 if busy else duty)

    # -- status / heartbeat --------------------------------------------------

    def _max_step(self) -> int:
        steps = [l.manager.step for l in self.lanes.values()
                 if l.manager.step is not None]
        return max(steps) if steps else 0

    def _swap_failures(self) -> int:
        return sum(l.manager.swap_failures for l in self.lanes.values())

    def _model_rows(self) -> Dict[str, Any]:
        return {name: lane.model_row()
                for name, lane in self.lanes.items()}

    def _beat(self) -> None:
        if self.heartbeat is None:
            return
        degraded = any(l.manager.last_error for l in self.lanes.values())
        try:
            self.heartbeat.beat(self._max_step(),
                                status="degraded" if degraded else "ok",
                                rollbacks=self._swap_failures(),
                                models=self._model_rows())
        except OSError:
            pass  # observability must not take serving down

    def _healthz(self):
        ok = self._running and all(l.healthy()
                                   for l in self.lanes.values())
        return ok, {"models": sorted(self.lanes),
                    "queue_depth": {n: l.batcher.depth()
                                    for n, l in self.lanes.items()}}

    def healthy(self) -> bool:
        return self._healthz()[0]

    def status(self) -> Dict[str, Any]:
        """/status JSON: per-model lane vitals + replica sets. The
        `models` key is the same compact-row schema single-model servers
        emit, so /pod/status renders per-model rows either way."""
        out: Dict[str, Any] = {
            "role": "serve",
            "router": True,
            "pool_workers": self.pool_size(),
            "pool_target": self._pool_target,
            # train->serve staleness at a glance (full per-model rows —
            # model_step, step_lag — live under "models")
            "freshness_s": {n: l.manager.freshness_s()
                            for n, l in self.lanes.items()},
            "models": self._model_rows(),
            "lanes": {n: lane.status() for n, lane in self.lanes.items()},
            "replicas": {m: [r.as_dict() for r in reps]
                         for m, reps in self.replicas.items()},
            "routed_latency": {m: s.summary()
                               for m, s in self.latency.items()},
            "hedging": {m: {"routed": c[0], "hedged": c[1]}
                        for m, c in self._hedge_counts.items()},
            "autoscale": self.fleet is not None,
        }
        if self.alerter is not None:
            out["slo"] = self.alerter.summary()
        rt = reqtrace.active()
        if rt is not None:
            ex = rt.exemplars()
            if ex:
                out["slow_requests"] = ex
            out["reqtrace"] = rt.stats()
        return out

    @property
    def status_address(self):
        return None if self._http is None else self._http.address
