"""Per-tenant admission control: token buckets AHEAD of the queue.

The 429 path (QueueFullError backpressure) is capacity-fair, not
CLIENT-fair: one hot tenant can keep the queue at its cap and starve
every quiet tenant into 429s. This module sits in the frontends — HTTP
reads an `X-Tenant` header, the binary wire carries a tenant field in
the request frame — and answers the flood BEFORE it occupies queue
slots: each tenant owns a token bucket (`rate_rps` steady, `burst`
depth), and a request that finds its tenant's bucket empty is shed
typed (`tenant_limit`, HTTP 429 / binary error frame 429) and counted
on `sparknet_serve_shed_total{model,reason="tenant_limit"}` — the same
family the batcher's deadline sheds ride, so one scrape shows who is
shedding whom and why.

Requests with no tenant share the "" bucket (an anonymous flood must
not out-compete named tenants by dropping the header). The tracked-
tenant table is bounded: past `max_tenants`, the stalest bucket is
evicted — an eviction forgives at most one burst, it never grows
memory without bound under a tenant-id spray.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .batcher import QueueFullError


class TenantLimitError(QueueFullError):
    """This tenant's token bucket is empty — shed ahead of the queue
    (HTTP 429 / binary error frame, error_kind "tenant_limit"). A
    QueueFullError subclass: clients that already back off on 429 keep
    working unchanged."""


class _Bucket:
    __slots__ = ("tokens", "t")

    def __init__(self, tokens: float, t: float):
        self.tokens = tokens
        self.t = t


class TenantAdmission:
    """Token-bucket admission keyed on tenant id (header / frame field).

    `allow(tenant)` refills that tenant's bucket at `rate_rps` up to
    `burst`, then spends one token — False means shed. Thread-safe (the
    frontends call it from accept threads / io loops concurrently)."""

    def __init__(self, rate_rps: float, burst: Optional[float] = None,
                 max_tenants: int = 4096):
        if rate_rps <= 0:
            raise ValueError(f"tenant rate must be > 0 (got {rate_rps})")
        self.rate_rps = float(rate_rps)
        self.burst = float(burst if burst is not None
                           else max(2.0 * rate_rps, 1.0))
        if self.burst < 1.0:
            raise ValueError(f"burst must be >= 1 (got {self.burst})")
        self.max_tenants = int(max_tenants)
        self._buckets: Dict[str, _Bucket] = {}
        self._lock = threading.Lock()
        self.shed = 0  # lifetime tenant_limit sheds (all tenants)

    def allow(self, tenant: Optional[str]) -> bool:
        key = tenant or ""
        now = time.monotonic()
        with self._lock:
            # pop + reinsert keeps dict order == recency order, so
            # eviction is O(1) next(iter(...)) — a tenant-id SPRAY (the
            # attack max_tenants bounds) must not turn each allow()
            # into a full-table scan under the shared lock
            b = self._buckets.pop(key, None)
            if b is None:
                if len(self._buckets) >= self.max_tenants:
                    # evict the least-recently-seen bucket (bounded
                    # memory; the evictee regains at most one burst)
                    del self._buckets[next(iter(self._buckets))]
                b = _Bucket(self.burst, now)
            else:
                b.tokens = min(self.burst,
                               b.tokens + (now - b.t) * self.rate_rps)
                b.t = now
            self._buckets[key] = b
            if b.tokens >= 1.0:
                b.tokens -= 1.0
                return True
            self.shed += 1
            return False

    def snapshot(self) -> Dict[str, float]:
        """{tenant: tokens} — a consistent copy (status/debugging)."""
        with self._lock:
            return {k: b.tokens for k, b in self._buckets.items()}
