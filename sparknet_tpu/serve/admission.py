"""Per-tenant, priority-aware admission control AHEAD of the queue.

The 429 path (QueueFullError backpressure) is capacity-fair, not
CLIENT-fair: one hot tenant can keep the queue at its cap and starve
every quiet tenant into 429s. This module sits in the frontends — HTTP
reads `X-Tenant` / `X-Priority` headers, the binary wire carries tenant
and priority fields in the request frame — and answers the flood BEFORE
it occupies queue slots: each tenant owns a token bucket (`rate_rps`
steady, `burst` depth), and a request that finds its tenant's bucket
empty is shed typed (`tenant_limit`, HTTP 429 / binary error frame 429)
and counted on `sparknet_serve_shed_total{model,reason="tenant_limit"}`
— the same family the batcher's deadline sheds ride, so one scrape
shows who is shedding whom and why.

Requests with no tenant share the "" bucket (an anonymous flood must
not out-compete named tenants by dropping the header). The tracked-
tenant table is bounded: past `max_tenants`, the stalest bucket is
evicted — an eviction forgives at most one burst, it never grows
memory without bound under a tenant-id spray. An evicted tenant that
RETURNS gets a fresh full burst (its bucket is rebuilt at its own
burst depth), never a stale empty one.

`PriorityAdmission` is the fleet control plane's FAST lever
(fleet/controller.py sets `pressure` each tick from SLO burn): requests
carry a priority class (high / normal / low; unknown or absent reads as
normal), per-tenant budgets are WEIGHTED (`weights[tenant]` scales both
rate and burst), and under pressure the admission tightens dynamically
— low-priority traffic sheds FIRST (typed `priority`, counted as
`shed_total{reason="priority"}`) and every tenant's refill rate
throttles toward `rate_floor`, so the door closes smoothly from the
bottom of the priority ladder up while replicas (the slow lever) grow.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

from .batcher import QueueFullError

#: priority classes, most- to least-important. Requests name them via
#: the X-Priority header / the binary frame's priority field; anything
#: unrecognized (or absent) is "normal" — a typo'd class must degrade to
#: the default, never crash the door or jump the queue.
PRIORITIES = ("high", "normal", "low")

#: default pressure thresholds at which each class sheds ("priority"
#: reason): low gives way first, normal under sustained burn, high only
#: at the explicit cap (inf = never admission-shed by pressure; the
#: queue's own 429 still bounds it).
DEFAULT_SHED_AT = {"high": math.inf, "normal": 0.9, "low": 0.5}


def parse_priority(value: Optional[str]) -> str:
    """Header/frame string -> a canonical priority class name."""
    v = (value or "").strip().lower()
    return v if v in PRIORITIES else "normal"


class TenantLimitError(QueueFullError):
    """This tenant's token bucket is empty — shed ahead of the queue
    (HTTP 429 / binary error frame, error_kind "tenant_limit"). A
    QueueFullError subclass: clients that already back off on 429 keep
    working unchanged."""


class PriorityShedError(QueueFullError):
    """Shed by priority class under admission pressure (HTTP 429 /
    binary error frame, error_kind "priority"): the fleet controller
    tightened the door and this request's class is below the cutoff.
    Low-priority traffic gives way first; retrying after Retry-After
    (or re-submitting at a higher class) is the intended response."""


class _Bucket:
    __slots__ = ("tokens", "t")

    def __init__(self, tokens: float, t: float):
        self.tokens = tokens
        self.t = t


class TenantAdmission:
    """Token-bucket admission keyed on tenant id (header / frame field).

    `allow(tenant)` refills that tenant's bucket at `rate_rps` up to
    `burst`, then spends one token — False means shed. Thread-safe (the
    frontends call it from accept threads / io loops concurrently).
    `admit(tenant, priority)` is the uniform frontend surface: None
    when admitted, else the shed-reason string (`"tenant_limit"` here;
    the PriorityAdmission subclass adds `"priority"`). `rate_rps=None`
    disables tenant buckets entirely (the priority-only door)."""

    def __init__(self, rate_rps: Optional[float],
                 burst: Optional[float] = None,
                 max_tenants: int = 4096):
        if rate_rps is not None and rate_rps <= 0:
            raise ValueError(f"tenant rate must be > 0 (got {rate_rps})")
        self.rate_rps = None if rate_rps is None else float(rate_rps)
        self.burst = float(burst if burst is not None
                           else max(2.0 * (rate_rps or 0.0), 1.0))
        if self.burst < 1.0:
            raise ValueError(f"burst must be >= 1 (got {self.burst})")
        self.max_tenants = int(max_tenants)
        self._buckets: Dict[str, _Bucket] = {}
        self._lock = threading.Lock()
        self.shed = 0  # lifetime admission sheds (all tenants/reasons)

    # -- per-tenant knobs (PriorityAdmission overrides) ----------------------

    def _rate_for(self, key: str) -> float:
        """This tenant's CURRENT refill rate (tokens/sec)."""
        return self.rate_rps or 0.0

    def _burst_for(self, key: str) -> float:
        """This tenant's bucket depth. Every cap in allow() uses the
        PER-TENANT depth — a weighted tenant's refill must saturate at
        ITS burst, and a fresh (or evicted-then-returning) tenant's
        bucket starts at ITS full depth, not the base one."""
        return self.burst

    def allow(self, tenant: Optional[str]) -> bool:
        if self.rate_rps is None:
            return True  # no tenant budgets configured
        key = tenant or ""
        now = time.monotonic()
        with self._lock:
            # pop + reinsert keeps dict order == recency order, so
            # eviction is O(1) next(iter(...)) — a tenant-id SPRAY (the
            # attack max_tenants bounds) must not turn each allow()
            # into a full-table scan under the shared lock
            b = self._buckets.pop(key, None)
            if b is None:
                if len(self._buckets) >= self.max_tenants:
                    # evict the least-recently-seen bucket (bounded
                    # memory; the evictee regains at most one burst)
                    del self._buckets[next(iter(self._buckets))]
                b = _Bucket(self._burst_for(key), now)
            else:
                b.tokens = min(self._burst_for(key),
                               b.tokens + (now - b.t) * self._rate_for(key))
                b.t = now
            self._buckets[key] = b
            if b.tokens >= 1.0:
                b.tokens -= 1.0
                return True
            self.shed += 1
            return False

    def admit(self, tenant: Optional[str],
              priority: Optional[str] = None) -> Optional[str]:
        """None = admitted; else the shed reason ("tenant_limit").
        The base class ignores `priority` (no pressure machinery)."""
        return None if self.allow(tenant) else "tenant_limit"

    def tracked_tenants(self) -> int:
        with self._lock:
            return len(self._buckets)

    def snapshot(self) -> Dict[str, float]:
        """{tenant: tokens} — a consistent copy (status/debugging)."""
        with self._lock:
            return {k: b.tokens for k, b in self._buckets.items()}


class PriorityAdmission(TenantAdmission):
    """The fleet-aware door: priority classes + weighted tenant budgets
    + pressure-driven tightening (module doc).

    `pressure` is a dimensionless overload level in [0, 1] set by the
    fleet controller each tick (policy.pressure_from_burn maps SLO burn
    onto it; 0 with no controller attached — the class/weight machinery
    still works statically). Under pressure:

      - a request whose class's `shed_at` threshold is <= pressure is
        shed with reason "priority" BEFORE any bucket is touched (the
        cheapest possible no);
      - every tenant's refill rate is throttled by
        `max(rate_floor, 1 - tighten * pressure)` — the whole door
        narrows, not just the bottom class.

    `weights[tenant]` scales that tenant's rate AND burst (a weight-2
    tenant owns twice the steady rate and twice the depth); unknown
    tenants get `default_weight`."""

    def __init__(self, rate_rps: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_tenants: int = 4096,
                 weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0,
                 shed_at: Optional[Dict[str, float]] = None,
                 tighten: float = 0.8, rate_floor: float = 0.1):
        super().__init__(rate_rps, burst, max_tenants)
        self.weights = {str(k): float(v)
                        for k, v in (weights or {}).items()}
        if any(w <= 0 for w in self.weights.values()):
            raise ValueError(f"tenant weights must be > 0 "
                             f"(got {self.weights})")
        self.default_weight = float(default_weight)
        self.shed_at = dict(DEFAULT_SHED_AT)
        for k, v in (shed_at or {}).items():
            if k not in PRIORITIES:
                raise ValueError(f"unknown priority class {k!r} "
                                 f"(classes: {PRIORITIES})")
            self.shed_at[k] = float(v)
        if not 0.0 <= tighten <= 1.0:
            raise ValueError(f"tighten must be in [0, 1] (got {tighten})")
        if not 0.0 < rate_floor <= 1.0:
            raise ValueError(f"rate_floor must be in (0, 1] "
                             f"(got {rate_floor})")
        self.tighten = float(tighten)
        self.rate_floor = float(rate_floor)
        self.pressure = 0.0
        self.shed_priority = 0     # lifetime "priority" sheds
        self.shed_tenant_limit = 0
        # scavenger-starvation clock: monotonic time since the LAST
        # low-priority request was admitted while at least one has been
        # pressure-shed since — the fleet controller's batch_starvation_s
        # signal. None = low traffic is flowing (or none has been shed).
        self._low_starved_since: Optional[float] = None

    def set_pressure(self, p: float) -> None:
        """The fleet controller's fast lever (clamped to [0, 1])."""
        self.pressure = min(1.0, max(0.0, float(p)))

    def _weight(self, key: str) -> float:
        return self.weights.get(key, self.default_weight)

    def _rate_for(self, key: str) -> float:
        throttle = max(self.rate_floor,
                       1.0 - self.tighten * self.pressure)
        return (self.rate_rps or 0.0) * self._weight(key) * throttle

    def _burst_for(self, key: str) -> float:
        # depth scales with weight but NOT with pressure: tightening
        # slows the refill, it does not confiscate already-earned burst
        return self.burst * self._weight(key)

    def admit(self, tenant: Optional[str],
              priority: Optional[str] = None) -> Optional[str]:
        cls = parse_priority(priority)
        if self.pressure >= self.shed_at.get(cls, math.inf):
            with self._lock:
                self.shed += 1
                self.shed_priority += 1
                if cls == "low" and self._low_starved_since is None:
                    self._low_starved_since = time.monotonic()
            return "priority"
        if cls == "low":
            # a low request made it past the pressure gate: the
            # scavenger class is flowing again, whatever the tenant
            # bucket says next (tenant_limit is that tenant's own
            # budget, not class starvation)
            with self._lock:
                self._low_starved_since = None
        if self.rate_rps is None:
            return None
        if self.allow(tenant):
            return None
        with self._lock:
            self.shed_tenant_limit += 1
        return "tenant_limit"

    def starvation_s(self) -> float:
        """Seconds the "low" class has been continuously pressure-shed
        with nothing admitted — 0 while scavenger traffic flows. The
        fleet controller exports this as `batch_starvation_s` and
        relieves pressure when it exceeds the policy bound."""
        with self._lock:
            since = self._low_starved_since
        return 0.0 if since is None else max(0.0,
                                             time.monotonic() - since)

    def status(self) -> Dict[str, object]:
        """The /fleet/status admission row."""
        return {"pressure": round(self.pressure, 4),
                "rate_rps": self.rate_rps, "burst": self.burst,
                "tighten": self.tighten, "rate_floor": self.rate_floor,
                "shed_at": {k: (None if math.isinf(v) else v)
                            for k, v in self.shed_at.items()},
                "weights": dict(self.weights),
                "tracked_tenants": self.tracked_tenants(),
                "shed_priority": self.shed_priority,
                "shed_tenant_limit": self.shed_tenant_limit,
                "batch_starvation_s": round(self.starvation_s(), 3)}
