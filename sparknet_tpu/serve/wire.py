"""The binary frame protocol: length-prefixed tensor transport for the
serve data plane.

The HTTP/1.1 front door (http_frontend.py) pays a re-encode on every
tensor — JSON lists or an npz zip container built per request/response —
plus stdlib header parsing on both sides. Once the forward itself is
cheap (int8, r9) and batches fill perfectly (derived ladders, r9), that
per-request wire cost IS the serving cost. This protocol removes it:
a fixed 32-byte header, a compact tensor DESCRIPTOR TABLE
(name/dtype/shape/byte-offset/byte-length), and a payload of raw
row-major tensor bytes. Decoding a request is one `np.frombuffer` view
per input — zero parse, zero copy past the socket read.

Frame layout (everything little-endian)::

    offset  size  field
    0       4     magic      b"SPK1"
    4       1     version    3 (2 = pre-queue-wait RESPONSE meta,
                             1 = the pre-priority REQUEST meta)
    5       1     type       1=REQUEST 2=RESPONSE 3=ERROR 4=CHUNK
                             5=CANCEL 6=SHM_HELLO 7=SHM_RELEASE
    6       2     flags      bit0 STREAM, bit1 LAST (final chunk),
                             bit2 SHM (payload rides a shared-memory
                             segment named in the meta; zero payload
                             bytes follow on the socket)
    8       8     request_id client-chosen; replies carry it back
                             (pipelining: many ids in flight per
                             connection, replies in COMPLETION order)
    16      8     meta_len   bytes of the type-specific meta section
    24      8     payload_len raw tensor bytes after the meta section

followed by `meta_len` meta bytes and `payload_len` payload bytes.
The header carries both lengths, so a reader always knows exactly how
many bytes complete the frame (length-prefixed: no delimiters, no
chunked-encoding scan).

Meta sections (str8 = u8 length + utf-8 bytes; str16 = u16 length):

  REQUEST:  model str8 | tenant str8 | priority str8 ("" = normal;
            the admission priority class, serve/admission.py) |
            deadline_ms f64 (NaN = none) |
            trace str8 ("" = untraced: the encoded TraceContext —
            trace_id, span id, sampling flag, hedge-leg tag — see
            obs/reqtrace.py) |
            n_tensors u16 | descriptor* |
            [seg str8 — only with FLAG_SHM: the shared-memory segment
            holding the payload bytes the descriptors index into]
  RESPONSE: model str8 | step i64 (-1 = unknown) |
            queue_wait_ms f64 (NaN = unknown: time the request sat in
            the batcher queue before its forward started) |
            n_tensors u16 | descriptor* | [seg str8, as above]
            (with FLAG_STREAM: descriptors announce the full payload,
            which follows as CHUNK frames instead of inline bytes —
            payload_len in the RESPONSE header is the TOTAL streamed
            size, its own inline payload is empty)
  ERROR:    code u16 (the HTTP status analog) | kind str8 | msg str16
  CHUNK:    offset u64 into the logical response payload; the frame
            payload is that slice. FLAG_LAST marks the final chunk.
  CANCEL:   (empty meta) — best-effort cancel of the in-flight
            request_id. If the request is still queued it is shed with
            a typed `cancelled` (499) error frame; if it already formed
            into a batch the cancel is DROPPED and the normal response
            arrives — the client must tolerate either reply order.
  SHM_HELLO: client->server: nonce_path str16 | nonce str16 — the
            same-host proof (the server reads nonce_path and grants shm
            only if the contents match the nonce; a remote peer cannot
            read the client's filesystem). server->client: ok u8 —
            1 grants FLAG_SHM frames on this connection, 0 means
            inline payloads only (transparent fallback, not an error).
  SHM_RELEASE: seg str8 — receiver is done with this response segment;
            the sender's ring may reuse the slot. (Request segments
            need no release frame: the terminal reply for the rid IS
            the release.)

  descriptor: name str8 | dtype str8 (numpy dtype.str, e.g. "<f4") |
              ndim u8 | dim u32 * ndim | offset u64 | nbytes u64

Error frames mirror the HTTP error table one-for-one (same codes, same
`error_kind` strings), so `binary_infer` raises the SAME typed
exceptions `http_infer` does and the router's remote-replica proxy is
transport-blind. `request_id == 0` marks a CONNECTION-level error with
no associated request (bad magic/version, over capacity). An oversized
frame's error DOES carry the offending request_id (the header was
readable, so the requester can be told), but — like the rid-0 cases —
the server closes the connection after answering: it will not read its
way through an oversized frame to stay in sync (the binary analog of
HTTP's close-on-413). Either way, a `too_large`/`bad_magic`/
`bad_version` kind means this connection is done after the answer.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"SPK1"
# version 4: REQUEST meta grew the trace str8 field (between deadline_ms
# and the descriptor table) carrying the encoded distributed-trace
# context. The bump is what makes a rolling upgrade honest: a v3 peer
# gets the TYPED bad_version error frame instead of silently misparsing
# the trace bytes as a descriptor count.
# (version 3 grew the RESPONSE queue_wait_ms f64 + the CANCEL/SHM_HELLO/
# SHM_RELEASE frame types and FLAG_SHM; version 2 grew the REQUEST
# priority str8; same discipline each time.)
VERSION = 4
HEADER = struct.Struct("<4sBBHQQQ")
HEADER_LEN = HEADER.size  # 32

T_REQUEST, T_RESPONSE, T_ERROR, T_CHUNK = 1, 2, 3, 4
T_CANCEL, T_SHM_HELLO, T_SHM_RELEASE = 5, 6, 7

FLAG_STREAM = 1  # request: "stream my response"; response: "chunks follow"
FLAG_LAST = 2    # final CHUNK of a streamed response
FLAG_SHM = 4     # payload bytes live in the shm segment named in meta

# the HTTP error table, spelled for the binary wire: (code, kind)
ERR_BAD_REQUEST = (400, "bad_request")
ERR_BAD_MAGIC = (400, "bad_magic")
ERR_BAD_VERSION = (400, "bad_version")
ERR_UNKNOWN_MODEL = (404, "unknown_model")
ERR_TOO_LARGE = (413, "too_large")
ERR_QUEUE_FULL = (429, "queue_full")
ERR_TENANT_LIMIT = (429, "tenant_limit")
ERR_PRIORITY = (429, "priority")
ERR_CANCELLED = (499, "cancelled")
ERR_OVER_CAPACITY = (503, "over_capacity")
ERR_DEADLINE = (503, "deadline")
ERR_NO_REPLICA = (503, "no_replica")
ERR_TIMEOUT = (503, "timeout")
ERR_INTERNAL = (500, "internal")


class WireError(RuntimeError):
    """A protocol violation on the binary wire (bad magic/version,
    malformed meta, oversized frame). The side that detects it answers a
    typed error frame where possible, then closes the connection — one
    bad client never takes the server down."""


@dataclass(frozen=True)
class TensorDesc:
    """One row of the descriptor table."""

    name: str
    dtype: str          # numpy dtype.str ("<f4"), endianness explicit
    shape: Tuple[int, ...]
    offset: int         # byte offset into the frame payload
    nbytes: int


def _pack_str8(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 255:
        raise WireError(f"str8 field too long ({len(b)} bytes)")
    return bytes((len(b),)) + b


def _pack_str16(s: str) -> bytes:
    b = s.encode("utf-8")[:65535]
    return struct.pack("<H", len(b)) + b


class _Reader:
    """Sequential meta-section reader with bounds checks (malformed meta
    raises WireError, never an IndexError deep in struct)."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise WireError("truncated meta section")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.take(8))[0]

    def str8(self) -> str:
        return self.take(self.u8()).decode("utf-8")

    def str16(self) -> str:
        # str16 carries error MESSAGES, which the packer truncates at a
        # byte boundary — decode lossy so a clipped multibyte codepoint
        # degrades a character, never the typed error it rides in
        return self.take(self.u16()).decode("utf-8", "replace")


# -- descriptor table ---------------------------------------------------------

def as_bytes_view(arr: np.ndarray) -> memoryview:
    """A flat byte view of the array's buffer — ZERO COPY for contiguous
    arrays (the writer sends straight from the forward's output buffers;
    no serialized second copy of the blob ever exists)."""
    a = np.ascontiguousarray(arr)
    return memoryview(a).cast("B")


def build_table(arrays: Dict[str, np.ndarray]
                ) -> Tuple[List[TensorDesc], List[memoryview], int]:
    """(descriptors, payload byte views, total payload bytes) for a dict
    of tensors. Views are zero-copy; the payload on the wire is their
    concatenation in table order."""
    descs: List[TensorDesc] = []
    views: List[memoryview] = []
    off = 0
    for name, v in arrays.items():
        a = np.asarray(v)
        mv = as_bytes_view(a)
        descs.append(TensorDesc(str(name), a.dtype.str, tuple(a.shape),
                                off, len(mv)))
        views.append(mv)
        off += len(mv)
    return descs, views, off


def _pack_table(descs: Sequence[TensorDesc]) -> bytes:
    parts = [struct.pack("<H", len(descs))]
    for d in descs:
        parts.append(_pack_str8(d.name))
        parts.append(_pack_str8(d.dtype))
        parts.append(bytes((len(d.shape),)))
        parts.append(struct.pack(f"<{len(d.shape)}I", *d.shape)
                     if d.shape else b"")
        parts.append(struct.pack("<QQ", d.offset, d.nbytes))
    return b"".join(parts)


def _read_table(r: _Reader) -> List[TensorDesc]:
    n = r.u16()
    descs = []
    for _ in range(n):
        name = r.str8()
        dtype = r.str8()
        ndim = r.u8()
        shape = tuple(r.u32() for _ in range(ndim))
        offset, nbytes = r.u64(), r.u64()
        descs.append(TensorDesc(name, dtype, shape, offset, nbytes))
    return descs


def tensors_from(descs: Sequence[TensorDesc], payload
                 ) -> Dict[str, np.ndarray]:
    """Descriptor table + payload (bytes/bytearray/memoryview) ->
    {name: array}. One `np.frombuffer` VIEW per tensor (no parse, no
    copy — the zero-decode half of the protocol's reason to exist)."""
    out: Dict[str, np.ndarray] = {}
    for d in descs:
        if d.offset + d.nbytes > len(payload):
            raise WireError(
                f"tensor {d.name!r} overruns the payload "
                f"({d.offset}+{d.nbytes} > {len(payload)})")
        dt = np.dtype(d.dtype)
        count = d.nbytes // dt.itemsize if dt.itemsize else 0
        arr = np.frombuffer(payload, dtype=dt, count=count,
                            offset=d.offset)
        try:
            arr = arr.reshape(d.shape)
        except ValueError as e:
            raise WireError(f"tensor {d.name!r}: {e}") from e
        out[d.name] = arr
    return out


# -- frame packers ------------------------------------------------------------

def _header(ftype: int, flags: int, request_id: int, meta_len: int,
            payload_len: int) -> bytes:
    return HEADER.pack(MAGIC, VERSION, ftype, flags, request_id,
                       meta_len, payload_len)


def pack_request(request_id: int, model: str,
                 payload: Dict[str, np.ndarray],
                 deadline_ms: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: Optional[str] = None,
                 stream: bool = False,
                 shm_seg: Optional[str] = None,
                 trace: Optional[str] = None
                 ) -> Tuple[bytes, List[memoryview]]:
    """(header+meta bytes, payload byte views). The caller writes the
    bytes then each view — the tensors are never re-serialized. With
    `shm_seg` the caller has ALREADY copied the payload into that
    shared-memory segment (at the descriptors' offsets): the frame sets
    FLAG_SHM, names the segment in the meta, carries payload_len 0, and
    the returned view list is empty — zero tensor bytes on the socket."""
    descs, views, total = build_table(payload)
    flags = FLAG_STREAM if stream else 0
    tail = b""
    if shm_seg is not None:
        flags |= FLAG_SHM
        tail = _pack_str8(shm_seg)
        views, total = [], 0
    meta = b"".join((
        _pack_str8(model),
        _pack_str8(tenant or ""),
        _pack_str8(priority or ""),
        struct.pack("<d", float("nan") if deadline_ms is None
                    else float(deadline_ms)),
        _pack_str8(trace or ""),
        _pack_table(descs),
        tail))
    head = _header(T_REQUEST, flags, request_id, len(meta), total)
    return head + meta, views


def unpack_request_meta(meta: bytes
                        ) -> Tuple[str, str, str, Optional[float],
                                   Optional[str], List[TensorDesc],
                                   Optional[str]]:
    """-> (model, tenant, priority, deadline_ms, trace, descriptors,
    shm_seg). trace is None when the request is untraced ("" on the
    wire); shm_seg is None for inline payloads (no trailing segment
    name)."""
    r = _Reader(meta)
    model = r.str8()
    tenant = r.str8()
    priority = r.str8()
    deadline_ms = r.f64()
    if deadline_ms != deadline_ms:  # NaN
        deadline = None
    else:
        deadline = float(deadline_ms)
    trace = r.str8() or None
    descs = _read_table(r)
    seg = r.str8() if r.pos < len(meta) else None
    return model, tenant, priority, deadline, trace, descs, seg


def pack_response(request_id: int, model: str, step: Optional[int],
                  arrays: Dict[str, np.ndarray], stream: bool = False,
                  chunk_bytes: int = 256 << 10,
                  queue_wait_ms: Optional[float] = None,
                  shm_seg: Optional[str] = None
                  ) -> List[Tuple[bytes, Optional[memoryview]]]:
    """The response as a list of (copied header/meta bytes, optional
    zero-copy payload view) write items.

    Non-streamed: ONE frame — [(header+meta, None)] + one (b"", view)
    per tensor. Streamed: a RESPONSE frame announcing the table with
    payload_len = total, then CHUNK frames each carrying <= chunk_bytes
    of payload (FLAG_LAST on the final one). Either way the only COPIED
    bytes are the headers — per-connection buffering is bounded by the
    header size, never the blob size. With `shm_seg` (mutually
    exclusive with stream) the caller has already copied the payload
    into that segment: one FLAG_SHM frame, zero payload bytes on the
    socket."""
    descs, views, total = build_table(arrays)
    meta = b"".join((_pack_str8(model),
                     struct.pack("<q", -1 if step is None else int(step)),
                     struct.pack("<d", float("nan") if queue_wait_ms is
                                 None else float(queue_wait_ms)),
                     _pack_table(descs)))
    items: List[Tuple[bytes, Optional[memoryview]]] = []
    if shm_seg is not None:
        assert not stream, "shm responses are single-frame"
        meta += _pack_str8(shm_seg)
        items.append((_header(T_RESPONSE, FLAG_SHM, request_id,
                              len(meta), 0) + meta, None))
        return items
    if not stream:
        items.append((_header(T_RESPONSE, 0, request_id, len(meta),
                              total) + meta, None))
        for v in views:
            items.append((b"", v))
        return items
    items.append((_header(T_RESPONSE, FLAG_STREAM, request_id,
                          len(meta), total) + meta, None))
    chunk_bytes = max(int(chunk_bytes), 1)
    # chunk offsets run over the CONCATENATED payload; a chunk never
    # spans tensors (keeps the slicing trivial and the bound still holds)
    sent = 0
    for vi, v in enumerate(views):
        pos = 0
        while pos < len(v) or (len(v) == 0 and pos == 0):
            piece = v[pos:pos + chunk_bytes]
            pos += len(piece)
            sent += len(piece)
            last = (vi == len(views) - 1) and pos >= len(v)
            meta_c = struct.pack("<Q", sent - len(piece))
            items.append((_header(T_CHUNK, FLAG_LAST if last else 0,
                                  request_id, len(meta_c), len(piece))
                          + meta_c, piece))
            if len(v) == 0:
                break
    if not views:  # empty response still needs its LAST marker
        meta_c = struct.pack("<Q", 0)
        items.append((_header(T_CHUNK, FLAG_LAST, request_id,
                              len(meta_c), 0) + meta_c, None))
    return items


def unpack_response_meta(meta: bytes
                         ) -> Tuple[str, Optional[int], Optional[float],
                                    List[TensorDesc], Optional[str]]:
    """-> (model, step, queue_wait_ms, descriptors, shm_seg)."""
    r = _Reader(meta)
    model = r.str8()
    step = r.i64()
    qw = r.f64()
    queue_wait = None if qw != qw else float(qw)  # NaN = unknown
    descs = _read_table(r)
    seg = r.str8() if r.pos < len(meta) else None
    return model, (None if step < 0 else step), queue_wait, descs, seg


def pack_error(request_id: int, code_kind: Tuple[int, str],
               msg: str) -> bytes:
    code, kind = code_kind
    meta = struct.pack("<H", int(code)) + _pack_str8(kind) \
        + _pack_str16(msg)
    return _header(T_ERROR, 0, request_id, len(meta), 0) + meta


def unpack_error_meta(meta: bytes) -> Tuple[int, str, str]:
    r = _Reader(meta)
    return r.u16(), r.str8(), r.str16()


def unpack_chunk_meta(meta: bytes) -> int:
    return _Reader(meta).u64()


def pack_cancel(request_id: int) -> bytes:
    """Best-effort cancel of an in-flight request_id (empty meta). The
    hedging router sends this for the losing leg; a cancel that loses
    the race to batch formation is simply dropped server-side."""
    return _header(T_CANCEL, 0, request_id, 0, 0)


def pack_shm_hello(request_id: int, nonce_path: str, nonce: str) -> bytes:
    """Client->server shm capability offer. `nonce_path` names a file the
    CLIENT wrote containing `nonce`; a server that can read the matching
    bytes shares the client's filesystem — the same-host proof that
    makes granting named-segment access safe."""
    meta = _pack_str16(nonce_path) + _pack_str16(nonce)
    return _header(T_SHM_HELLO, 0, request_id, len(meta), 0) + meta


def unpack_shm_hello_meta(meta: bytes) -> Tuple[str, str]:
    r = _Reader(meta)
    return r.str16(), r.str16()


def pack_shm_hello_ack(request_id: int, ok: bool) -> bytes:
    """Server->client answer to SHM_HELLO: ok u8 (1 = FLAG_SHM granted
    on this connection, 0 = inline payloads only)."""
    meta = bytes((1 if ok else 0,))
    return _header(T_SHM_HELLO, FLAG_LAST, request_id, len(meta), 0) \
        + meta


def unpack_shm_hello_ack_meta(meta: bytes) -> bool:
    return _Reader(meta).u8() == 1


def pack_shm_release(seg: str) -> bytes:
    """Receiver->sender: done with this response segment, the ring slot
    may be reused. rid 0: releases are per-segment, not per-request."""
    meta = _pack_str8(seg)
    return _header(T_SHM_RELEASE, 0, 0, len(meta), 0) + meta


def unpack_shm_release_meta(meta: bytes) -> str:
    return _Reader(meta).str8()


def parse_header(buf) -> Tuple[int, int, int, int, int]:
    """First HEADER_LEN bytes -> (type, flags, request_id, meta_len,
    payload_len). Raises WireError (with the offending field named) on
    bad magic or version — the caller answers the typed error frame and
    closes."""
    magic, version, ftype, flags, req_id, meta_len, payload_len = \
        HEADER.unpack_from(bytes(buf[:HEADER_LEN]))
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != VERSION:
        raise WireError(f"unsupported wire version {version} "
                        f"(this server speaks {VERSION})")
    return ftype, flags, req_id, meta_len, payload_len
