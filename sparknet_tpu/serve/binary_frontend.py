"""The binary data plane: a `selectors` event-loop front door speaking
the length-prefixed frame protocol (serve/wire.py), behind the SAME
`InferenceServer`/`ModelRouter` backends as the HTTP frontend.

Why a second wire: the HTTP/1.1 door costs one OS thread per connection
(ThreadingHTTPServer), full-body buffering on both sides, and an
npz/JSON re-encode of every tensor. At 10k rps those per-request costs
dominate once the forward is cheap. This frontend removes all three:

  - EVENT LOOP, NOT THREAD-PER-CONNECTION: one acceptor (io loop 0's
    listener) plus a small FIXED set of io threads, each running its own
    `selectors` loop over a share of the connections (new connections
    are dealt round-robin). Reads, frame decode (one `np.frombuffer`
    view per tensor — zero parse), submit, and writes for a connection
    all happen on its io thread; 10k idle connections cost file
    descriptors, not threads.
  - PIPELINING: a connection may have MANY request-ids in flight;
    replies are written in COMPLETION order (each response future's
    done-callback enqueues its frames the moment the forward resolves —
    a slow request never convoys the fast ones behind it).
  - CHUNKED RESPONSE STREAMING (flag-gated): a request with FLAG_STREAM
    gets its response as a descriptor-table frame followed by sized
    CHUNK frames written zero-copy from the forward's output buffers —
    first-byte latency decouples from blob size, and the only bytes the
    transport ever COPIES per connection are frame headers (the npz door
    serializes the whole blob into a second buffer before byte one).

Shed-not-hang carries over wholesale: every error path answers a TYPED
error frame (wire.py's table mirrors the HTTP codes one-for-one), a
malformed frame (bad magic/version, oversized) fails ITS connection
alone after a typed answer, and a wedged forward is reaped by the io
loop's timeout sweep — a client of this transport never hangs.

`BinaryClient` / `binary_infer` at the bottom are the matching client
(keep-alive, pipelined submits, streaming reassembly, thread-cached) —
`ModelRouter.add_remote_replica(..., transport="binary")` proxies over
it, so cross-replica hops drop the HTTP tax too.
"""
from __future__ import annotations

import itertools
import selectors
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logger import Logger
from . import wire
from .admission import (PriorityShedError, TenantAdmission,
                        TenantLimitError)
from .batcher import DeadlineExpiredError, QueueFullError
from .http_frontend import (BackendAdapter, lru_cache_drop,
                            lru_cache_get, register_transport_metrics)
from .router import NoReplicaError, UnknownModelError

_DEFAULT_WAIT_S = 30.0  # reply bound for requests with no deadline


def _exception_to_err(e: BaseException) -> Tuple[Tuple[int, str], str]:
    """Serve exception -> (wire error (code, kind), message). The exact
    mapping the HTTP frontend's except-ladder implements."""
    if isinstance(e, TenantLimitError):
        return wire.ERR_TENANT_LIMIT, str(e)
    if isinstance(e, PriorityShedError):
        return wire.ERR_PRIORITY, str(e)
    if isinstance(e, QueueFullError):
        return wire.ERR_QUEUE_FULL, str(e)
    if isinstance(e, DeadlineExpiredError):
        return wire.ERR_DEADLINE, str(e)
    if isinstance(e, NoReplicaError):
        return wire.ERR_NO_REPLICA, str(e)
    if isinstance(e, UnknownModelError):
        return wire.ERR_UNKNOWN_MODEL, str(e)
    if isinstance(e, (ValueError, KeyError, TypeError, wire.WireError)):
        return wire.ERR_BAD_REQUEST, str(e)
    return wire.ERR_INTERNAL, f"{type(e).__name__}: {e}"


def raise_for_error(code: int, kind: str, msg: str) -> None:
    """Wire error frame -> the SAME typed exception the local submit
    path (and http_infer) raises — transport-blind remote replicas.
    Protocol violations (bad magic/version, oversized frame) stay
    WireError: they mean OUR framing was wrong, not the request."""
    if kind in ("bad_magic", "bad_version", "too_large"):
        raise wire.WireError(f"server rejected the frame: {kind}: {msg}")
    if kind == "tenant_limit":
        raise TenantLimitError(msg)
    if kind == "priority":
        raise PriorityShedError(msg)
    if code == 429:
        raise QueueFullError(msg)
    if kind == "deadline":
        raise DeadlineExpiredError(msg)
    if code == 503:
        raise NoReplicaError(msg or f"replica shed ({kind})")
    if code == 404:
        raise UnknownModelError(msg)
    if code == 400:
        raise ValueError(f"binary_infer: {kind}: {msg}")
    raise RuntimeError(f"binary_infer: {code} {kind}: {msg}")


class _Conn:
    """One client connection: owned by exactly one io loop. The outbox
    is the only cross-thread surface (response done-callbacks append
    under `lock`; the io thread drains)."""

    __slots__ = ("sock", "loop", "rbuf", "outbox", "lock", "wview",
                 "wcopied", "closed", "close_after_flush", "inflight",
                 "copied_pending", "peak_copied", "reject_until")

    def __init__(self, sock, loop):
        self.sock = sock
        self.loop = loop
        self.rbuf = bytearray()
        self.outbox: deque = deque()
        self.lock = threading.Lock()
        self.wview: Optional[memoryview] = None
        self.wcopied = False
        self.closed = False
        self.close_after_flush = False
        # reject mode (over capacity): the typed error frame is queued,
        # incoming bytes are discarded (closing with unread request
        # bytes would RST the socket and destroy the answer in flight),
        # and the reaper closes the connection at this deadline if the
        # client hasn't hung up first
        self.reject_until: Optional[float] = None
        # req_id -> absolute reply bound (monotonic); popped on
        # completion, or by the reaper (which answers a timeout frame)
        self.inflight: Dict[int, float] = {}
        self.copied_pending = 0   # bytes of COPIED (header) data queued
        self.peak_copied = 0      # its high-water mark


class _IoLoop(threading.Thread):
    """One selectors loop over a share of the connections. `call_soon`
    is the only way other threads touch loop state."""

    def __init__(self, frontend: "BinaryFrontend", idx: int):
        super().__init__(name=f"serve-bin-io-{idx}", daemon=True)
        self.frontend = frontend
        self.idx = idx
        self.sel = selectors.DefaultSelector()
        self._rsock, self._wsock = socket.socketpair()
        self._rsock.setblocking(False)
        self._wsock.setblocking(False)
        self.sel.register(self._rsock, selectors.EVENT_READ, "wake")
        self._pending: List[Any] = []
        self._plock = threading.Lock()
        self.conns: set = set()
        self.running = True
        self._next_reap = 0.0

    def call_soon(self, fn) -> None:
        with self._plock:
            self._pending.append(fn)
        try:
            self._wsock.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wake is already queued

    def stop(self) -> None:
        self.running = False
        self.call_soon(lambda: None)

    def adopt(self, conn: _Conn) -> None:
        """Register a freshly-accepted connection (loop thread only)."""
        if not self.running:
            conn.sock.close()
            self.frontend._conn_closed()
            return
        self.conns.add(conn)
        self.sel.register(conn.sock, selectors.EVENT_READ, conn)
        self.arm_write(conn)  # an outbox queued pre-adopt (the reject
        #                       path's error frame) must still flush

    def arm_write(self, conn: _Conn) -> None:
        """(Re)compute the interest set (loop thread only)."""
        if conn.closed:
            return
        events = selectors.EVENT_READ
        with conn.lock:
            if conn.outbox or conn.wview is not None:
                events |= selectors.EVENT_WRITE
        try:
            self.sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError):
            pass  # already closed/unregistered

    def run(self) -> None:
        try:
            while self.running:
                events = self.sel.select(timeout=0.25)
                with self._plock:
                    pending, self._pending = self._pending, []
                for fn in pending:
                    fn()
                for key, mask in events:
                    data = key.data
                    if data == "wake":
                        try:
                            while self._rsock.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    elif data == "accept":
                        self.frontend._accept()
                    else:
                        if mask & selectors.EVENT_READ:
                            self._read(data)
                        if mask & selectors.EVENT_WRITE and \
                                not data.closed:
                            self._write(data)
                now = time.monotonic()
                if now >= self._next_reap:
                    self._next_reap = now + 1.0
                    self._reap(now)
        finally:
            for conn in list(self.conns):
                self.close_conn(conn)
            self.sel.close()
            self._rsock.close()
            self._wsock.close()

    # -- per-connection io (loop thread only) --------------------------------

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 18)
        except BlockingIOError:
            return
        except OSError:
            self.close_conn(conn)
            return
        if not data:
            self.close_conn(conn)
            return
        conn.rbuf += data
        self.frontend._process(conn)

    def _write(self, conn: _Conn) -> None:
        while True:
            if conn.wview is None:
                with conn.lock:
                    if not conn.outbox:
                        break
                    conn.wview, conn.wcopied = conn.outbox.popleft()
            try:
                n = conn.sock.send(conn.wview)
            except BlockingIOError:
                break
            except OSError:
                self.close_conn(conn)
                return
            if conn.wcopied:
                with conn.lock:
                    conn.copied_pending -= n
            conn.wview = conn.wview[n:] if n < len(conn.wview) else None
        self.arm_write(conn)
        with conn.lock:
            drained = not conn.outbox and conn.wview is None
        if drained and conn.close_after_flush:
            self.close_conn(conn)

    def _reap(self, now: float) -> None:
        """Answer (typed) any in-flight request past its reply bound —
        a wedged worker must never leave a binary client hanging. Also
        closes reject-mode connections whose client never hung up."""
        for conn in list(self.conns):
            if conn.reject_until is not None:
                if now >= conn.reject_until:
                    self.close_conn(conn)
                continue
            expired: List[int] = []
            with conn.lock:
                for rid, bound in list(conn.inflight.items()):
                    if now >= bound:
                        expired.append(rid)
                        del conn.inflight[rid]
            for rid in expired:
                self.frontend._answer_error(
                    conn, rid, wire.ERR_TIMEOUT,
                    "response wait timed out")

    def close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        with conn.lock:
            conn.inflight.clear()  # late completions become no-ops
            conn.outbox.clear()
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self.conns.discard(conn)
        self.frontend._conn_closed()


class BinaryFrontend:
    """The event-loop binary-frame inference endpoint over an
    InferenceServer or ModelRouter. Port 0 binds ephemeral; the bound
    address is `.address`."""

    transport = "binary"

    def __init__(self, backend, port: int = 0, host: str = "127.0.0.1",
                 io_threads: int = 2,
                 max_frame_bytes: int = 64 << 20,
                 chunk_bytes: int = 256 << 10,
                 default_deadline_s: Optional[float] = None,
                 max_connections: int = 4096,
                 tenants: Optional[TenantAdmission] = None,
                 logger: Optional[Logger] = None):
        assert io_threads >= 1
        self.backend = backend
        self.adapter = BackendAdapter(backend)
        self.default_deadline_s = default_deadline_s
        self.max_frame_bytes = int(max_frame_bytes)
        self.chunk_bytes = int(chunk_bytes)
        self.max_connections = int(max_connections)
        self.tenants = tenants
        self.log = logger
        self.registry = backend.registry
        self._c_req, self._c_conns, self._g_active, self._c_shed = \
            register_transport_metrics(self.registry, self.transport)
        self.connections = 0       # lifetime accepted
        self.requests = 0          # lifetime request frames
        self.rejected_over_cap = 0
        self._active = 0
        self._active_lock = threading.Lock()
        self._g_active.set_fn(lambda: self._active,
                              transport=self.transport)
        self.peak_buffered_bytes = 0  # max COPIED bytes queued per conn
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self._loops = [_IoLoop(self, i) for i in range(io_threads)]
        self._loops[0].sel.register(self._listener,
                                    selectors.EVENT_READ, "accept")
        self._rr = itertools.count()
        for lp in self._loops:
            lp.start()
        if logger is not None:
            logger.log(f"serve: binary data plane at "
                       f"spkn://{self.address[0]}:{self.address[1]} "
                       f"({io_threads} io threads)")

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()[:2]

    def stop(self) -> None:
        for lp in self._loops:
            lp.stop()
        for lp in self._loops:
            lp.join(timeout=5.0)
        try:
            self._listener.close()
        except OSError:
            pass

    def _conn_closed(self) -> None:
        with self._active_lock:
            self._active -= 1

    # -- accept (io loop 0) ---------------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            self.connections += 1
            self._c_conns.inc(transport=self.transport)
            with self._active_lock:
                over = self._active >= self.max_connections
                self._active += 1  # rejects count too (close is
                #                    symmetric for both kinds)
            lp = self._loops[next(self._rr) % len(self._loops)]
            conn = _Conn(sock, lp)
            if over:
                # answered, not refused — but the client is mid-send of
                # its request, so the connection enters REJECT mode:
                # queue the typed frame, discard its input, and let the
                # client hang up after reading the answer (closing now,
                # with unread request bytes queued, would RST the
                # socket and destroy the answer in flight). The reaper
                # bounds a client that never hangs up.
                self.rejected_over_cap += 1
                conn.reject_until = time.monotonic() + 10.0
            lp.call_soon(lambda c=conn, l=lp: l.adopt(c))
            if over:
                # after adopt is queued: the enqueue's write-arm must
                # find the socket registered
                self._answer_error(conn, 0, wire.ERR_OVER_CAPACITY,
                                   "server at connection capacity")

    # -- frame processing (a conn's io thread) --------------------------------

    def _process(self, conn: _Conn) -> None:
        if conn.reject_until is not None:
            conn.rbuf.clear()  # reject mode: input is discarded
            return
        while not conn.closed and not conn.close_after_flush:
            if len(conn.rbuf) < wire.HEADER_LEN:
                return
            try:
                ftype, flags, req_id, meta_len, payload_len = \
                    wire.parse_header(conn.rbuf)
            except wire.WireError as e:
                err = (wire.ERR_BAD_MAGIC if "magic" in str(e)
                       else wire.ERR_BAD_VERSION)
                self._answer_error(conn, 0, err, str(e), close=True)
                return
            if meta_len + payload_len > self.max_frame_bytes:
                # the 413 analog: typed answer, then close THIS
                # connection (we will not read our way through an
                # oversized frame to stay in sync)
                self._answer_error(
                    conn, req_id, wire.ERR_TOO_LARGE,
                    f"frame of {meta_len + payload_len} bytes exceeds "
                    f"the {self.max_frame_bytes}-byte cap", close=True)
                return
            frame_len = wire.HEADER_LEN + meta_len + payload_len
            if len(conn.rbuf) < frame_len:
                return  # length-prefixed: wait for the rest
            meta = bytes(conn.rbuf[wire.HEADER_LEN:
                                   wire.HEADER_LEN + meta_len])
            payload = bytes(conn.rbuf[wire.HEADER_LEN + meta_len:
                                      frame_len])
            del conn.rbuf[:frame_len]
            if ftype != wire.T_REQUEST:
                self._answer_error(
                    conn, req_id, wire.ERR_BAD_REQUEST,
                    f"unexpected frame type {ftype} (server accepts "
                    f"REQUEST frames)")
                continue
            self._handle_request(conn, flags, req_id, meta, payload)

    def _handle_request(self, conn: _Conn, flags: int, req_id: int,
                        meta: bytes, payload: bytes) -> None:
        self.requests += 1
        stream = bool(flags & wire.FLAG_STREAM)
        with conn.lock:
            dup = req_id in conn.inflight
        if dup:
            # a duplicate id would overwrite the first entry and leave
            # one of the two completions unanswered — reject it before
            # anything is submitted (one io thread serves a connection,
            # so this check cannot race a concurrent insert)
            self._answer_error(
                conn, req_id, wire.ERR_BAD_REQUEST,
                f"request id {req_id} is already in flight on this "
                f"connection")
            return
        try:
            model_s, tenant, priority, deadline_ms, descs = \
                wire.unpack_request_meta(meta)
            # admission runs BEFORE tensor decode / model resolution
            # (the HTTP rule): a shed tenant's flood must not buy
            # io-thread decode time, and a malformed request still
            # spends its tenant's token
            reason = (self.tenants.admit(tenant or None,
                                         priority or None)
                      if self.tenants is not None else None)
            if reason is not None:
                self._c_shed.inc(model=model_s or "", reason=reason)
                self._answer_error(
                    conn, req_id,
                    wire.ERR_TENANT_LIMIT if reason == "tenant_limit"
                    else wire.ERR_PRIORITY,
                    "tenant rate limit exceeded"
                    if reason == "tenant_limit" else
                    "shed by priority class under admission pressure")
                return
            inputs = wire.tensors_from(descs, payload)
            model = self.adapter.resolve(model_s or None)
            self.adapter.coerce(model, inputs)
            deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                          else self.default_deadline_s)
            fut = self.adapter.submit(model, inputs, deadline_s)
        except BaseException as e:
            self._answer_error(conn, req_id, *_exception_to_err(e))
            return
        bound = time.monotonic() + (
            deadline_s + 5.0 if deadline_s is not None
            else _DEFAULT_WAIT_S)
        with conn.lock:
            if conn.closed:
                return
            conn.inflight[req_id] = bound
        fut.add_done_callback(
            lambda f, c=conn, r=req_id, s=stream, m=model:
            self._complete(c, r, s, m, f))

    # -- completion (forward-worker / proxy threads) --------------------------

    def _complete(self, conn: _Conn, req_id: int, stream: bool,
                  model: str, fut) -> None:
        with conn.lock:
            live = conn.inflight.pop(req_id, None) is not None
        if not live:
            return  # reaped (already answered) or connection gone
        exc = fut.exception()
        if exc is not None:
            self._answer_error(conn, req_id, *_exception_to_err(exc))
            return
        out = {k: np.asarray(v) for k, v in fut.result().items()}
        items = wire.pack_response(req_id, model,
                                   self.adapter.step(model), out,
                                   stream=stream,
                                   chunk_bytes=self.chunk_bytes)
        self._c_req.inc(code="200", transport=self.transport)
        self._enqueue(conn, items)

    # -- reply plumbing (any thread) ------------------------------------------

    def _answer_error(self, conn: _Conn, req_id: int,
                      code_kind: Tuple[int, str], msg: str,
                      close: bool = False) -> None:
        self._c_req.inc(code=str(code_kind[0]), transport=self.transport)
        if close:
            conn.close_after_flush = True
        self._enqueue(conn, [(wire.pack_error(req_id, code_kind, msg),
                              None)])

    def _enqueue(self, conn: _Conn,
                 items: List[Tuple[bytes, Optional[memoryview]]]) -> None:
        if conn.closed:
            return
        with conn.lock:
            for head, view in items:
                if head:
                    conn.outbox.append((memoryview(head), True))
                    conn.copied_pending += len(head)
                if view is not None and len(view):
                    conn.outbox.append((view, False))
            conn.peak_copied = max(conn.peak_copied, conn.copied_pending)
            peak = conn.peak_copied
        # the bench's buffer_bounded_by_chunk acceptance reads this
        # high-water mark: the max-update must not lose a racing larger
        # sample to an unsynchronized read-compare-write
        with self._active_lock:
            if peak > self.peak_buffered_bytes:
                self.peak_buffered_bytes = peak
        conn.loop.call_soon(lambda c=conn: c.loop.arm_write(c))


# ---------------------------------------------------------------------------
# the matching client
# ---------------------------------------------------------------------------

def _parse_address(address) -> Tuple[str, int]:
    """(host, port) | 'host:port' | 'spkn://host:port' -> (host, port)."""
    if isinstance(address, (tuple, list)):
        return str(address[0]), int(address[1])
    s = str(address)
    for scheme in ("spkn://", "tcp://", "http://"):
        if s.startswith(scheme):
            s = s[len(scheme):]
    s = s.rstrip("/")
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"binary address {address!r} is not host:port")
    return host, int(port)


class BinaryClient:
    """Keep-alive, pipelined client for the binary frame transport.

    `submit` writes a request frame and returns its request-id without
    waiting; `collect` reads frames (in whatever completion order the
    server chose) until that id resolves — so N submits followed by N
    collects is a pipelined burst on one connection. `infer` is the
    one-shot convenience and records `last_timing` (first-byte /
    complete, seconds from submit) — the streaming bench reads it.

    Thread-safety: one connection, one user thread (the thread-cached
    `binary_infer` below gives each thread its own client)."""

    def __init__(self, host, port: Optional[int] = None,
                 timeout: float = 30.0):
        if port is None:
            host, port = _parse_address(host)
        self.addr = (host, int(port))
        self.timeout = float(timeout)
        self.sock = socket.create_connection(self.addr, timeout=timeout)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._rbuf = bytearray()
        self._ids = itertools.count(1)
        # req_id -> reassembly state (supports out-of-order completion)
        self._pending: Dict[int, Dict[str, Any]] = {}
        self.last_timing: Optional[Dict[str, float]] = None
        self.closed = False

    def close(self) -> None:
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass

    # -- submit side ---------------------------------------------------------

    def submit(self, payload: Dict[str, np.ndarray],
               model: str = "", deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: Optional[str] = None,
               stream: bool = False) -> int:
        rid = next(self._ids)
        head, views = wire.pack_request(
            rid, model, {k: np.asarray(v) for k, v in payload.items()},
            deadline_ms=None if deadline_s is None else deadline_s * 1e3,
            tenant=tenant, priority=priority, stream=stream)
        self._pending[rid] = {"t_submit": time.perf_counter(),
                              "t_first": None, "done": False,
                              "outputs": None, "exc": None,
                              "buf": None, "descs": None, "got": 0,
                              "total": 0, "model": None, "step": None}
        # _fill shrinks the socket timeout toward a deadline; a cached
        # client's NEXT send must not inherit that sliver
        self.sock.settimeout(self.timeout)
        self.sock.sendall(head)
        for v in views:
            self.sock.sendall(v)
        return rid

    # -- receive side --------------------------------------------------------

    def _fill(self, n: int, deadline: float) -> None:
        """Block until the read buffer holds >= n bytes."""
        while len(self._rbuf) < n:
            budget = deadline - time.perf_counter()
            if budget <= 0:
                raise TimeoutError(
                    f"binary_infer: no reply within the timeout "
                    f"({self.timeout:.1f}s)")
            self.sock.settimeout(min(budget, self.timeout))
            try:
                data = self.sock.recv(1 << 18)
            except socket.timeout:
                continue
            if not data:
                raise ConnectionError(
                    "binary transport: server closed the connection")
            self._rbuf += data

    def _read_frame(self, deadline: float) -> None:
        self._fill(wire.HEADER_LEN, deadline)
        ftype, flags, rid, meta_len, payload_len = \
            wire.parse_header(self._rbuf)
        inline = 0 if (ftype == wire.T_RESPONSE
                       and flags & wire.FLAG_STREAM) else payload_len
        self._fill(wire.HEADER_LEN + meta_len + inline, deadline)
        meta = bytes(self._rbuf[wire.HEADER_LEN:
                                wire.HEADER_LEN + meta_len])
        payload = bytes(self._rbuf[wire.HEADER_LEN + meta_len:
                                   wire.HEADER_LEN + meta_len + inline])
        del self._rbuf[:wire.HEADER_LEN + meta_len + inline]
        now = time.perf_counter()
        if ftype == wire.T_ERROR:
            code, kind, msg = wire.unpack_error_meta(meta)
            if rid == 0:
                # connection-level: the stream is done for — but the
                # error is still the server's TYPED answer (e.g. 503
                # over_capacity must surface as NoReplicaError exactly
                # as it would over HTTP, so router proxies stay
                # transport-blind)
                self.close()
                raise_for_error(code, kind, msg)
            st = self._pending.get(rid)
            if st is not None:
                st["exc"] = (code, kind, msg)
                st["done"] = True
                if st["t_first"] is None:
                    st["t_first"] = now
            return
        st = self._pending.get(rid)
        if st is None:
            return  # reply to an abandoned id: drop it
        if st["t_first"] is None:
            st["t_first"] = now
        if ftype == wire.T_RESPONSE:
            model, step, descs = wire.unpack_response_meta(meta)
            st["model"], st["step"], st["descs"] = model, step, descs
            if flags & wire.FLAG_STREAM:
                st["total"] = payload_len
                st["buf"] = bytearray(payload_len)
                if payload_len == 0:
                    st["outputs"] = wire.tensors_from(descs, b"")
                    st["done"] = True
            else:
                st["outputs"] = wire.tensors_from(descs, payload)
                st["done"] = True
        elif ftype == wire.T_CHUNK:
            off = wire.unpack_chunk_meta(meta)
            if st["buf"] is None or off + len(payload) > st["total"]:
                raise wire.WireError(
                    f"chunk for request {rid} outside its announced "
                    f"payload")
            st["buf"][off:off + len(payload)] = payload
            st["got"] += len(payload)
            if st["got"] >= st["total"] or flags & wire.FLAG_LAST:
                if st["got"] < st["total"]:
                    raise wire.WireError(
                        f"stream for request {rid} ended {st['total'] - st['got']} "
                        f"bytes short")
                # frombuffer views the bytearray directly — no full-blob
                # copy on the client side of the zero-copy wire either
                st["outputs"] = wire.tensors_from(st["descs"],
                                                  st["buf"])
                st["done"] = True
        # any other type from a server is a protocol error
        else:
            raise wire.WireError(f"unexpected frame type {ftype} "
                                 f"from server")

    def collect(self, rid: int, timeout: Optional[float] = None
                ) -> Dict[str, np.ndarray]:
        """Read until request `rid` resolves (other ids' replies are
        absorbed into their own pending states — pipelining)."""
        deadline = time.perf_counter() + (timeout if timeout is not None
                                          else self.timeout)
        while True:
            st = self._pending.get(rid)
            if st is None:
                raise KeyError(f"unknown request id {rid}")
            if st["done"]:
                self._pending.pop(rid)
                self.last_timing = {
                    "t_first_byte_s": st["t_first"] - st["t_submit"],
                    "t_complete_s":
                        time.perf_counter() - st["t_submit"]}
                if st["exc"] is not None:
                    raise_for_error(*st["exc"])
                return st["outputs"]
            self._read_frame(deadline)

    def infer(self, payload: Dict[str, np.ndarray], model: str = "",
              deadline_s: Optional[float] = None,
              tenant: Optional[str] = None,
              priority: Optional[str] = None, stream: bool = False,
              timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        rid = self.submit(payload, model=model, deadline_s=deadline_s,
                          tenant=tenant, priority=priority,
                          stream=stream)
        return self.collect(rid, timeout=timeout)


# -- thread-cached convenience client (the proxy/bench entry point) ----------

_client_cache = threading.local()
MAX_CACHED_CLIENTS = 8  # per thread; LRU-evicted past this


def _cached_client(host: str, port: int, timeout: float) -> BinaryClient:
    cli = lru_cache_get(
        _client_cache, "clients", (host, port),
        lambda: BinaryClient(host, port, timeout=timeout),
        MAX_CACHED_CLIENTS)
    cli.timeout = float(timeout)
    return cli


def _drop_client(host: str, port: int) -> None:
    lru_cache_drop(_client_cache, "clients", (host, port))


def binary_infer(address, model: str,
                 payload: Dict[str, np.ndarray],
                 deadline_s: Optional[float] = None,
                 timeout: float = 30.0,
                 tenant: Optional[str] = None,
                 priority: Optional[str] = None,
                 stream: bool = False) -> Dict[str, np.ndarray]:
    """One inference request over the binary transport (thread-cached
    keep-alive client — the `http_infer` counterpart the router's
    binary remote replicas and the bench drivers ride). The http_infer
    cache rules apply: ANY failure mid-exchange evicts this address's
    cached client (never re-use a stream in an unknown state); a stale
    server-closed socket gets ONE retry on a fresh connection."""
    host, port = _parse_address(address)
    for attempt in (0, 1):
        cli = _cached_client(host, port, timeout)
        try:
            return cli.infer(payload, model=model, deadline_s=deadline_s,
                             tenant=tenant, priority=priority,
                             stream=stream, timeout=timeout)
        except (TenantLimitError, QueueFullError, DeadlineExpiredError,
                NoReplicaError, UnknownModelError, ValueError):
            # typed sheds arrived ON the stream, which is usually still
            # good — except a connection-level frame (rid 0, e.g.
            # over_capacity), whose delivery closed the client
            if cli.closed:
                _drop_client(host, port)
            raise
        except TimeoutError:
            _drop_client(host, port)
            raise  # a slow server is not a stale socket: no retry
        except ConnectionError as e:
            # a server-closed cached connection: retry once fresh
            _drop_client(host, port)
            if attempt:
                raise ConnectionError(
                    f"binary_infer to {address}: {e}") from e
        except BaseException:
            _drop_client(host, port)
            raise
