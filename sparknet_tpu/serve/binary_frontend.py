"""The binary data plane: a `selectors` event-loop front door speaking
the length-prefixed frame protocol (serve/wire.py), behind the SAME
`InferenceServer`/`ModelRouter` backends as the HTTP frontend.

Why a second wire: the HTTP/1.1 door costs one OS thread per connection
(ThreadingHTTPServer), full-body buffering on both sides, and an
npz/JSON re-encode of every tensor. At 10k rps those per-request costs
dominate once the forward is cheap. This frontend removes all three:

  - EVENT LOOP, NOT THREAD-PER-CONNECTION: one acceptor (io loop 0's
    listener) plus a small FIXED set of io threads, each running its own
    `selectors` loop over a share of the connections (new connections
    are dealt round-robin). Reads, frame decode (one `np.frombuffer`
    view per tensor — zero parse), submit, and writes for a connection
    all happen on its io thread; 10k idle connections cost file
    descriptors, not threads.
  - PIPELINING: a connection may have MANY request-ids in flight;
    replies are written in COMPLETION order (each response future's
    done-callback enqueues its frames the moment the forward resolves —
    a slow request never convoys the fast ones behind it).
  - CHUNKED RESPONSE STREAMING (flag-gated): a request with FLAG_STREAM
    gets its response as a descriptor-table frame followed by sized
    CHUNK frames written zero-copy from the forward's output buffers —
    first-byte latency decouples from blob size, and the only bytes the
    transport ever COPIES per connection are frame headers (the npz door
    serializes the whole blob into a second buffer before byte one).

Shed-not-hang carries over wholesale: every error path answers a TYPED
error frame (wire.py's table mirrors the HTTP codes one-for-one), a
malformed frame (bad magic/version, oversized) fails ITS connection
alone after a typed answer, and a wedged forward is reaped by the io
loop's timeout sweep — a client of this transport never hangs.

Tail-latency extensions (wire v3):
  - CANCEL frames: a client may cancel an in-flight request id; if the
    request is still queued server-side it resolves with the typed
    `cancelled` error, otherwise the cancel is dropped and the normal
    reply arrives — exactly one terminal frame per id either way. The
    hedging router uses this to reap its losing leg.
  - spkn-shm (serve/shm.py): same-host peers negotiate FLAG_SHM at
    connect (SHM_HELLO + nonce proof); granted, tensor payloads ride
    named shared-memory ring segments in BOTH directions and zero
    payload bytes cross the socket (`payload_rx_bytes` /
    `payload_tx_bytes` pin it). Remote peers fall back inline.
  - Responses carry the request's measured queue wait (`queue_wait_ms`
    in the meta, `BinaryClient.last_timing`), splitting the observed
    tail into queueing vs compute.

`BinaryClient` / `binary_infer` at the bottom are the matching client
(keep-alive, pipelined submits, streaming reassembly, thread-cached) —
`ModelRouter.add_remote_replica(..., transport="binary")` proxies over
it, so cross-replica hops drop the HTTP tax too.
"""
from __future__ import annotations

import itertools
import selectors
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import reqtrace
from ..utils.logger import Logger
from . import shm, wire
from .admission import (PriorityShedError, TenantAdmission,
                        TenantLimitError)
from .batcher import (DeadlineExpiredError, QueueFullError,
                      RequestCancelledError)
from .http_frontend import (BackendAdapter, lru_cache_drop,
                            lru_cache_get, register_transport_metrics)
from .router import NoReplicaError, UnknownModelError
from .server import encode_outputs, pop_outputs

_DEFAULT_WAIT_S = 30.0  # reply bound for requests with no deadline


def _exception_to_err(e: BaseException) -> Tuple[Tuple[int, str], str]:
    """Serve exception -> (wire error (code, kind), message). The exact
    mapping the HTTP frontend's except-ladder implements."""
    if isinstance(e, TenantLimitError):
        return wire.ERR_TENANT_LIMIT, str(e)
    if isinstance(e, PriorityShedError):
        return wire.ERR_PRIORITY, str(e)
    if isinstance(e, QueueFullError):
        return wire.ERR_QUEUE_FULL, str(e)
    if isinstance(e, DeadlineExpiredError):
        return wire.ERR_DEADLINE, str(e)
    if isinstance(e, RequestCancelledError):
        return wire.ERR_CANCELLED, str(e)
    if isinstance(e, NoReplicaError):
        return wire.ERR_NO_REPLICA, str(e)
    if isinstance(e, UnknownModelError):
        return wire.ERR_UNKNOWN_MODEL, str(e)
    # FileNotFoundError: a FLAG_SHM request named a segment this host
    # cannot map — the CLIENT's framing was wrong, not the server
    if isinstance(e, (ValueError, KeyError, TypeError, wire.WireError,
                      FileNotFoundError)):
        return wire.ERR_BAD_REQUEST, str(e)
    return wire.ERR_INTERNAL, f"{type(e).__name__}: {e}"


def raise_for_error(code: int, kind: str, msg: str) -> None:
    """Wire error frame -> the SAME typed exception the local submit
    path (and http_infer) raises — transport-blind remote replicas.
    Protocol violations (bad magic/version, oversized frame) stay
    WireError: they mean OUR framing was wrong, not the request."""
    if kind in ("bad_magic", "bad_version", "too_large"):
        raise wire.WireError(f"server rejected the frame: {kind}: {msg}")
    if kind == "tenant_limit":
        raise TenantLimitError(msg)
    if kind == "priority":
        raise PriorityShedError(msg)
    if code == 429:
        raise QueueFullError(msg)
    if kind == "deadline":
        raise DeadlineExpiredError(msg)
    if kind == "cancelled":
        raise RequestCancelledError(msg)
    if code == 503:
        raise NoReplicaError(msg or f"replica shed ({kind})")
    if code == 404:
        raise UnknownModelError(msg)
    if code == 400:
        raise ValueError(f"binary_infer: {kind}: {msg}")
    raise RuntimeError(f"binary_infer: {code} {kind}: {msg}")


class _Conn:
    """One client connection: owned by exactly one io loop. The outbox
    is the only cross-thread surface (response done-callbacks append
    under `lock`; the io thread drains)."""

    __slots__ = ("sock", "loop", "rbuf", "outbox", "lock", "wview",
                 "wcopied", "closed", "close_after_flush", "inflight",
                 "copied_pending", "peak_copied", "reject_until",
                 "shm_ok", "shm_ring", "shm_segs")

    def __init__(self, sock, loop):
        self.sock = sock
        self.loop = loop
        self.rbuf = bytearray()
        self.outbox: deque = deque()
        self.lock = threading.Lock()
        self.wview: Optional[memoryview] = None
        self.wcopied = False
        self.closed = False
        self.close_after_flush = False
        # reject mode (over capacity): the typed error frame is queued,
        # incoming bytes are discarded (closing with unread request
        # bytes would RST the socket and destroy the answer in flight),
        # and the reaper closes the connection at this deadline if the
        # client hasn't hung up first
        self.reject_until: Optional[float] = None
        # req_id -> (reply bound (monotonic), response future, model,
        # journal row, trace record); popped on completion, or by the
        # reaper (which answers a timeout frame). The future rides along
        # so a CANCEL frame can reach the batcher's queue entry for this
        # id; the trace record so every terminal path can close it.
        self.inflight: Dict[int, Tuple[float, Any, str,
                                       Optional[dict],
                                       Optional[dict]]] = {}
        self.copied_pending = 0   # bytes of COPIED (header) data queued
        self.peak_copied = 0      # its high-water mark
        # spkn-shm (serve/shm.py): granted after a verified SHM_HELLO
        self.shm_ok = False
        self.shm_ring = None      # response-segment ring (lazy)
        self.shm_segs: Dict[str, Any] = {}  # attached request segments


class _IoLoop(threading.Thread):
    """One selectors loop over a share of the connections. `call_soon`
    is the only way other threads touch loop state."""

    def __init__(self, frontend: "BinaryFrontend", idx: int):
        super().__init__(name=f"serve-bin-io-{idx}", daemon=True)
        self.frontend = frontend
        self.idx = idx
        self.sel = selectors.DefaultSelector()
        self._rsock, self._wsock = socket.socketpair()
        self._rsock.setblocking(False)
        self._wsock.setblocking(False)
        self.sel.register(self._rsock, selectors.EVENT_READ, "wake")
        self._pending: List[Any] = []
        self._plock = threading.Lock()
        self.conns: set = set()
        self.running = True
        self._next_reap = 0.0

    def call_soon(self, fn) -> None:
        with self._plock:
            self._pending.append(fn)
        try:
            self._wsock.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wake is already queued

    def stop(self) -> None:
        self.running = False
        self.call_soon(lambda: None)

    def adopt(self, conn: _Conn) -> None:
        """Register a freshly-accepted connection (loop thread only)."""
        if not self.running:
            conn.sock.close()
            self.frontend._conn_closed()
            return
        self.conns.add(conn)
        self.sel.register(conn.sock, selectors.EVENT_READ, conn)
        self.arm_write(conn)  # an outbox queued pre-adopt (the reject
        #                       path's error frame) must still flush

    def arm_write(self, conn: _Conn) -> None:
        """(Re)compute the interest set (loop thread only)."""
        if conn.closed:
            return
        events = selectors.EVENT_READ
        with conn.lock:
            if conn.outbox or conn.wview is not None:
                events |= selectors.EVENT_WRITE
        try:
            self.sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError):
            pass  # already closed/unregistered

    def run(self) -> None:
        try:
            while self.running:
                events = self.sel.select(timeout=0.25)
                with self._plock:
                    pending, self._pending = self._pending, []
                for fn in pending:
                    fn()
                for key, mask in events:
                    data = key.data
                    if data == "wake":
                        try:
                            while self._rsock.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    elif data == "accept":
                        self.frontend._accept()
                    else:
                        if mask & selectors.EVENT_READ:
                            self._read(data)
                        if mask & selectors.EVENT_WRITE and \
                                not data.closed:
                            self._write(data)
                now = time.monotonic()
                if now >= self._next_reap:
                    self._next_reap = now + 1.0
                    self._reap(now)
        finally:
            for conn in list(self.conns):
                self.close_conn(conn)
            self.sel.close()
            self._rsock.close()
            self._wsock.close()

    # -- per-connection io (loop thread only) --------------------------------

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 18)
        except BlockingIOError:
            return
        except OSError:
            self.close_conn(conn)
            return
        if not data:
            self.close_conn(conn)
            return
        conn.rbuf += data
        self.frontend._process(conn)

    def _write(self, conn: _Conn) -> None:
        while True:
            if conn.wview is None:
                with conn.lock:
                    if not conn.outbox:
                        break
                    conn.wview, conn.wcopied = conn.outbox.popleft()
            try:
                n = conn.sock.send(conn.wview)
            except BlockingIOError:
                break
            except OSError:
                self.close_conn(conn)
                return
            if conn.wcopied:
                with conn.lock:
                    conn.copied_pending -= n
            conn.wview = conn.wview[n:] if n < len(conn.wview) else None
        self.arm_write(conn)
        with conn.lock:
            drained = not conn.outbox and conn.wview is None
        if drained and conn.close_after_flush:
            self.close_conn(conn)

    def _reap(self, now: float) -> None:
        """Answer (typed) any in-flight request past its reply bound —
        a wedged worker must never leave a binary client hanging. Also
        closes reject-mode connections whose client never hung up."""
        for conn in list(self.conns):
            if conn.reject_until is not None:
                if now >= conn.reject_until:
                    self.close_conn(conn)
                continue
            expired: List[Tuple[int, Optional[dict],
                               Optional[dict]]] = []
            with conn.lock:
                for rid, entry in list(conn.inflight.items()):
                    if now >= entry[0]:
                        expired.append((rid, entry[3], entry[4]))
                        del conn.inflight[rid]
            rt = reqtrace.active()
            for rid, jinfo, rec in expired:
                self.frontend._journal_row(jinfo, "timeout")
                if rt is not None and rec is not None:
                    rt.finish(rec, "timeout")
                self.frontend._answer_error(
                    conn, rid, wire.ERR_TIMEOUT,
                    "response wait timed out")

    def close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        with conn.lock:
            conn.inflight.clear()  # late completions become no-ops
            conn.outbox.clear()
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        # shm teardown: drop request-segment mappings (the client owns
        # and unlinks those) and unlink our response ring. A mapping
        # pinned by a still-live tensor view refuses to close
        # (BufferError) — it falls to process exit, never to a crash.
        for seg in conn.shm_segs.values():
            try:
                seg.close()
            except Exception:
                pass
        conn.shm_segs.clear()
        if conn.shm_ring is not None:
            conn.shm_ring.close()
        self.conns.discard(conn)
        self.frontend._conn_closed()


class BinaryFrontend:
    """The event-loop binary-frame inference endpoint over an
    InferenceServer or ModelRouter. Port 0 binds ephemeral; the bound
    address is `.address`."""

    transport = "binary"

    def __init__(self, backend, port: int = 0, host: str = "127.0.0.1",
                 io_threads: int = 2,
                 max_frame_bytes: int = 64 << 20,
                 chunk_bytes: int = 256 << 10,
                 default_deadline_s: Optional[float] = None,
                 max_connections: int = 4096,
                 tenants: Optional[TenantAdmission] = None,
                 logger: Optional[Logger] = None,
                 enable_shm: bool = True,
                 journal: Optional[Logger] = None):
        assert io_threads >= 1
        self.backend = backend
        self.adapter = BackendAdapter(backend)
        self.default_deadline_s = default_deadline_s
        self.max_frame_bytes = int(max_frame_bytes)
        self.chunk_bytes = int(chunk_bytes)
        self.max_connections = int(max_connections)
        self.tenants = tenants
        self.log = logger
        # spkn-shm: grant FLAG_SHM to same-host peers (serve/shm.py).
        # Sweep segments orphaned by kill -9'd predecessors BEFORE any
        # ring exists — a crashed replica must not leak /dev/shm forever.
        self.enable_shm = bool(enable_shm) and shm.shm_available()
        self.swept_segments = (shm.sweep_orphans()
                               if self.enable_shm else [])
        # request journal (ROADMAP 5a): one JSONL row per request frame
        # — arrival shape + outcome — for replaying real traffic shapes
        self.journal = journal
        # tensor payload bytes that crossed THIS socket, per direction
        # (headers/meta excluded). The shm bench arm pins rx == tx == 0.
        self.payload_rx_bytes = 0
        self.payload_tx_bytes = 0
        self._byte_lock = threading.Lock()
        self.registry = backend.registry
        self._c_req, self._c_conns, self._g_active, self._c_shed = \
            register_transport_metrics(self.registry, self.transport)
        self.connections = 0       # lifetime accepted
        self.requests = 0          # lifetime request frames
        self.rejected_over_cap = 0
        self._active = 0
        self._active_lock = threading.Lock()
        self._g_active.set_fn(lambda: self._active,
                              transport=self.transport)
        self.peak_buffered_bytes = 0  # max COPIED bytes queued per conn
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self._loops = [_IoLoop(self, i) for i in range(io_threads)]
        self._loops[0].sel.register(self._listener,
                                    selectors.EVENT_READ, "accept")
        self._rr = itertools.count()
        for lp in self._loops:
            lp.start()
        if logger is not None:
            logger.log(f"serve: binary data plane at "
                       f"spkn://{self.address[0]}:{self.address[1]} "
                       f"({io_threads} io threads, shm "
                       f"{'on' if self.enable_shm else 'off'})")
            if self.swept_segments:
                logger.log(f"serve: swept {len(self.swept_segments)} "
                           f"orphaned shm segment(s) from dead peers")

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()[:2]

    def stop(self) -> None:
        for lp in self._loops:
            lp.stop()
        for lp in self._loops:
            lp.join(timeout=5.0)
        try:
            self._listener.close()
        except OSError:
            pass

    def _conn_closed(self) -> None:
        with self._active_lock:
            self._active -= 1

    # -- accept (io loop 0) ---------------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            self.connections += 1
            self._c_conns.inc(transport=self.transport)
            with self._active_lock:
                over = self._active >= self.max_connections
                self._active += 1  # rejects count too (close is
                #                    symmetric for both kinds)
            lp = self._loops[next(self._rr) % len(self._loops)]
            conn = _Conn(sock, lp)
            if over:
                # answered, not refused — but the client is mid-send of
                # its request, so the connection enters REJECT mode:
                # queue the typed frame, discard its input, and let the
                # client hang up after reading the answer (closing now,
                # with unread request bytes queued, would RST the
                # socket and destroy the answer in flight). The reaper
                # bounds a client that never hangs up.
                self.rejected_over_cap += 1
                conn.reject_until = time.monotonic() + 10.0
            lp.call_soon(lambda c=conn, l=lp: l.adopt(c))
            if over:
                # after adopt is queued: the enqueue's write-arm must
                # find the socket registered
                self._answer_error(conn, 0, wire.ERR_OVER_CAPACITY,
                                   "server at connection capacity")

    # -- frame processing (a conn's io thread) --------------------------------

    def _process(self, conn: _Conn) -> None:
        if conn.reject_until is not None:
            conn.rbuf.clear()  # reject mode: input is discarded
            return
        while not conn.closed and not conn.close_after_flush:
            if len(conn.rbuf) < wire.HEADER_LEN:
                return
            try:
                ftype, flags, req_id, meta_len, payload_len = \
                    wire.parse_header(conn.rbuf)
            except wire.WireError as e:
                err = (wire.ERR_BAD_MAGIC if "magic" in str(e)
                       else wire.ERR_BAD_VERSION)
                self._answer_error(conn, 0, err, str(e), close=True)
                return
            if meta_len + payload_len > self.max_frame_bytes:
                # the 413 analog: typed answer, then close THIS
                # connection (we will not read our way through an
                # oversized frame to stay in sync)
                self._answer_error(
                    conn, req_id, wire.ERR_TOO_LARGE,
                    f"frame of {meta_len + payload_len} bytes exceeds "
                    f"the {self.max_frame_bytes}-byte cap", close=True)
                return
            frame_len = wire.HEADER_LEN + meta_len + payload_len
            if len(conn.rbuf) < frame_len:
                return  # length-prefixed: wait for the rest
            meta = bytes(conn.rbuf[wire.HEADER_LEN:
                                   wire.HEADER_LEN + meta_len])
            payload = bytes(conn.rbuf[wire.HEADER_LEN + meta_len:
                                      frame_len])
            del conn.rbuf[:frame_len]
            if ftype == wire.T_CANCEL:
                # best-effort: reaches the batcher's queue entry if the
                # request hasn't formed yet (its future then resolves
                # with the typed `cancelled` error and answers the rid);
                # a cancel that lost the race — or names an unknown/
                # already-answered id — is silently dropped
                with conn.lock:
                    entry = conn.inflight.get(req_id)
                if entry is not None:
                    self.adapter.cancel(entry[2], entry[1])
                continue
            if ftype == wire.T_SHM_HELLO:
                self._handle_shm_hello(conn, req_id, meta)
                continue
            if ftype == wire.T_SHM_RELEASE:
                try:
                    name = wire.unpack_shm_release_meta(meta)
                except wire.WireError:
                    continue  # malformed release: the slot stays busy
                if conn.shm_ring is not None:
                    conn.shm_ring.release(name)
                continue
            if ftype != wire.T_REQUEST:
                self._answer_error(
                    conn, req_id, wire.ERR_BAD_REQUEST,
                    f"unexpected frame type {ftype} (server accepts "
                    f"REQUEST frames)")
                continue
            self._handle_request(conn, flags, req_id, meta, payload)

    def _handle_shm_hello(self, conn: _Conn, req_id: int,
                          meta: bytes) -> None:
        """Grant FLAG_SHM iff the peer proved same-host residency by
        writing a nonce we can read back through OUR filesystem. Any
        failure is a quiet deny — the connection proceeds inline."""
        ok = False
        if self.enable_shm:
            try:
                path, nonce = wire.unpack_shm_hello_meta(meta)
                ok = shm.check_nonce(path, nonce)
            except wire.WireError:
                ok = False
        if ok and conn.shm_ring is None:
            conn.shm_ring = shm.ShmRing()
        conn.shm_ok = ok
        self._enqueue(conn, [(wire.pack_shm_hello_ack(req_id, ok),
                              None)])

    def _handle_request(self, conn: _Conn, flags: int, req_id: int,
                        meta: bytes, payload: bytes) -> None:
        self.requests += 1
        stream = bool(flags & wire.FLAG_STREAM)
        with conn.lock:
            dup = req_id in conn.inflight
        if dup:
            # a duplicate id would overwrite the first entry and leave
            # one of the two completions unanswered — reject it before
            # anything is submitted (one io thread serves a connection,
            # so this check cannot race a concurrent insert)
            self._answer_error(
                conn, req_id, wire.ERR_BAD_REQUEST,
                f"request id {req_id} is already in flight on this "
                f"connection")
            return
        jinfo = rec = ctx = None
        rt = reqtrace.active()
        try:
            model_s, tenant, priority, deadline_ms, trace_s, descs, \
                seg = wire.unpack_request_meta(meta)
            # propagated context decodes even when THIS process is not
            # tracing (the journal still correlates); this front door
            # MINTS one only when tracing is on and none arrived
            if trace_s:
                ctx = reqtrace.parse_context(trace_s)
            if rt is not None:
                if ctx is None:
                    ctx = rt.mint()
                rec = rt.begin(ctx, transport=self.transport,
                               model=model_s or "")
            if self.journal is not None:
                jinfo = {"transport": self.transport,
                         "model": model_s or "",
                         "tenant": tenant or "",
                         "priority": priority or "",
                         "deadline_ms": deadline_ms,
                         "request_id": req_id,
                         "trace_id": ctx.trace_id if ctx else None,
                         "sizes": {d.name: int(d.nbytes)
                                   for d in descs}}
            # admission runs BEFORE tensor decode / model resolution
            # (the HTTP rule): a shed tenant's flood must not buy
            # io-thread decode time, and a malformed request still
            # spends its tenant's token
            reason = (self.tenants.admit(tenant or None,
                                         priority or None)
                      if self.tenants is not None else None)
            if rec is not None:
                rt.stage(ctx, "admission", rec["ts"],
                         rt.now_us() - rec["ts"])
            if reason is not None:
                self._c_shed.inc(model=model_s or "", reason=reason)
                self._journal_row(jinfo, reason)
                if rec is not None:
                    rt.finish(rec, reason)
                self._answer_error(
                    conn, req_id,
                    wire.ERR_TENANT_LIMIT if reason == "tenant_limit"
                    else wire.ERR_PRIORITY,
                    "tenant rate limit exceeded"
                    if reason == "tenant_limit" else
                    "shed by priority class under admission pressure")
                return
            t_dec = rt.now_us() if rec is not None else 0.0
            if seg is not None:
                # spkn-shm request: the payload lives in the client's
                # named segment; map it (cached per connection — the
                # ring reuses names) and view the tensors in place.
                # Batch formation copies rows into bucket buffers before
                # the reply, so the client reusing the slot after its
                # terminal reply can never race a live view.
                if not conn.shm_ok:
                    raise ValueError(
                        "FLAG_SHM request without a granted SHM_HELLO "
                        "on this connection")
                segobj = conn.shm_segs.get(seg)
                if segobj is None:
                    segobj = shm.attach(seg)
                    conn.shm_segs[seg] = segobj
                inputs = wire.tensors_from(descs, segobj.buf)
            else:
                inputs = wire.tensors_from(descs, payload)
                with self._byte_lock:
                    self.payload_rx_bytes += len(payload)
            # the outputs request rides the tensor table as a reserved
            # key (no frame-format change); pop it before the net sees
            # the payload
            inputs, outputs = pop_outputs(inputs)
            model = self.adapter.resolve(model_s or None)
            self.adapter.coerce(model, inputs)
            if rec is not None:
                rt.stage(ctx, "decode", t_dec, rt.now_us() - t_dec,
                         shm=seg is not None)
            deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                          else self.default_deadline_s)
            fut = self.adapter.submit(model, inputs, deadline_s,
                                      priority=priority or None,
                                      outputs=outputs, trace=ctx)
        except BaseException as e:
            ck, msg = _exception_to_err(e)
            self._journal_row(jinfo, ck[1])
            if rec is not None:
                rt.finish(rec, ck[1])
            self._answer_error(conn, req_id, ck, msg)
            return
        bound = time.monotonic() + (
            deadline_s + 5.0 if deadline_s is not None
            else _DEFAULT_WAIT_S)
        with conn.lock:
            if conn.closed:
                return
            conn.inflight[req_id] = (bound, fut, model, jinfo, rec)
        fut.add_done_callback(
            lambda f, c=conn, r=req_id, s=stream, m=model:
            self._complete(c, r, s, m, f))

    # -- completion (forward-worker / proxy threads) --------------------------

    def _complete(self, conn: _Conn, req_id: int, stream: bool,
                  model: str, fut) -> None:
        with conn.lock:
            entry = conn.inflight.pop(req_id, None)
        if entry is None:
            return  # reaped (already answered) or connection gone
        jinfo, rec = entry[3], entry[4]
        rt = reqtrace.active()
        exc = fut.exception()
        if exc is not None:
            ck, msg = _exception_to_err(exc)
            self._journal_row(jinfo, ck[1])
            if rt is not None and rec is not None:
                rt.finish(rec, ck[1])
            self._answer_error(conn, req_id, ck, msg)
            return
        t_reply = rt.now_us() if (rt is not None
                                  and rec is not None) else 0.0
        # queue wait: stamped on the batcher future at batch formation
        # (server.py) — rides the response meta so clients can split
        # tail latency into queueing vs compute
        qw = getattr(fut, "_spkn_queue_wait_s", None)
        qw_ms = None if qw is None else qw * 1e3
        out = {k: np.asarray(v) for k, v in fut.result().items()}
        items = None
        if conn.shm_ok and not stream and conn.shm_ring is not None:
            # spkn-shm response: copy the payload into a ring slot and
            # send only the descriptor table. A full ring (all slots
            # awaiting SHM_RELEASE) falls back to inline — the protocol
            # never blocks on the ring.
            descs, views, total = wire.build_table(out)
            slot = conn.shm_ring.acquire(total) if total else None
            if slot is not None:
                name, view = slot
                shm.copy_into(view, views)
                items = wire.pack_response(
                    req_id, model, self.adapter.step(model), out,
                    queue_wait_ms=qw_ms, shm_seg=name)
        if items is None:
            items = wire.pack_response(req_id, model,
                                       self.adapter.step(model), out,
                                       stream=stream,
                                       chunk_bytes=self.chunk_bytes,
                                       queue_wait_ms=qw_ms)
        self._journal_row(jinfo, "ok", queue_wait_ms=qw_ms)
        self._c_req.inc(code="200", transport=self.transport)
        self._enqueue(conn, items)
        if rt is not None and rec is not None:
            # pack + outbox enqueue; the socket write itself is async on
            # the io thread and belongs to the client's wire span
            rt.stage(rec["ctx"], "reply", t_reply,
                     rt.now_us() - t_reply, stream=stream)
            rt.finish(rec, "ok")

    def _journal_row(self, jinfo: Optional[dict], outcome: str,
                     queue_wait_ms: Optional[float] = None) -> None:
        """One JSONL row per answered request frame (--request-journal).
        Best-effort: a journal failure must never fail the data plane."""
        if jinfo is None or self.journal is None:
            return
        try:
            self.journal.metrics(0, kind="request", outcome=outcome,
                                 queue_wait_ms=queue_wait_ms, **jinfo)
        except Exception:
            pass

    # -- reply plumbing (any thread) ------------------------------------------

    def _answer_error(self, conn: _Conn, req_id: int,
                      code_kind: Tuple[int, str], msg: str,
                      close: bool = False) -> None:
        self._c_req.inc(code=str(code_kind[0]), transport=self.transport)
        if close:
            conn.close_after_flush = True
        self._enqueue(conn, [(wire.pack_error(req_id, code_kind, msg),
                              None)])

    def _enqueue(self, conn: _Conn,
                 items: List[Tuple[bytes, Optional[memoryview]]]) -> None:
        if conn.closed:
            return
        tx = 0
        with conn.lock:
            for head, view in items:
                if head:
                    conn.outbox.append((memoryview(head), True))
                    conn.copied_pending += len(head)
                if view is not None and len(view):
                    conn.outbox.append((view, False))
                    tx += len(view)
            conn.peak_copied = max(conn.peak_copied, conn.copied_pending)
            peak = conn.peak_copied
        if tx:
            with self._byte_lock:
                self.payload_tx_bytes += tx
        # the bench's buffer_bounded_by_chunk acceptance reads this
        # high-water mark: the max-update must not lose a racing larger
        # sample to an unsynchronized read-compare-write
        with self._active_lock:
            if peak > self.peak_buffered_bytes:
                self.peak_buffered_bytes = peak
        conn.loop.call_soon(lambda c=conn: c.loop.arm_write(c))


# ---------------------------------------------------------------------------
# the matching client
# ---------------------------------------------------------------------------

def _parse_address(address) -> Tuple[str, int]:
    """(host, port) | 'host:port' | 'spkn://host:port' -> (host, port)."""
    if isinstance(address, (tuple, list)):
        return str(address[0]), int(address[1])
    s = str(address)
    for scheme in ("spkn://", "tcp://", "http://"):
        if s.startswith(scheme):
            s = s[len(scheme):]
    s = s.rstrip("/")
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"binary address {address!r} is not host:port")
    return host, int(port)


class BinaryClient:
    """Keep-alive, pipelined client for the binary frame transport.

    `submit` writes a request frame and returns its request-id without
    waiting; `collect` reads frames (in whatever completion order the
    server chose) until that id resolves — so N submits followed by N
    collects is a pipelined burst on one connection. `infer` is the
    one-shot convenience and records `last_timing` (first-byte /
    complete, seconds from submit; plus the server-reported
    `queue_wait_ms` when known) — the bench reads it.

    spkn-shm: `use_shm=None` auto-offers the shared-memory transport to
    loopback servers (SHM_HELLO handshake at connect); the server's
    same-host nonce check decides. Granted, tensor payloads ride named
    segments in both directions and zero payload bytes cross the
    socket; denied (remote peer, shm-less build), everything falls back
    inline transparently.

    Thread-safety: one connection, one user thread — except `cancel`,
    which the router's hedge scheduler may call from its own thread
    (all socket WRITES serialize on `_wlock`; reads stay single-owner).
    The thread-cached `binary_infer` below gives each thread its own
    client."""

    def __init__(self, host, port: Optional[int] = None,
                 timeout: float = 30.0,
                 use_shm: Optional[bool] = None):
        if port is None:
            host, port = _parse_address(host)
        self.addr = (host, int(port))
        self.timeout = float(timeout)
        self.sock = socket.create_connection(self.addr, timeout=timeout)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._rbuf = bytearray()
        self._ids = itertools.count(1)
        self._wlock = threading.Lock()
        # req_id -> reassembly state (supports out-of-order completion)
        self._pending: Dict[int, Dict[str, Any]] = {}
        self.last_timing: Optional[Dict[str, float]] = None
        self.closed = False
        # tensor payload bytes that crossed the socket, per direction
        self.payload_tx_bytes = 0
        self.payload_rx_bytes = 0
        self._shm_granted: Optional[bool] = None
        self._ring = None   # request-segment ring (ours; slots freed on
        #                     the rid's terminal reply)
        self._segs: Dict[str, Any] = {}  # attached response segments
        if use_shm is None:
            use_shm = host in ("127.0.0.1", "localhost", "::1")
        if use_shm and shm.shm_available():
            self._shm_handshake()

    def _shm_handshake(self) -> None:
        """Offer spkn-shm: write the same-host nonce, send SHM_HELLO,
        block (briefly) for the ack. Any failure — old server, remote
        filesystem, timeout — quietly leaves the connection inline."""
        path, nonce = shm.write_nonce()
        try:
            rid = next(self._ids)
            self.sock.settimeout(self.timeout)
            with self._wlock:
                self.sock.sendall(wire.pack_shm_hello(rid, path, nonce))
            deadline = time.perf_counter() + min(self.timeout, 5.0)
            while self._shm_granted is None:
                self._read_frame(deadline)
        except (OSError, TimeoutError, ConnectionError, wire.WireError):
            self._shm_granted = False
        finally:
            shm.cleanup_nonce(path)
        if self._shm_granted:
            self._ring = shm.ShmRing()

    def close(self) -> None:
        self.closed = True
        if self._ring is not None:
            self._ring.close()
            self._ring = None
        for seg in self._segs.values():
            try:
                seg.close()
            except Exception:
                pass  # a live tensor view pins the mapping; leave it
        self._segs.clear()
        try:
            self.sock.close()
        except OSError:
            pass

    # -- submit side ---------------------------------------------------------

    def submit(self, payload: Dict[str, np.ndarray],
               model: str = "", deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: Optional[str] = None,
               stream: bool = False,
               outputs: Optional[Tuple[str, ...]] = None,
               trace=None) -> int:
        rid = next(self._ids)
        # trace context: accepted as a TraceContext or its encoded wire
        # string; rides the REQUEST meta, and the local tracer (when on)
        # records this client's wait as the `wire:binary` span that
        # assembly matches against the server's request row
        ctx = reqtrace.parse_context(trace) if trace is not None else None
        rt = reqtrace.active() if ctx is not None else None
        arrays = {k: np.asarray(v)
                  for k, v in encode_outputs(payload, outputs).items()}
        seg_name = None
        if self._ring is not None:
            # spkn-shm: copy the payload into a ring slot; the frame
            # then carries only the descriptor table. Ring full -> None
            # -> this request goes inline (never blocks).
            descs, pviews, total = wire.build_table(arrays)
            slot = self._ring.acquire(total) if total else None
            if slot is not None:
                seg_name, view = slot
                shm.copy_into(view, pviews)
        head, views = wire.pack_request(
            rid, model, arrays,
            deadline_ms=None if deadline_s is None else deadline_s * 1e3,
            tenant=tenant, priority=priority, stream=stream,
            shm_seg=seg_name,
            trace=None if ctx is None else ctx.encoded())
        self._pending[rid] = {"t_submit": time.perf_counter(),
                              "t_first": None, "done": False,
                              "outputs": None, "exc": None,
                              "buf": None, "descs": None, "got": 0,
                              "total": 0, "model": None, "step": None,
                              "queue_wait_ms": None,
                              "shm_seg": seg_name,
                              "trace": ctx if rt is not None else None,
                              "t_submit_us": (rt.now_us()
                                              if rt is not None
                                              else 0.0)}
        # _fill shrinks the socket timeout toward a deadline; a cached
        # client's NEXT send must not inherit that sliver
        self.sock.settimeout(self.timeout)
        with self._wlock:
            self.sock.sendall(head)
            for v in views:
                self.sock.sendall(v)
        self.payload_tx_bytes += sum(len(v) for v in views)
        return rid

    def cancel(self, rid: int) -> None:
        """Fire-and-forget CANCEL for an in-flight request id (the
        hedging router's losing leg). If the server's batcher still
        holds the request, the rid resolves with the typed `cancelled`
        error; otherwise the normal reply arrives — either way exactly
        one terminal frame. Safe to call from a thread other than the
        connection's owner (write-locked); send failures are swallowed
        (cancel is an optimization, never a correctness dependency)."""
        if self.closed or rid not in self._pending:
            return
        try:
            with self._wlock:
                self.sock.sendall(wire.pack_cancel(rid))
        except OSError:
            pass

    # -- receive side --------------------------------------------------------

    def _fill(self, n: int, deadline: float) -> None:
        """Block until the read buffer holds >= n bytes."""
        while len(self._rbuf) < n:
            budget = deadline - time.perf_counter()
            if budget <= 0:
                raise TimeoutError(
                    f"binary_infer: no reply within the timeout "
                    f"({self.timeout:.1f}s)")
            self.sock.settimeout(min(budget, self.timeout))
            try:
                data = self.sock.recv(1 << 18)
            except socket.timeout:
                continue
            if not data:
                raise ConnectionError(
                    "binary transport: server closed the connection")
            self._rbuf += data

    def _read_frame(self, deadline: float) -> None:
        self._fill(wire.HEADER_LEN, deadline)
        ftype, flags, rid, meta_len, payload_len = \
            wire.parse_header(self._rbuf)
        inline = 0 if (ftype == wire.T_RESPONSE
                       and flags & wire.FLAG_STREAM) else payload_len
        self._fill(wire.HEADER_LEN + meta_len + inline, deadline)
        meta = bytes(self._rbuf[wire.HEADER_LEN:
                                wire.HEADER_LEN + meta_len])
        payload = bytes(self._rbuf[wire.HEADER_LEN + meta_len:
                                   wire.HEADER_LEN + meta_len + inline])
        del self._rbuf[:wire.HEADER_LEN + meta_len + inline]
        now = time.perf_counter()
        if ftype == wire.T_ERROR:
            code, kind, msg = wire.unpack_error_meta(meta)
            if rid == 0:
                # connection-level: the stream is done for — but the
                # error is still the server's TYPED answer (e.g. 503
                # over_capacity must surface as NoReplicaError exactly
                # as it would over HTTP, so router proxies stay
                # transport-blind)
                self.close()
                raise_for_error(code, kind, msg)
            st = self._pending.get(rid)
            if st is not None:
                st["exc"] = (code, kind, msg)
                st["done"] = True
                if st["t_first"] is None:
                    st["t_first"] = now
            return
        if ftype == wire.T_SHM_HELLO:
            # the handshake ack (FLAG_LAST); rid is the hello's own id
            try:
                self._shm_granted = wire.unpack_shm_hello_ack_meta(meta)
            except wire.WireError:
                self._shm_granted = False
            return
        st = self._pending.get(rid)
        if st is None:
            return  # reply to an abandoned id: drop it
        if st["t_first"] is None:
            st["t_first"] = now
        if ftype == wire.T_RESPONSE:
            model, step, queue_wait_ms, descs, seg = \
                wire.unpack_response_meta(meta)
            st["model"], st["step"], st["descs"] = model, step, descs
            st["queue_wait_ms"] = queue_wait_ms
            if flags & wire.FLAG_SHM and seg is not None:
                # spkn-shm response: map the server's segment, copy the
                # tensors OUT (np.array), then release the slot — the
                # returned arrays must outlive the server's reuse of it
                segobj = self._segs.get(seg)
                if segobj is None:
                    segobj = shm.attach(seg)
                    self._segs[seg] = segobj
                outs = wire.tensors_from(descs, segobj.buf)
                st["outputs"] = {k: np.array(v)
                                 for k, v in outs.items()}
                st["done"] = True
                try:
                    with self._wlock:
                        self.sock.sendall(wire.pack_shm_release(seg))
                except OSError:
                    pass  # a dead socket surfaces on the next read
            elif flags & wire.FLAG_STREAM:
                st["total"] = payload_len
                st["buf"] = bytearray(payload_len)
                if payload_len == 0:
                    st["outputs"] = wire.tensors_from(descs, b"")
                    st["done"] = True
            else:
                st["outputs"] = wire.tensors_from(descs, payload)
                st["done"] = True
                self.payload_rx_bytes += len(payload)
        elif ftype == wire.T_CHUNK:
            off = wire.unpack_chunk_meta(meta)
            if st["buf"] is None or off + len(payload) > st["total"]:
                raise wire.WireError(
                    f"chunk for request {rid} outside its announced "
                    f"payload")
            st["buf"][off:off + len(payload)] = payload
            st["got"] += len(payload)
            self.payload_rx_bytes += len(payload)
            if st["got"] >= st["total"] or flags & wire.FLAG_LAST:
                if st["got"] < st["total"]:
                    raise wire.WireError(
                        f"stream for request {rid} ended {st['total'] - st['got']} "
                        f"bytes short")
                # frombuffer views the bytearray directly — no full-blob
                # copy on the client side of the zero-copy wire either
                st["outputs"] = wire.tensors_from(st["descs"],
                                                  st["buf"])
                st["done"] = True
        # any other type from a server is a protocol error
        else:
            raise wire.WireError(f"unexpected frame type {ftype} "
                                 f"from server")

    def collect(self, rid: int, timeout: Optional[float] = None
                ) -> Dict[str, np.ndarray]:
        """Read until request `rid` resolves (other ids' replies are
        absorbed into their own pending states — pipelining)."""
        deadline = time.perf_counter() + (timeout if timeout is not None
                                          else self.timeout)
        while True:
            st = self._pending.get(rid)
            if st is None:
                raise KeyError(f"unknown request id {rid}")
            if st["done"]:
                self._pending.pop(rid)
                # terminal reply: the server is done reading our shm
                # request slot (formation copied the rows before the
                # forward) — free it for the next submit
                if st["shm_seg"] is not None and self._ring is not None:
                    self._ring.release(st["shm_seg"])
                self.last_timing = {
                    "t_first_byte_s": st["t_first"] - st["t_submit"],
                    "t_complete_s":
                        time.perf_counter() - st["t_submit"],
                    "queue_wait_ms": st["queue_wait_ms"]}
                ctx = st.get("trace")
                if ctx is not None:
                    rt = reqtrace.active()
                    if rt is not None:
                        # the client-side wire span (submit -> terminal
                        # frame, typed errors included): its span id
                        # equals the server request row's — the hop
                        # assembly stitches and clock-aligns on
                        rt.stage(ctx, "wire:binary", st["t_submit_us"],
                                 rt.now_us() - st["t_submit_us"],
                                 kind="client",
                                 shm=st["shm_seg"] is not None)
                if st["exc"] is not None:
                    raise_for_error(*st["exc"])
                return st["outputs"]
            self._read_frame(deadline)

    def infer(self, payload: Dict[str, np.ndarray], model: str = "",
              deadline_s: Optional[float] = None,
              tenant: Optional[str] = None,
              priority: Optional[str] = None, stream: bool = False,
              timeout: Optional[float] = None,
              outputs: Optional[Tuple[str, ...]] = None,
              trace=None) -> Dict[str, np.ndarray]:
        rid = self.submit(payload, model=model, deadline_s=deadline_s,
                          tenant=tenant, priority=priority,
                          stream=stream, outputs=outputs, trace=trace)
        return self.collect(rid, timeout=timeout)


# -- thread-cached convenience client (the proxy/bench entry point) ----------

_client_cache = threading.local()
MAX_CACHED_CLIENTS = 8  # per thread; LRU-evicted past this


def _cached_client(host: str, port: int, timeout: float,
                   use_shm: Optional[bool] = None) -> BinaryClient:
    # use_shm is part of the key: an A/B driver forcing the transport
    # per call must never be handed a cached client negotiated the
    # other way
    cli = lru_cache_get(
        _client_cache, "clients", (host, port, use_shm),
        lambda: BinaryClient(host, port, timeout=timeout,
                             use_shm=use_shm),
        MAX_CACHED_CLIENTS)
    cli.timeout = float(timeout)
    return cli


def _drop_client(host: str, port: int,
                 use_shm: Optional[bool] = None) -> None:
    lru_cache_drop(_client_cache, "clients", (host, port, use_shm))


def binary_infer(address, model: str,
                 payload: Dict[str, np.ndarray],
                 deadline_s: Optional[float] = None,
                 timeout: float = 30.0,
                 tenant: Optional[str] = None,
                 priority: Optional[str] = None,
                 stream: bool = False,
                 cancel_box: Optional[dict] = None,
                 use_shm: Optional[bool] = None,
                 outputs: Optional[Tuple[str, ...]] = None,
                 trace=None) -> Dict[str, np.ndarray]:
    """One inference request over the binary transport (thread-cached
    keep-alive client — the `http_infer` counterpart the router's
    binary remote replicas and the bench drivers ride). The http_infer
    cache rules apply: ANY failure mid-exchange evicts this address's
    cached client (never re-use a stream in an unknown state); a stale
    server-closed socket gets ONE retry on a fresh connection.

    `cancel_box`: when given, a best-effort `cancel` callable for THIS
    request is stored under "cancel" once it is on the wire — the
    hedging router calls it (from its scheduler thread) to cancel the
    losing leg."""
    host, port = _parse_address(address)
    for attempt in (0, 1):
        cli = _cached_client(host, port, timeout, use_shm)
        try:
            rid = cli.submit(payload, model=model,
                             deadline_s=deadline_s, tenant=tenant,
                             priority=priority, stream=stream,
                             outputs=outputs, trace=trace)
            if cancel_box is not None:
                cancel_box["cancel"] = \
                    lambda c=cli, r=rid: c.cancel(r)
            return cli.collect(rid, timeout=timeout)
        except (TenantLimitError, QueueFullError, DeadlineExpiredError,
                RequestCancelledError,
                NoReplicaError, UnknownModelError, ValueError):
            # typed sheds arrived ON the stream, which is usually still
            # good — except a connection-level frame (rid 0, e.g.
            # over_capacity), whose delivery closed the client
            if cli.closed:
                _drop_client(host, port, use_shm)
            raise
        except TimeoutError:
            _drop_client(host, port, use_shm)
            raise  # a slow server is not a stale socket: no retry
        except ConnectionError as e:
            # a server-closed cached connection: retry once fresh
            _drop_client(host, port, use_shm)
            if attempt:
                raise ConnectionError(
                    f"binary_infer to {address}: {e}") from e
        except BaseException:
            _drop_client(host, port, use_shm)
            raise
