"""spkn-shm: the shared-memory local transport for colocated replicas.

The binary wire (wire.py) already made the descriptor table cheap — a
few hundred bytes of header/meta per request. What remains on the hot
LOCAL hop (router process -> SubprocessReplicaProvider child on the same
box) is the tensor payload itself: every request and response still
memcpy's its bytes INTO the kernel socket path on one side and OUT of it
on the other. For colocated processes that round trip is pure waste —
the bytes never leave the machine.

This module moves the payload into `multiprocessing.shared_memory`
segments: the descriptor table still travels over the socket (framing,
ordering, and error handling stay exactly the wire protocol's), but the
payload bytes are written once into a named segment and read in place by
the peer. Zero tensor bytes through the socket in either direction —
pinned by byte counters in BENCH_TAIL's shm arm. (The request TRACE
context needs no shm treatment: it is a <50-byte str8 in the REQUEST
meta, so it rides the socket-side descriptor table unchanged and spans
on both ends of an shm hop join the same trace.)

Three pieces:

- `ShmRing`: a per-connection ring of REUSABLE named segments. A sender
  acquires a slot, copies the payload in, names the slot in the frame
  meta (FLAG_SHM), and the receiver maps it by name. Slots are recycled,
  not allocated per request — segment create/unlink is a syscall pair
  that would eat the win at high rps. Request slots are released when
  the terminal reply for the request id arrives (by then the server has
  copied the rows into its bucket buffers); response slots are released
  by an explicit SHM_RELEASE frame once the client has copied the
  tensors out. A full ring (all slots in flight) is not an error: the
  sender falls back to inline payload for that frame.

- The same-host proof: `write_nonce` / `check_nonce`. Before granting
  FLAG_SHM the server must know the client really shares its filesystem
  and memory — a remote peer that happens to guess segment names must
  get inline fallback, not garbage reads. The client writes a random
  nonce to a private temp file and sends (path, nonce) in SHM_HELLO; the
  server grants shm only if reading the path yields the nonce. A remote
  client's path either doesn't exist on the server's filesystem or holds
  different bytes — the handshake degrades to inline transparently.

- `sweep_orphans`: segments survive their creator (that is the point of
  named shm), so a kill -9'd peer leaks its ring in /dev/shm. Every
  segment name embeds its creator pid; the sweep (run at frontend
  startup) unlinks any spkn segment whose pid is dead. A replica
  restarting after a crash cleans up after its predecessor before
  serving a single request.
"""
from __future__ import annotations

import os
import secrets
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

try:  # gate: some minimal builds ship Python without _posixshmem
    from multiprocessing import shared_memory as _shm_mod
    from multiprocessing import resource_tracker as _rt
    HAVE_SHM = True
except ImportError:  # pragma: no cover - exercised only on such builds
    _shm_mod = None
    _rt = None
    HAVE_SHM = False

if HAVE_SHM:
    class _Segment(_shm_mod.SharedMemory):
        """SharedMemory whose finalizer tolerates still-exported buffer
        views. A zero-copy tensor view can outlive its connection (the
        batcher's request objects are GC'd lazily); stdlib __del__ then
        sprays BufferError through the GC. The mapping is reclaimed at
        process exit either way — the finalizer must stay quiet."""

        def __del__(self):  # pragma: no cover - GC-timing dependent
            try:
                super().__del__()
            except BufferError:
                pass
else:  # pragma: no cover
    _Segment = None

SEG_PREFIX = "spkn_shm"

# segment names THIS process created (rings). An attach of our own
# segment (client and server colocated in one process, e.g. tests) must
# not untrack it — the tracker entry is a set keyed by name, and the
# creator's unlink() still needs it to balance.
_OWNED: set = set()
_owned_lock = threading.Lock()


def shm_available() -> bool:
    """True when the interpreter can create POSIX shared memory."""
    return HAVE_SHM


def _untrack(name: str) -> None:
    """Detach a segment from this process's resource tracker (ATTACHER
    side only). On 3.10 attaching registers the segment exactly like
    creating it, so the first exiting attacher's tracker would unlink
    the mapping out from under every other process. Creators stay
    tracked: their explicit unlink() balances the register, and a
    creator that dies without unlinking gets cleaned by its tracker —
    or, failing that, by `sweep_orphans`. Private API, so best effort:
    a tracker that has changed shape just means noisier exits."""
    try:
        _rt.unregister("/" + name, "shared_memory")
    except Exception:
        pass


def attach(name: str):
    """Map an existing segment by name (receiver side). Raises
    FileNotFoundError if the sender's segment is gone (e.g. swept)."""
    seg = _Segment(name=name, create=False)
    with _owned_lock:
        ours = name in _OWNED
    if not ours:
        _untrack(seg.name)
    return seg


class ShmRing:
    """A ring of reusable named shared-memory segments (one per
    direction per connection). Thread-safe; `acquire` returns None when
    every slot is in flight or the payload exceeds `max_bytes` — the
    caller sends that frame inline and the protocol never blocks on the
    ring."""

    def __init__(self, n_slots: int = 4, slot_bytes: int = 1 << 20,
                 max_bytes: int = 256 << 20):
        self.n_slots = max(1, int(n_slots))
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._uid = secrets.token_hex(4)
        self._gen = 0
        # slot -> (SharedMemory, in_use); grown lazily on first acquire
        self._slots: Dict[int, Tuple[object, bool]] = {}
        self._by_name: Dict[str, int] = {}
        self._init_bytes = max(4096, int(slot_bytes))
        self._closed = False

    def _make(self, slot: int, nbytes: int):
        # generation in the name: a resized slot gets a FRESH name, so a
        # peer still holding the old (now unlinked) mapping can never
        # confuse it with the new segment
        self._gen += 1
        name = (f"{SEG_PREFIX}_{os.getpid()}_{self._uid}_"
                f"{slot}g{self._gen}")
        seg = _Segment(name=name, create=True, size=nbytes)
        with _owned_lock:
            _OWNED.add(name)
        return seg

    def acquire(self, nbytes: int) -> Optional[Tuple[str, memoryview]]:
        """A free slot of >= nbytes as (segment name, writable view of
        its first nbytes), or None (ring full / payload too big / ring
        closed) — in which case the caller sends inline."""
        if nbytes > self.max_bytes:
            return None
        nbytes = max(1, int(nbytes))
        with self._lock:
            if self._closed:
                return None
            free = None
            for slot in range(self.n_slots):
                seg, in_use = self._slots.get(slot, (None, False))
                if in_use:
                    continue
                if seg is not None and seg.size >= nbytes:
                    self._slots[slot] = (seg, True)
                    self._by_name[seg.name] = slot
                    return seg.name, seg.buf[:nbytes]
                if free is None:
                    free = slot
            if free is None:
                return None
            old, _ = self._slots.get(free, (None, False))
            if old is not None:
                self._by_name.pop(old.name, None)
                with _owned_lock:
                    _OWNED.discard(old.name)
                # unlink BEFORE close: a still-exported view (a reader
                # mid-copy) makes close() raise BufferError, and the
                # name must come free regardless — the mapping itself
                # is reclaimed when the last view dies
                try:
                    old.unlink()
                except Exception:
                    pass
                try:
                    old.close()
                except Exception:
                    pass
            want = max(self._init_bytes, nbytes)
            seg = self._make(free, want)
            self._slots[free] = (seg, True)
            self._by_name[seg.name] = free
            return seg.name, seg.buf[:nbytes]

    def release(self, name: str) -> bool:
        """Mark the named slot free for reuse. Unknown names are ignored
        (a release can race a resize) — returns whether it hit."""
        with self._lock:
            slot = self._by_name.get(name)
            if slot is None:
                return False
            seg, _ = self._slots[slot]
            self._slots[slot] = (seg, False)
            return True

    def in_flight(self) -> int:
        with self._lock:
            return sum(1 for _, used in self._slots.values() if used)

    def close(self) -> None:
        """Unlink every segment (connection teardown). Peers still
        holding mappings keep valid memory until they close — unlink
        only removes the name."""
        with self._lock:
            self._closed = True
            slots, self._slots = self._slots, {}
            self._by_name.clear()
        for seg, _ in slots.values():
            with _owned_lock:
                _OWNED.discard(seg.name)
            # unlink first (see the resize path): the name MUST come
            # free even when a peer's view keeps the mapping exported
            try:
                seg.unlink()
            except Exception:
                pass
            try:
                seg.close()
            except Exception:
                pass


# -- the same-host proof ------------------------------------------------------

def write_nonce(dir: Optional[str] = None) -> Tuple[str, str]:
    """Client side of the handshake: (path, nonce). The file is 0600 in
    a fresh private directory — possession of the PATH is not the proof,
    reading the matching BYTES through the server's own filesystem is."""
    d = tempfile.mkdtemp(prefix="spkn-shm-", dir=dir)
    nonce = secrets.token_hex(16)
    path = os.path.join(d, "nonce")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        os.write(fd, nonce.encode("ascii"))
    finally:
        os.close(fd)
    return path, nonce


def check_nonce(path: str, nonce: str) -> bool:
    """Server side: grant shm only if the client's claimed file really
    holds the claimed nonce ON THIS HOST. Any failure (missing path, a
    remote peer's foreign filesystem, junk) is a quiet False — the
    connection proceeds inline."""
    if not nonce or len(nonce) > 256:
        return False
    try:
        with open(path, "rb") as f:
            return f.read(257).decode("ascii", "replace") == nonce
    except OSError:
        return False


def cleanup_nonce(path: str) -> None:
    """Remove the nonce file + its private dir (client, post-handshake)."""
    try:
        os.unlink(path)
        os.rmdir(os.path.dirname(path))
    except OSError:
        pass


# -- orphan reclamation -------------------------------------------------------

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # alive, someone else's
        return True
    except OSError:
        return False
    return True


def sweep_orphans(shm_dir: str = "/dev/shm") -> List[str]:
    """Unlink every spkn segment whose creator pid is dead (kill -9
    leaves the ring linked in /dev/shm forever otherwise). Run at
    frontend startup, BEFORE any ring exists. Returns the names swept.
    Non-Linux (no /dev/shm listing) is a quiet no-op — segments there
    age out with the OS's own lifecycle."""
    swept: List[str] = []
    if not HAVE_SHM or not os.path.isdir(shm_dir):
        return swept
    prefix = SEG_PREFIX + "_"
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return swept
    for name in names:
        if not name.startswith(prefix):
            continue
        rest = name[len(prefix):].split("_", 1)
        try:
            pid = int(rest[0])
        except (ValueError, IndexError):
            continue
        if _pid_alive(pid):
            continue
        try:
            # attach registers with OUR tracker (3.10); unlink's
            # unregister balances it — no _untrack here
            seg = _Segment(name=name, create=False)
            seg.close()
            seg.unlink()
            swept.append(name)
        except OSError:
            continue
    return swept


def copy_into(view: memoryview, views) -> int:
    """Concatenate payload byte views into a segment view (the sender's
    one copy). Returns bytes written."""
    off = 0
    for v in views:
        n = len(v)
        view[off:off + n] = v
        off += n
    return off
