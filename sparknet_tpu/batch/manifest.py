"""The batch job's resumable work-unit manifest.

Commit protocol (the sharded-checkpoint writers' manifest-LAST rule,
utils/checkpoint.py, generalized to a long-running job):

  1. a unit's rows are computed and its `part-<uid>.npz` is written
     ATOMICALLY (store.write_bytes: bucket finalize / local
     temp+rename);
  2. only then is the unit recorded in `MANIFEST.json`, itself
     rewritten atomically.

So the manifest is always a TRUE inventory: every unit it lists has a
complete part object behind it. A driver killed -9 between (1) and (2)
leaves an orphan part — the resume pass treats the manifest as the only
authority, redoes that unit, and the atomic rewrite of the part makes
the redo invisible (never a torn row, never a doubled one). Units are
disjoint row ranges of the input, so "every manifest unit exactly once"
IS row-level exactly-once.

The manifest also pins the job's IDENTITY (input url, row count, unit
size, model, output blobs): a resume against a different input or plan
must fail loudly, not silently interleave two jobs' rows.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from . import store

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_VERSION = 1


def plan_units(n_rows: int, unit_rows: int) -> List[Tuple[int, int]]:
    """Disjoint [start, stop) row ranges covering the input (the
    member-index split: contiguous, last unit ragged)."""
    if n_rows <= 0:
        raise ValueError(f"n_rows must be > 0 (got {n_rows})")
    if unit_rows <= 0:
        raise ValueError(f"unit_rows must be > 0 (got {unit_rows})")
    return [(lo, min(lo + unit_rows, n_rows))
            for lo in range(0, n_rows, unit_rows)]


def part_name(uid: int) -> str:
    return f"part-{uid:05d}.npz"


def new_manifest(job_id: str, input_url: str, n_rows: int,
                 unit_rows: int, model: str,
                 outputs: Tuple[str, ...]) -> Dict[str, Any]:
    units = plan_units(n_rows, unit_rows)
    return {
        "version": MANIFEST_VERSION,
        "job_id": job_id,
        "input": input_url,
        "n_rows": int(n_rows),
        "unit_rows": int(unit_rows),
        "n_units": len(units),
        "model": model,
        "outputs": list(outputs),
        "done": False,
        # uid (as str: JSON keys) -> completion record; ABSENT = pending
        "units": {},
    }


def save_manifest(out_dir: str, m: Dict[str, Any]) -> None:
    data = json.dumps(m, indent=1, sort_keys=True).encode()
    store.write_bytes(store.join(out_dir, MANIFEST_NAME), data)


def load_manifest(out_dir: str) -> Optional[Dict[str, Any]]:
    url = store.join(out_dir, MANIFEST_NAME)
    if not store.exists(url):
        return None
    m = json.loads(store.read_bytes(url).decode())
    if m.get("version") != MANIFEST_VERSION:
        raise ValueError(
            f"manifest {url} has version {m.get('version')!r}; this "
            f"driver speaks {MANIFEST_VERSION}")
    return m


def check_resume(m: Dict[str, Any], input_url: str, n_rows: int,
                 unit_rows: int, model: str,
                 outputs: Tuple[str, ...]) -> None:
    """A resume must be the SAME job: same input identity and the same
    unit plan. Anything else would interleave two jobs' rows under one
    manifest — fail loudly instead."""
    want = {"input": input_url, "n_rows": int(n_rows),
            "unit_rows": int(unit_rows), "model": model,
            "outputs": list(outputs)}
    got = {k: m.get(k) for k in want}
    if got != want:
        diffs = {k: (got[k], want[k]) for k in want if got[k] != want[k]}
        raise ValueError(
            f"manifest does not match this job (resume would mix "
            f"outputs); differing fields (manifest, requested): {diffs}")


def pending_units(m: Dict[str, Any]) -> List[Tuple[int, int, int]]:
    """(uid, start, stop) for every unit the manifest does NOT record
    as complete — the resume worklist."""
    done = set(int(k) for k in m["units"])
    return [(uid, lo, hi)
            for uid, (lo, hi) in enumerate(
                plan_units(m["n_rows"], m["unit_rows"]))
            if uid not in done]


def record_unit(m: Dict[str, Any], uid: int, lo: int, hi: int,
                nbytes: int, replica: str, attempts: int) -> None:
    m["units"][str(uid)] = {
        "start": int(lo), "stop": int(hi), "rows": int(hi - lo),
        "part": part_name(uid), "bytes": int(nbytes),
        "replica": replica, "attempts": int(attempts),
    }
    if len(m["units"]) == m["n_units"]:
        m["done"] = True
