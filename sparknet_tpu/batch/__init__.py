"""`sparknet_tpu.batch` — bulk inference at fleet scale (the r14
subsystem; SparkNet's FeaturizerApp grown from a single-process demo
into a fleet workload).

A batch job is a dataset swept through the serving fleet as a SCAVENGER
tenant: every request goes out `priority=low`, `tenant=batch`, so the
admission stack (serve/admission.py) sheds it FIRST whenever online
traffic needs the capacity — the job soaks idle cycles, it never buys
them at the online SLO's expense. The fleet side of the bargain lives in
fleet/policy.py: scavenger backlog is excluded from the autoscaler's
demand signals (the fleet must not grow to chase work that exists to
fill slack), and a `batch_starvation_s` clock bounds how long sustained
pressure may keep the door welded shut.

  - `manifest.py`: the work-unit plan + resumable job manifest with
    manifest-LAST commit semantics (the sharded-checkpoint writers'
    rule): each unit's `part-*.npz` is fully written before the
    `MANIFEST.json` row that makes it count, so a kill -9 at ANY point
    resumes from completed units only — never a torn or double row.
  - `store.py`: one read/write/exists surface over local paths and
    gs:// | s3:// buckets (riding the data/gcs.py, data/s3.py clients;
    local writes are temp+rename atomic to match the buckets' atomic
    object semantics).
  - `driver.py`: the `sparknet-batch` console entry — shards the input
    into units, dispatches them across the replica fleet over the
    binary transport (chunked streaming replies), retries unit failures
    with full jitter on a DIFFERENT replica (a replica death mid-unit
    is a retry, not a job failure), and reports fleet-aggregate rows/s
    and cost-per-million-embeddings.
"""
from .driver import BatchConfig, BatchDriver, main
from .manifest import (MANIFEST_NAME, load_manifest, new_manifest,
                       part_name, pending_units, plan_units,
                       save_manifest)
from .store import (delete, exists, is_bucket, join, list_names,
                    read_bytes, write_bytes)

__all__ = [
    "BatchConfig", "BatchDriver", "main",
    "MANIFEST_NAME", "plan_units", "new_manifest", "load_manifest",
    "save_manifest", "pending_units", "part_name",
    "read_bytes", "write_bytes", "exists", "delete", "list_names",
    "join", "is_bucket",
]
