"""`sparknet-batch` — the bulk-inference driver (module doc in
__init__.py; manifest/commit semantics in manifest.py).

Shape of a run:

  input npz (local / gs:// / s3://)
    -> ArrayDataset (aligned field check)
    -> work units: disjoint [start, stop) row ranges (manifest.plan_units)
    -> `concurrency` units in flight at once, each dispatched WHOLE to
       one replica over the binary transport: per-row requests
       pipelined `window` deep on one connection (the PR 12 chunked
       CHUNK-frame path carries the replies), every request
       `tenant=batch`, `priority=low`, with the named output blobs
       riding the per-request outputs route (serve/server.py)
    -> part-<uid>.npz written atomically, THEN the manifest row
       (manifest-last: kill -9 anywhere resumes exactly-once)

Failure policy — the scavenger contract:

  - admission sheds (priority / tenant_limit / queue_full / deadline)
    are BACKPRESSURE, not failures: the unit backs off with full jitter
    and retries on the next replica, forever. Sustained pressure cannot
    strand the job because the fleet controller's batch-starvation
    relief (fleet/policy.py) re-opens the door within
    `batch_max_starvation_s`.
  - transport deaths (ConnectionError: a replica kill -9 mid-unit) and
    timeouts are RETRIES on a different replica, counted against
    `max_attempts` — a job fails only when every replica refuses a unit
    `max_attempts` times over.
"""
from __future__ import annotations

import argparse
import io
import json
import sys
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..data.dataset import ArrayDataset
from ..data.gcs import retry_delay
from ..obs import MetricsRegistry, StatusServer
from ..obs import reqtrace
from ..serve.batcher import DeadlineExpiredError, QueueFullError
from ..serve.binary_frontend import BinaryClient
from ..utils.heartbeat import HeartbeatWriter
from ..utils.logger import Logger
from . import manifest as mf
from . import store

#: sheds that mean "not now", never "broken" — retried without limit.
#: QueueFullError covers its Priority/TenantLimit subtypes; a deadline
#: expiry on a low request IS the admission stack aging it out under
#: pressure, the same backpressure by another door.
BACKPRESSURE_ERRORS = (QueueFullError, DeadlineExpiredError)


@dataclass
class BatchConfig:
    """Knobs for one batch job (the `sparknet-batch` CLI mirrors
    these)."""

    input: str                      # npz url: local / gs:// / s3://
    output: str                     # output dir/prefix (parts+manifest)
    replicas: List[str]             # binary frontend addresses
    model: str = ""                 # "" = the replica's sole model
    outputs: Tuple[str, ...] = ()   # named blobs ("" -> lane default)
    unit_rows: int = 64             # rows per work unit
    window: int = 16                # pipelined requests per connection
    concurrency: int = 2            # units in flight across the fleet
    tenant: str = "batch"
    priority: str = "low"
    deadline_s: Optional[float] = 10.0   # per-request answer-by bound
    request_timeout_s: float = 30.0
    max_attempts: int = 6           # HARD failures per unit (not sheds)
    use_shm: bool = False           # spkn-shm to colocated replicas.
    # Off by default: a bulk driver is built to be kill -9'd, and every
    # killed connection would orphan a /dev/shm segment until the next
    # frontend sweep; the unit pipeline amortizes TCP fine.
    backoff_cap_s: float = 2.0      # full-jitter retry sleep ceiling
    pace_s: float = 0.0             # sleep between unit starts (chaos)
    job_id: Optional[str] = None    # default: derived fresh per job
    cost_per_replica_hour: float = 0.0   # $ -> cost_per_million
    jsonl_path: Optional[str] = None
    heartbeat_path: Optional[str] = None
    status_port: Optional[int] = None
    progress_every_units: int = 5

    def __post_init__(self) -> None:
        if not self.replicas:
            raise ValueError("at least one replica address is required")
        if self.unit_rows < 1 or self.window < 1 or self.concurrency < 1:
            raise ValueError(
                f"unit_rows/window/concurrency must be >= 1 (got "
                f"{self.unit_rows}, {self.window}, {self.concurrency})")
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1 "
                             f"(got {self.max_attempts})")


class UnitFailedError(RuntimeError):
    """One work unit exhausted max_attempts across the fleet."""


class BatchDriver:
    """One job: plan -> dispatch -> commit, resumable (module doc)."""

    def __init__(self, cfg: BatchConfig,
                 registry: Optional[MetricsRegistry] = None,
                 logger: Optional[Logger] = None):
        self.cfg = cfg
        self.registry = registry or MetricsRegistry()
        self.log = logger if logger is not None else (
            Logger(echo=False, jsonl_path=cfg.jsonl_path)
            if cfg.jsonl_path else None)
        r = self.registry
        self._c_units = r.counter(
            "sparknet_batch_units_done_total",
            "work units completed and committed to the manifest")
        self._c_retries = r.counter(
            "sparknet_batch_units_retried_total",
            "unit dispatch retries by kind (shed = backpressure, "
            "error = transport death / timeout)", labels=("kind",))
        self._c_rows = r.counter(
            "sparknet_batch_rows_total",
            "embedding rows computed and committed")
        self._c_bytes = r.counter(
            "sparknet_batch_output_bytes_total",
            "bytes of committed part objects")
        self._g_inflight = r.gauge(
            "sparknet_batch_units_inflight",
            "work units currently dispatched to replicas")
        self._g_rows_per_s = r.gauge(
            "sparknet_batch_rows_per_s",
            "committed rows per second, job-aggregate")
        self._g_inflight.set(0)
        self._g_rows_per_s.set(0.0)
        self.heartbeat = (HeartbeatWriter(cfg.heartbeat_path,
                                          role="batch", interval_s=1.0,
                                          registry=r)
                          if cfg.heartbeat_path else None)
        self._status_http: Optional[StatusServer] = None
        self._lock = threading.Lock()   # manifest + counters
        self._inflight = 0
        self._t0 = 0.0
        self.units_done = 0             # committed THIS run
        self.units_skipped = 0          # already in the manifest
        self.rows_done = 0              # committed THIS run
        self.retries = 0
        self.output_bytes = 0
        self._stop = threading.Event()

    # -- input ---------------------------------------------------------------

    def _load_input(self) -> ArrayDataset:
        raw = store.read_bytes(self.cfg.input)
        with np.load(io.BytesIO(raw), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        if not arrays:
            raise ValueError(f"input {self.cfg.input} holds no arrays")
        return ArrayDataset(arrays)

    # -- one unit ------------------------------------------------------------

    def _unit_rows_out(self, cli: BinaryClient, data: ArrayDataset,
                      lo: int, hi: int,
                      trace=None) -> Dict[str, np.ndarray]:
        """Dispatch one unit's rows pipelined on one connection; returns
        {blob: (rows, ...) array}. Raises on the FIRST failed row — the
        unit is the retry granule, a half-computed unit is never
        committed."""
        cfg = self.cfg
        rids: List[int] = []
        results: List[Optional[Dict[str, np.ndarray]]] = []
        nexti = lo
        while nexti < hi or rids:
            while nexti < hi and len(rids) < cfg.window:
                payload = {k: v[nexti] for k, v in data.arrays.items()}
                rids.append(cli.submit(
                    payload, model=cfg.model, deadline_s=cfg.deadline_s,
                    tenant=cfg.tenant, priority=cfg.priority,
                    stream=True,
                    outputs=(cfg.outputs or None),
                    # every row request is a child span of the unit's
                    # trace — one trace id per work unit, so the
                    # assembler reconstructs the whole unit's fan-out
                    trace=(trace.child() if trace is not None
                           else None)))
                nexti += 1
            results.append(cli.collect(rids.pop(0),
                                       timeout=cfg.request_timeout_s))
        n = hi - lo
        assert len(results) == n, (len(results), n)
        keys = sorted(results[0])
        if not keys:
            raise ValueError(
                "replica returned no output blobs (name --outputs "
                "explicitly, or configure the lane's outputs)")
        return {k: np.stack([r[k] for r in results]) for k in keys}

    def _run_unit(self, data: ArrayDataset, uid: int, lo: int,
                  hi: int) -> Tuple[str, int, int]:
        """Compute + commit one unit; returns (replica, attempts,
        nbytes). Rotates replicas per attempt; full-jitter backoff."""
        cfg = self.cfg
        hard_attempts = 0
        attempt = 0
        # one trace per work unit (the driver is a front door: it MINTS)
        rt = reqtrace.active()
        ctx = rec = None
        if rt is not None:
            ctx = rt.mint()
            rec = rt.begin(ctx, transport="batch", model=cfg.model)
        while True:
            if self._stop.is_set():
                if rec is not None:
                    rt.finish(rec, "cancelled")
                raise UnitFailedError(f"unit {uid}: driver stopping")
            addr = cfg.replicas[(uid + attempt) % len(cfg.replicas)]
            attempt += 1
            cli = None
            try:
                host, port = _parse_hostport(addr)
                cli = BinaryClient(host, port,
                                   timeout=cfg.request_timeout_s,
                                   use_shm=cfg.use_shm)
                out = self._unit_rows_out(cli, data, lo, hi, trace=ctx)
                buf = io.BytesIO()
                np.savez(buf, **out)
                raw = buf.getvalue()
                store.write_bytes(
                    store.join(cfg.output, mf.part_name(uid)), raw)
                if rec is not None:
                    rt.stage(ctx, "unit", rec["ts"],
                             rt.now_us() - rec["ts"], unit=uid,
                             rows=hi - lo, attempts=attempt)
                    rt.finish(rec, "ok")
                return addr, attempt, len(raw)
            except BACKPRESSURE_ERRORS as e:
                # shed, typed: the fleet is busy — the scavenger waits
                # its turn (jittered) and tries another replica. Does
                # NOT count against max_attempts.
                self._note_retry("shed", uid, addr, attempt, e)
            except (ConnectionError, TimeoutError, OSError) as e:
                # a dying/dead replica (kill -9 mid-unit lands here):
                # a retry, not a job failure — but bounded
                hard_attempts += 1
                self._note_retry("error", uid, addr, attempt, e)
                if hard_attempts >= cfg.max_attempts:
                    if rec is not None:
                        rt.finish_exc(rec, e)
                    raise UnitFailedError(
                        f"unit {uid} rows [{lo}, {hi}): "
                        f"{hard_attempts} hard failures across the "
                        f"fleet; last: {type(e).__name__}: {e}") from e
            finally:
                if cli is not None:
                    try:
                        cli.close()
                    except Exception:
                        pass
            time.sleep(min(retry_delay(min(attempt, 6)),
                           cfg.backoff_cap_s))

    def _note_retry(self, kind: str, uid: int, addr: str,
                    attempt: int, err: BaseException) -> None:
        self._c_retries.inc(kind=kind)
        with self._lock:
            self.retries += 1
        if self.log is not None:
            self.log.metrics(uid, event="batch_retry", unit=uid,
                             kind=kind, replica=addr, attempt=attempt,
                             error=f"{type(err).__name__}: {err}")

    # -- the job -------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        data = self._load_input()
        m = mf.load_manifest(cfg.output)
        if m is None:
            m = mf.new_manifest(
                cfg.job_id or f"batch-{uuid.uuid4().hex[:8]}",
                cfg.input, len(data), cfg.unit_rows, cfg.model,
                cfg.outputs)
            # the EMPTY manifest is written up front: an out dir with
            # parts but no manifest is indistinguishable from another
            # job's leavings, and resume must never guess
            mf.save_manifest(cfg.output, m)
        else:
            mf.check_resume(m, cfg.input, len(data), cfg.unit_rows,
                            cfg.model, cfg.outputs)
        pending = mf.pending_units(m)
        self.units_skipped = m["n_units"] - len(pending)
        self._t0 = time.monotonic()
        if self.cfg.status_port is not None:
            self._status_http = StatusServer(
                self.cfg.status_port, registry=self.registry,
                status=self.status)
        if self.heartbeat is not None:
            self.heartbeat.beat(0, status="ok", force=True,
                                job_id=m["job_id"],
                                units_total=m["n_units"],
                                units_done=len(m["units"]))
        try:
            if pending:
                with ThreadPoolExecutor(
                        max_workers=min(cfg.concurrency, len(pending)),
                        thread_name_prefix="batch-unit") as ex:
                    futs = []
                    for uid, lo, hi in pending:
                        if cfg.pace_s > 0:
                            time.sleep(cfg.pace_s)
                        futs.append(ex.submit(
                            self._dispatch, data, m, uid, lo, hi))
                    for f in futs:
                        f.result()  # first unit failure fails the job
        except BaseException:
            self._stop.set()  # stop queued units; in-flight ones drain
            raise
        finally:
            self._shutdown()
        return self._summary(m)

    def _dispatch(self, data: ArrayDataset, m: Dict[str, Any],
                  uid: int, lo: int, hi: int) -> None:
        if self._stop.is_set():
            raise UnitFailedError(f"unit {uid}: driver stopping")
        with self._lock:
            self._inflight += 1
            self._g_inflight.set(self._inflight)
        t0 = time.monotonic()
        try:
            addr, attempts, nbytes = self._run_unit(data, uid, lo, hi)
        finally:
            with self._lock:
                self._inflight -= 1
                self._g_inflight.set(self._inflight)
        dt = time.monotonic() - t0
        with self._lock:
            # part is on the store: NOW the manifest may say so
            # (manifest-last; a kill between the two redoes the unit)
            mf.record_unit(m, uid, lo, hi, nbytes, addr, attempts)
            mf.save_manifest(self.cfg.output, m)
            self.units_done += 1
            self.rows_done += hi - lo
            self.output_bytes += nbytes
            rows_per_s = self.rows_done / max(
                time.monotonic() - self._t0, 1e-9)
            done_total = len(m["units"])
        self._c_units.inc()
        self._c_rows.inc(hi - lo)
        self._c_bytes.inc(nbytes)
        self._g_rows_per_s.set(round(rows_per_s, 3))
        if self.log is not None:
            self.log.metrics(uid, event="batch_unit", unit=uid,
                             rows=hi - lo, replica=addr,
                             attempts=attempts, bytes=nbytes,
                             dt_s=round(dt, 4))
            if (self.cfg.progress_every_units and
                    done_total % self.cfg.progress_every_units == 0):
                self.log.metrics(done_total, event="batch_progress",
                                 units_done=done_total,
                                 units_total=m["n_units"],
                                 rows=self.rows_done,
                                 rows_per_s=round(rows_per_s, 3))
        if self.heartbeat is not None:
            self.heartbeat.beat(done_total, status="ok",
                                job_id=m["job_id"],
                                units_total=m["n_units"],
                                units_done=done_total,
                                rows_per_s=round(rows_per_s, 3))

    def _shutdown(self) -> None:
        if self._status_http is not None:
            self._status_http.stop()
            self._status_http = None

    def _summary(self, m: Dict[str, Any]) -> Dict[str, Any]:
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        rows_per_s = self.rows_done / elapsed
        n_rep = len(self.cfg.replicas)
        cost = (self.cfg.cost_per_replica_hour * n_rep
                * (elapsed / 3600.0))
        out = {
            "job_id": m["job_id"],
            "done": bool(m["done"]),
            "units_total": m["n_units"],
            "units_done": len(m["units"]),
            "units_this_run": self.units_done,
            "units_skipped_resume": self.units_skipped,
            "rows_total": m["n_rows"],
            "rows_this_run": self.rows_done,
            "elapsed_s": round(elapsed, 3),
            "rows_per_s": round(rows_per_s, 3),
            "img_per_s": round(rows_per_s, 3),   # rows ARE images here
            "retries": self.retries,
            "output_bytes": self.output_bytes,
            "replicas": n_rep,
            "cost_usd": round(cost, 6),
            "cost_per_million_embeddings": (
                round(cost / (self.rows_done / 1e6), 6)
                if self.rows_done else None),
        }
        if self.heartbeat is not None:
            try:
                self.heartbeat.beat(len(m["units"]), status="done",
                                    force=True, job_id=m["job_id"],
                                    units_total=m["n_units"],
                                    units_done=len(m["units"]))
                self.heartbeat.flush()
            except OSError:
                pass
        if self.log is not None:
            self.log.metrics(len(m["units"]), event="batch_done", **{
                k: v for k, v in out.items() if k != "job_id"},
                job_id=m["job_id"])
        return out

    def status(self) -> Dict[str, Any]:
        """The /status row (obs StatusServer)."""
        with self._lock:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            return {
                "role": "batch",
                "units_done": self.units_done,
                "units_skipped_resume": self.units_skipped,
                "units_inflight": self._inflight,
                "rows": self.rows_done,
                "rows_per_s": round(self.rows_done / elapsed, 3),
                "retries": self.retries,
                "output_bytes": self.output_bytes,
                "replicas": list(self.cfg.replicas),
            }


def _parse_hostport(addr: str) -> Tuple[str, int]:
    """'host:port' / 'spkn://host:port' -> (host, port)."""
    a = addr.split("://", 1)[-1].rstrip("/")
    host, _, port = a.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"replica address {addr!r} is not host:port")
    return host, int(port)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="sparknet-batch",
        description="bulk inference across the replica fleet as a "
                    "low-priority scavenger tenant (resumable; "
                    "manifest-last commit)")
    ap.add_argument("--input", required=True,
                    help="input npz (local / gs:// / s3://)")
    ap.add_argument("--out", required=True,
                    help="output dir/prefix for part-*.npz + "
                         "MANIFEST.json")
    ap.add_argument("--replicas", required=True,
                    help="comma-separated binary frontend addresses "
                         "(host:port or spkn://host:port)")
    ap.add_argument("--model", default="")
    ap.add_argument("--outputs", default="",
                    help="comma-separated blob names to extract "
                         "(e.g. the embedding layer); empty = lane "
                         "default outputs")
    ap.add_argument("--unit-rows", type=int, default=64)
    ap.add_argument("--window", type=int, default=16)
    ap.add_argument("--concurrency", type=int, default=2)
    ap.add_argument("--tenant", default="batch")
    ap.add_argument("--priority", default="low")
    ap.add_argument("--deadline-ms", type=float, default=10000.0)
    ap.add_argument("--timeout-s", type=float, default=30.0)
    ap.add_argument("--max-attempts", type=int, default=6)
    ap.add_argument("--pace-s", type=float, default=0.0,
                    help="sleep between unit starts (chaos windows)")
    ap.add_argument("--job-id", default=None)
    ap.add_argument("--cost-per-replica-hour", type=float, default=0.0)
    ap.add_argument("--jsonl", default=None)
    ap.add_argument("--heartbeat", default=None)
    ap.add_argument("--status-port", type=int, default=None)
    args = ap.parse_args(argv)
    cfg = BatchConfig(
        input=args.input, output=args.out,
        replicas=[a for a in args.replicas.split(",") if a],
        model=args.model,
        outputs=tuple(o for o in args.outputs.split(",") if o),
        unit_rows=args.unit_rows, window=args.window,
        concurrency=args.concurrency, tenant=args.tenant,
        priority=args.priority,
        deadline_s=(args.deadline_ms / 1e3
                    if args.deadline_ms > 0 else None),
        request_timeout_s=args.timeout_s,
        max_attempts=args.max_attempts, pace_s=args.pace_s,
        job_id=args.job_id,
        cost_per_replica_hour=args.cost_per_replica_hour,
        jsonl_path=args.jsonl, heartbeat_path=args.heartbeat,
        status_port=args.status_port)
    out = BatchDriver(cfg).run()
    json.dump(out, sys.stdout, indent=1, sort_keys=True)
    sys.stdout.write("\n")
    return 0 if out["done"] else 1


if __name__ == "__main__":
    sys.exit(main())
