"""One tiny object-store surface for batch outputs: local directories
and gs:// | s3:// prefixes behave identically.

The property the manifest protocol needs is ATOMIC VISIBILITY: a reader
(a resuming driver) must see each object either absent or complete,
never half-written. Buckets give that for free (an object exists only
once its upload finalizes); local files get it from the
write-to-temp-then-os.replace dance (same filesystem, so the rename is
atomic on POSIX). Nothing here retries — the driver owns retry policy
(full jitter, data/gcs.retry_delay) because a store error mid-unit must
interact with unit accounting, not hide beneath it.
"""
from __future__ import annotations

import os
import tempfile
from typing import List

from ..utils.checkpoint import _bucket_ops, is_bucket_path


def is_bucket(path: str) -> bool:
    return is_bucket_path(path)


def join(root: str, *names: str) -> str:
    if is_bucket_path(root):
        return "/".join((root.rstrip("/"),) + names)
    return os.path.join(root, *names)


def read_bytes(url: str) -> bytes:
    if is_bucket_path(url):
        return _bucket_ops(url).read(url)
    with open(url, "rb") as f:
        return f.read()


def write_bytes(url: str, data: bytes) -> None:
    """All-or-nothing write: bucket objects finalize atomically; local
    files go through a same-directory temp + os.replace."""
    if is_bucket_path(url):
        _bucket_ops(url).write(url, data)
        return
    d = os.path.dirname(os.path.abspath(url))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-",
                               suffix=os.path.basename(url))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, url)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def exists(url: str) -> bool:
    if is_bucket_path(url):
        try:
            _bucket_ops(url).stat(url, fresh=True)
            return True
        except Exception:
            return False
    return os.path.exists(url)


def delete(url: str) -> None:
    if is_bucket_path(url):
        _bucket_ops(url).delete(url, missing_ok=True)
        return
    try:
        os.unlink(url)
    except FileNotFoundError:
        pass


def list_names(root: str) -> List[str]:
    """Object/file basenames directly under the prefix (temp files from
    an interrupted local write are invisible — they never count)."""
    if is_bucket_path(root):
        urls = _bucket_ops(root).list_urls(root.rstrip("/") + "/")
        return sorted(u.rsplit("/", 1)[-1] for u in urls)
    if not os.path.isdir(root):
        return []
    return sorted(n for n in os.listdir(root)
                  if not n.startswith(".tmp-"))
