"""sparknet_tpu — a TPU-native distributed deep-network training framework.

Built from scratch (JAX/XLA/Pallas/pjit) with the capabilities of the
reference SparkNet (AMPLab, arXiv:1511.06051): declarative model specs
compiled to XLA, Caffe-semantics SGD, schema-driven data loading, and
data-parallel τ-local-step parameter-averaging training where weight sync is
an on-device `pmean` over the ICI mesh rather than a driver round trip.
"""

__version__ = "0.1.0"

from .model.spec import NetSpec, LayerSpec, InputSpec  # noqa: F401
from .model.layers import OpsImpl  # noqa: F401
from .model.net import CompiledNet  # noqa: F401
from .model.prototxt import (  # noqa: F401
    net_from_prototxt,
    net_from_prototxt_file,
    solver_from_prototxt,
    solver_from_prototxt_file,
)
