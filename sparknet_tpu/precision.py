"""Numeric policy: compute dtype + MXU precision for matmuls/convs.

Two supported modes:
  - "float32" (default): f32 operands, Precision.HIGHEST — bit-faithful to the
    reference's float32 Caffe kernels; use for accuracy-parity runs and tests.
  - "bfloat16": operands cast to bf16, f32 accumulation
    (preferred_element_type) — the TPU MXU fast path; use for throughput.

Set globally via `set_policy("bfloat16")` or scoped with `policy(...)`.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

_state = threading.local()


def _get() -> str:
    return getattr(_state, "mode", "float32")


def set_policy(mode: str) -> None:
    assert mode in ("float32", "bfloat16"), mode
    _state.mode = mode


@contextlib.contextmanager
def policy(mode: str):
    prev = _get()
    set_policy(mode)
    try:
        yield
    finally:
        set_policy(prev)


def compute_dtype():
    return jnp.bfloat16 if _get() == "bfloat16" else jnp.float32


def matmul_precision():
    if _get() == "bfloat16":
        return jax.lax.Precision.DEFAULT  # operands already bf16
    return jax.lax.Precision.HIGHEST


def preferred_out():
    """Accumulation/output dtype for matmuls & convs.

    float32 mode: explicit f32. bfloat16 mode: None (output stays bf16 —
    the MXU still accumulates partial products in f32 internally; an explicit
    f32 preferred_element_type would break the conv transpose rule with mixed
    cotangent dtypes)."""
    return None if _get() == "bfloat16" else jnp.float32


def cast_in(x: jnp.ndarray) -> jnp.ndarray:
    dt = compute_dtype()
    if x.dtype in (jnp.float32, jnp.bfloat16) and x.dtype != dt:
        return x.astype(dt)
    return x


def cast_host_inputs(batch: dict, dt=None) -> dict:
    """Cast float32 HOST arrays in a batch dict to the compute dtype —
    value-identical to the first in-net `cast_in` (same f32->bf16 rounding)
    and halves the host->device bytes under bfloat16. Device-resident
    arrays pass through untouched (casting them here would round-trip
    through the host).

    `dt` overrides the policy lookup: the policy is THREAD-LOCAL, so
    callers running on worker threads (the train loop's prefetcher) must
    capture `compute_dtype()` on the main thread and pass it in."""
    import numpy as np

    dt = dt if dt is not None else compute_dtype()
    if dt == jnp.float32:
        return batch
    return {k: (np.asarray(v).astype(dt)
                if not hasattr(v, "devices")
                and np.asarray(v).dtype == np.float32 else v)
            for k, v in batch.items()}
