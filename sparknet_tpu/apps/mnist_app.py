"""MNIST training app — reference `apps/MnistApp.scala` equivalent.

Reference defaults: batch 64, τ=10, eval every 5 rounds, Momentum(0.01
exp-decay, 0.9) (`MnistApp.scala:18,118`; `models/tensorflow/mnist/
mnist_graph.py` optimizer block: lr = 0.01 * 0.95^(epoch)). The exp-decay is
expressed with the solver's `exp` policy: gamma^iter with gamma chosen so one
epoch (train_size/batch iters) decays by 0.95.
"""
from __future__ import annotations

import argparse

from ..data.mnist import MnistLoader
from ..data.dataset import ArrayDataset
from ..parallel import initialize_multihost
from ..parallel.mesh import host_id_count
from ..solver import SolverConfig
from ..utils.config import RunConfig
from ..zoo import lenet
from .train_loop import resolve_spec, train


def default_config(train_size: int = 60000) -> RunConfig:
    iters_per_epoch = max(train_size // 64, 1)
    gamma = 0.95 ** (1.0 / iters_per_epoch)
    return RunConfig(
        model="lenet",
        solver=SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="exp",
                            gamma=gamma),
        data_dir="data/mnist", tau=10, local_batch=64,
        eval_every=5, max_rounds=100)


def build_datasets(cfg: RunConfig):
    loader = MnistLoader(cfg.data_dir)
    return (ArrayDataset(loader.train_batch_dict()),
            ArrayDataset(loader.test_batch_dict()))


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="RunConfig JSON path")
    p.add_argument("--data-dir", default=None)
    p.add_argument("overrides", nargs="*")
    args = p.parse_args(argv)
    initialize_multihost()  # BEFORE any other JAX use (mesh.py:49)
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)
    train_ds, test_ds = build_datasets(cfg)
    pi, pc = host_id_count()
    train_ds, test_ds = train_ds.host_shard(pi, pc), test_ds.host_shard(pi, pc)
    spec = resolve_spec(cfg, data=(cfg.local_batch, 1, 28, 28),
                        label=(cfg.local_batch, 1))
    train(cfg, spec, train_ds, test_ds)


if __name__ == "__main__":
    main()
