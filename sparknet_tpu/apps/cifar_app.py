"""CIFAR-10 training app — reference `apps/CifarApp.scala` equivalent.

Reference defaults preserved: batch 100, τ=10, eval every 5 rounds, solver
lr 0.001 fixed / momentum 0.9 / weight decay 0.004
(`CifarApp.scala:20,127,107`; `models/cifar10/cifar10_quick_solver.prototxt`).

Usage:
    python -m sparknet_tpu.apps.cifar_app --data-dir data/cifar10 \
        [--config run.json] [key=value ...]
"""
from __future__ import annotations

import argparse

from ..data.cifar import CifarLoader
from ..data.dataset import ArrayDataset
from ..parallel import initialize_multihost
from ..parallel.mesh import host_id_count
from ..solver import SolverConfig
from ..utils.config import RunConfig
from .train_loop import resolve_spec, train


def default_config() -> RunConfig:
    return RunConfig(
        model="cifar10_quick",
        solver=SolverConfig(base_lr=0.001, momentum=0.9, weight_decay=0.004,
                            lr_policy="fixed", max_iter=4000),
        data_dir="data/cifar10", tau=10, local_batch=100,
        eval_every=5, max_rounds=100)


def build_datasets(cfg: RunConfig):
    loader = CifarLoader(cfg.data_dir, seed=cfg.seed)
    return (ArrayDataset(loader.train_batch_dict(cfg.subtract_mean)),
            ArrayDataset(loader.test_batch_dict(cfg.subtract_mean)))


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="RunConfig JSON path")
    p.add_argument("--data-dir", default=None)
    p.add_argument("overrides", nargs="*", help="key=value config overrides")
    args = p.parse_args(argv)
    initialize_multihost()  # BEFORE any other JAX use (mesh.py:49)
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)
    train_ds, test_ds = build_datasets(cfg)
    # every host loads identically, then keeps its disjoint slice
    # (the reference's repartition + per-executor cache)
    pi, pc = host_id_count()
    train_ds, test_ds = train_ds.host_shard(pi, pc), test_ds.host_shard(pi, pc)
    spec = resolve_spec(cfg, data=(cfg.local_batch, 3, 32, 32),
                        label=(cfg.local_batch, 1))
    train(cfg, spec, train_ds, test_ds)


if __name__ == "__main__":
    main()
