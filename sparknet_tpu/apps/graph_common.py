"""Shared plumbing for the serialized-graph-backend apps (graph_mnist_app,
graph_imagenet_app): graph-file dispatch, input-shape validation, and the
GraphTrainer loop wiring — one copy, both reference pairings
(`apps/MnistApp.scala`, `apps/TFImageNetApp.scala`)."""
from __future__ import annotations

from typing import Callable, Optional, Tuple

from ..backend import GraphDef, GraphNet
from ..backend.tf_import import import_tf_graphdef_file
from ..parallel import GraphTrainer, make_mesh
from ..utils.config import RunConfig
from ..utils.logger import Logger, default_logger
from .train_loop import run_loop


def load_graph(path: Optional[str],
               default_builder: Callable[[], GraphDef]) -> GraphDef:
    """`None` -> build natively; `.pb` -> frozen TF GraphDef import;
    anything else -> portable GraphDef JSON."""
    if path is None:
        return default_builder()
    if path.endswith(".pb"):
        return import_tf_graphdef_file(path)
    return GraphDef.load(path)


def check_input_shape(net: GraphNet, field: str,
                      expect: Tuple[int, ...]) -> None:
    """Fail fast (and name the knob) when the graph's placeholder disagrees
    with the data pipeline's per-example shape — otherwise the mismatch
    surfaces as a bare XLA matmul shape error deep inside the jitted round
    that never mentions e.g. `crop`."""
    shapes = net.input_shapes()
    if field not in shapes:
        # graph uses a different input name — GraphNet's own "batch missing
        # graph input" validation will name the real inputs at run time
        return
    got = shapes[field][1:]  # drop the batch dim
    if got and got != tuple(expect):
        raise ValueError(
            f"graph input {field!r} expects per-example shape {got} but the "
            f"data pipeline produces {tuple(expect)} — check crop/model "
            f"settings against the graph (a natively built alexnet graph "
            f"is fixed at 227x227x3)")


def train_graph(cfg: RunConfig, graph: GraphDef, train_ds, test_ds=None,
                logger: Optional[Logger] = None, batch_transform=None,
                eval_transform=None,
                expect_data_shape: Optional[Tuple[int, ...]] = None):
    """The reference graph-backend loop: GraphNet -> mesh -> GraphTrainer ->
    the shared `run_loop` driver. Returns final device state."""
    log = logger or default_logger(cfg.workdir)
    net = GraphNet(graph, seed=cfg.seed)
    if expect_data_shape is not None:
        check_input_shape(net, "data", expect_data_shape)
    mesh = make_mesh(cfg.n_devices)
    trainer = GraphTrainer(net, mesh, tau=cfg.tau,
                           compute_health=(cfg.health is not None
                                           and cfg.health.enabled))
    log.log(f"graph backend: {len(net.variable_names)} variables; "
            f"mesh {trainer.n_devices} devices; tau={cfg.tau} "
            f"local_batch={cfg.local_batch}")
    return run_loop(cfg, trainer, train_ds, test_ds, log,
                    batch_transform=batch_transform,
                    eval_transform=eval_transform)
