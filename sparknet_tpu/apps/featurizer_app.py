"""Feature-extraction app — reference `apps/FeaturizerApp.scala` equivalent.

The reference's only inference-shaped workload: build a net (no solver), set
weights once, then map the dataset through `forward(..., List("ip1"))`
extracting a hidden blob per example (`FeaturizerApp.scala:75-98`). Here:
load weights (checkpoint or npz), batched jitted forward, write features npz.

Usage:
    python -m sparknet_tpu.apps.featurizer_app --data-dir data/cifar10 \
        --weights w.npz --blob ip1 --out features.npz
"""
from __future__ import annotations

import argparse

import numpy as np

from ..data.cifar import CifarLoader
from ..net_api import JaxNet
from ..zoo import cifar10_quick


def featurize(net: JaxNet, batch_dict, blob: str, batch_size: int
              ) -> np.ndarray:
    n = len(next(iter(batch_dict.values())))
    feats = []
    usable = (n // batch_size) * batch_size
    for i in range(0, usable, batch_size):
        batch = {k: v[i:i + batch_size] for k, v in batch_dict.items()}
        out = net.forward(batch, blob_names=[blob])
        feats.append(np.asarray(out[blob]))
    return np.concatenate(feats) if feats else np.empty((0,))


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--weights", help="WeightCollection .npz (optional)")
    p.add_argument("--blob", default="ip1")
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--out", default="features.npz")
    args = p.parse_args(argv)

    loader = CifarLoader(args.data_dir)
    net = JaxNet(cifar10_quick(batch=args.batch))
    if args.weights:
        net.load_weights(args.weights)
    feats = featurize(net, loader.train_batch_dict(), args.blob, args.batch)
    np.savez(args.out, features=feats)
    print(f"wrote {feats.shape} features to {args.out}")


if __name__ == "__main__":
    main()
