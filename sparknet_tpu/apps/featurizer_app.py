"""Feature-extraction app — reference `apps/FeaturizerApp.scala` equivalent.

The reference's only inference-shaped workload: build a net (no solver), set
weights once, then map the dataset through `forward(..., List("ip1"))`
extracting a hidden blob per example (`FeaturizerApp.scala:75-98`). Here:
load weights (checkpoint, npz, or .caffemodel), batched jitted forward,
write features npz. Works against EITHER backend — a zoo/prototxt layer-IR
net, or (--graph) a serialized/imported graph, whose hidden nodes are
fetched by name through the same NetInterface spelling.

Usage:
    python -m sparknet_tpu.apps.featurizer_app --data-dir data/cifar10 \
        --weights w.caffemodel --blob ip1 --out features.npz
    python -m sparknet_tpu.apps.featurizer_app --data-dir data/cifar10 \
        --graph model.pb --blob relu3
"""
from __future__ import annotations

import argparse

import numpy as np

from ..data.cifar import CifarLoader
from ..net_api import JaxNet
from ..zoo import cifar10_quick


def featurize(net, batch_dict, blob: str, batch_size: int) -> np.ndarray:
    """`net` is any NetInterface impl (JaxNet or GraphNet)."""
    n = len(next(iter(batch_dict.values())))
    feats = []
    usable = (n // batch_size) * batch_size
    for i in range(0, usable, batch_size):
        batch = {k: v[i:i + batch_size] for k, v in batch_dict.items()}
        out = net.forward(batch, blob_names=[blob])
        feats.append(np.asarray(out[blob]))
    return np.concatenate(feats) if feats else np.empty((0,))


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--weights", help="weights file (.npz / .caffemodel)")
    p.add_argument("--graph", help="serialized graph (.pb / .json) to "
                   "featurize instead of the layer-IR net")
    p.add_argument("--blob", default="ip1")
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--out", default="features.npz")
    args = p.parse_args(argv)

    loader = CifarLoader(args.data_dir)
    batch_dict = loader.train_batch_dict()
    if args.graph:
        from ..backend import GraphNet
        from .graph_common import load_graph
        net = GraphNet(load_graph(args.graph, None))
        if args.weights:
            # assigns by VARIABLE name via set_weights (//assign protocol);
            # a collection whose names don't match fails loudly there
            from ..model.weights import WeightCollection
            net.set_weights(WeightCollection.load(args.weights))
        missing = [i for i in net.input_names if i not in batch_dict]
        if missing:
            raise ValueError(
                f"graph inputs {missing} not provided by the loader "
                f"(has {sorted(batch_dict)}) — this app feeds "
                f"data/label-shaped graphs")
        # fail fast on a dataset/graph size mismatch (layouts may be
        # transposed by _prep, so compare element counts per example)
        shapes = net.input_shapes()
        for iname in net.input_names:
            want = shapes[iname]
            got = batch_dict[iname].shape
            if want and int(np.prod(want[1:])) != int(np.prod(got[1:])):
                raise ValueError(
                    f"graph input {iname!r} expects per-example shape "
                    f"{tuple(want[1:])} but the dataset provides "
                    f"{tuple(got[1:])}")
        batch_dict = {k: v for k, v in batch_dict.items()
                      if k in net.input_names}
    else:
        net = JaxNet(cifar10_quick(batch=args.batch))
        if args.weights:
            net.load_weights(args.weights)
    feats = featurize(net, batch_dict, args.blob, args.batch)
    np.savez(args.out, features=feats)
    print(f"wrote {feats.shape} features to {args.out}")


if __name__ == "__main__":
    main()
