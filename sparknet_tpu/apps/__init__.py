from .train_loop import train, probe_value  # noqa: F401
