"""MNIST training on the SERIALIZED-GRAPH backend — the reference's
`apps/MnistApp.scala` pairing: a TensorFlowNet-style graph (in-graph
Momentum optimizer + exp-decay lr) trained inside the distributed
τ-averaging loop (MnistApp.scala:98-138; batch 64, τ=10, eval every 5).

The graph can be:
  - (default) our portable generator `build_mnist_graph()` — the analogue of
    the reference generating `mnist_graph.pb` with `mnist_graph.py`;
  - `--graph path.json` — a portable GraphDef JSON produced elsewhere;
  - `--graph path.pb` — a frozen TF GraphDef (e.g. the reference's own
    `models/tensorflow/mnist/mnist_graph.pb`), trained through its imported
    in-graph optimizer.
"""
from __future__ import annotations

import argparse

import numpy as np

from ..backend import GraphDef, GraphNet, build_mnist_graph
from ..backend.tf_import import import_tf_graphdef_file
from ..data.dataset import ArrayDataset
from ..data.mnist import MnistLoader
from ..parallel import GraphTrainer, initialize_multihost, make_mesh
from ..parallel.mesh import host_id_count
from ..utils.config import RunConfig
from ..utils.logger import Logger, default_logger
from .train_loop import run_loop


def default_config() -> RunConfig:
    return RunConfig(model="graph:mnist", data_dir="data/mnist", tau=10,
                     local_batch=64, eval_every=5, eval_batch=512,
                     max_rounds=100)


def load_graph(path: str | None, batch: int, train_size: int) -> GraphDef:
    if path is None:
        return build_mnist_graph(batch=batch, train_size=train_size)
    if path.endswith(".pb"):
        return import_tf_graphdef_file(path)
    return GraphDef.load(path)


def _nhwc(arrays):
    """Loader emits Caffe NCHW; the graph backend is NHWC (TPU layout)."""
    out = dict(arrays)
    out["data"] = np.ascontiguousarray(
        np.transpose(arrays["data"], (0, 2, 3, 1)))
    out["label"] = arrays["label"].reshape(-1)
    return out


def train_graph(cfg: RunConfig, graph: GraphDef, train_ds: ArrayDataset,
                test_ds: ArrayDataset | None = None,
                logger: Logger | None = None):
    """The MnistApp loop over GraphTrainer: the shared `run_loop` driver with
    the serialized-graph backend slotted in. Returns final device state."""
    log = logger or default_logger(cfg.workdir)
    net = GraphNet(graph, seed=cfg.seed)
    mesh = make_mesh(cfg.n_devices)
    trainer = GraphTrainer(net, mesh, tau=cfg.tau)
    log.log(f"graph backend: {len(net.variable_names)} variables; "
            f"mesh {trainer.n_devices} devices; tau={cfg.tau} "
            f"local_batch={cfg.local_batch}")
    return run_loop(cfg, trainer, train_ds, test_ds, log)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="RunConfig JSON path")
    p.add_argument("--graph", default=None,
                   help=".pb (TF GraphDef) or .json (portable) graph file")
    p.add_argument("--data-dir", default=None)
    p.add_argument("overrides", nargs="*")
    args = p.parse_args(argv)
    initialize_multihost()
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)
    loader = MnistLoader(cfg.data_dir)
    train_ds = ArrayDataset(_nhwc(loader.train_batch_dict()))
    test_ds = ArrayDataset(_nhwc(loader.test_batch_dict()))
    pi, pc = host_id_count()
    train_ds, test_ds = train_ds.host_shard(pi, pc), test_ds.host_shard(pi, pc)
    graph = load_graph(args.graph, cfg.local_batch, len(train_ds))
    train_graph(cfg, graph, train_ds, test_ds)


if __name__ == "__main__":
    main()
