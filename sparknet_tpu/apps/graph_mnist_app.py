"""MNIST training on the SERIALIZED-GRAPH backend — the reference's
`apps/MnistApp.scala` pairing: a TensorFlowNet-style graph (in-graph
Momentum optimizer + exp-decay lr) trained inside the distributed
τ-averaging loop (MnistApp.scala:98-138; batch 64, τ=10, eval every 5).

The graph can be:
  - (default) our portable generator `build_mnist_graph()` — the analogue of
    the reference generating `mnist_graph.pb` with `mnist_graph.py`;
  - `--graph path.json` — a portable GraphDef JSON produced elsewhere;
  - `--graph path.pb` — a frozen TF GraphDef (e.g. the reference's own
    `models/tensorflow/mnist/mnist_graph.pb`), trained through its imported
    in-graph optimizer.
"""
from __future__ import annotations

import argparse
import functools

import numpy as np

from ..backend import build_mnist_graph
from ..data.dataset import ArrayDataset
from ..data.mnist import MnistLoader
from ..parallel import initialize_multihost
from ..parallel.mesh import host_id_count
from ..utils.config import RunConfig
from .graph_common import load_graph, train_graph  # noqa: F401 (re-export:
# tests and callers use graph_mnist_app.train_graph for the MnistApp pairing)


def default_config() -> RunConfig:
    return RunConfig(model="graph:mnist", data_dir="data/mnist", tau=10,
                     local_batch=64, eval_every=5, eval_batch=512,
                     max_rounds=100)


def _nhwc(arrays):
    """Loader emits Caffe NCHW; the graph backend is NHWC (TPU layout)."""
    out = dict(arrays)
    out["data"] = np.ascontiguousarray(
        np.transpose(arrays["data"], (0, 2, 3, 1)))
    out["label"] = arrays["label"].reshape(-1)
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="RunConfig JSON path")
    p.add_argument("--graph", default=None,
                   help=".pb (TF GraphDef) or .json (portable) graph file")
    p.add_argument("--data-dir", default=None)
    p.add_argument("overrides", nargs="*")
    args = p.parse_args(argv)
    initialize_multihost()
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)
    loader = MnistLoader(cfg.data_dir)
    train_ds = ArrayDataset(_nhwc(loader.train_batch_dict()))
    test_ds = ArrayDataset(_nhwc(loader.test_batch_dict()))
    pi, pc = host_id_count()
    train_ds, test_ds = train_ds.host_shard(pi, pc), test_ds.host_shard(pi, pc)
    graph = load_graph(args.graph, functools.partial(
        build_mnist_graph, batch=cfg.local_batch, train_size=len(train_ds)))
    train_graph(cfg, graph, train_ds, test_ds,
                expect_data_shape=(28, 28, 1))


if __name__ == "__main__":
    main()
