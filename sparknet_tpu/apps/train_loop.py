"""The canonical training driver: the reference's app loop, mesh-native.

Reference shape (`apps/CifarApp.scala:100-149`):
    while true:
      broadcast weights; set on workers        -> (free: device-resident)
      every Nth round: distributed eval        -> trainer.evaluate (psum)
      foreachPartition: τ local solver steps   -> trainer.train_round (scan)
      collect + average weights on driver      -> (inside round: pmean)
      log conv1[0] divergence probe            -> probe_value()

Additions the reference lacked (SURVEY §5.3-5.5): checkpoint/resume of the
full TrainState + round counter — saved through a TWO-STAGE async pipeline
(stage 1 blocks only for the device->host fetch; a background writer
serializes, digests, and persists to a local dir or natively to a
gs://|s3:// bucket, at most one snapshot in flight), metrics JSONL,
per-phase timing, a termination condition (max_rounds instead of
`while(true)`), and the training health supervisor: on-device anomaly
signals classified per flush,
skip-and-continue for isolated loss spikes, rollback to the newest verified
checkpoint (with LR backoff and an advanced data order for the retried
window) for nonfinite rounds or repeated spikes, and a loud hard-fail once
the rollback budget is spent (utils/health.py).
"""
from __future__ import annotations

import math
import time
import warnings
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..model.layers import OpsImpl
from ..model.net import CompiledNet
from ..model.spec import NetSpec
from ..obs import (MetricsRegistry, StatusServer, register_build_info,
                   trace as obs_trace)
from ..obs import device as obs_device
from ..obs import pod as obs_pod
from ..parallel.elastic import ElasticRelaunch, MembershipController
from ..parallel.mesh import fetch_global, make_mesh
from ..parallel.sharded import ShardedTrainer
from ..parallel.trainer import ParallelTrainer, TrainState
from ..data.dataset import ArrayDataset, RoundSampler
from ..utils import checkpoint as ckpt
from ..utils import profiling
from ..utils.config import RunConfig
from ..utils.health import (HealthConfig, HealthMonitor, TrainingHealthError,
                            poison_batch)
from ..utils.heartbeat import HeartbeatWriter
from ..utils.logger import Logger, default_logger
from ..utils.metrics import PhaseTimers, ThroughputMeter
from .. import precision

def _hb_float(v: float):
    """Heartbeat-safe float: NaN/Inf -> None (RFC 8259, like the JSONL)."""
    return float(v) if math.isfinite(v) else None


#: retried rounds sample a disjoint deterministic data window: round R on
#: rollback generation g draws as logical round R + g * _RETRY_DATA_OFFSET
#: (stateless samplers only — a streaming source simply continues forward,
#: which advances the data order by construction)
_RETRY_DATA_OFFSET = 1 << 20


def resolve_spec(cfg: RunConfig, **input_shapes) -> NetSpec:
    """cfg.model -> NetSpec: a zoo builder name, or a .prototxt path
    (capability parity: the reference's apps loaded prototxt data files,
    `apps/CifarApp.scala:83-88`)."""
    from .. import zoo
    from ..model.prototxt import net_from_prototxt_file
    if cfg.model.endswith(".prototxt"):
        return net_from_prototxt_file(
            cfg.model, input_shapes=input_shapes or None)
    builders = {
        "cifar10_quick": lambda: zoo.cifar10_quick(batch=cfg.local_batch),
        "caffenet": lambda: zoo.caffenet(batch=cfg.local_batch,
                                         crop=cfg.crop or 227,
                                         n_classes=cfg.n_classes),
        "lenet": lambda: zoo.lenet(batch=cfg.local_batch),
        "adult_mlp": lambda: zoo.adult_mlp(batch=cfg.local_batch),
    }
    if cfg.model not in builders:
        raise ValueError(f"unknown model {cfg.model!r}: expected a .prototxt "
                         f"path or one of {sorted(builders)}")
    return builders[cfg.model]()


def resolve_trainer_impl(cfg: RunConfig) -> str:
    """cfg.trainer_impl -> the concrete layer-IR trainer implementation.
    "auto" defers to $SPARKNET_TRAINER_IMPL (the CI matrix leg runs the
    whole suite with it set to "named") and falls back to "shard_map",
    today's default. Validated here — trainer BUILD time, the OpsImpl /
    ElasticConfig rule — so a typo'd knob cannot silently train on the
    wrong implementation."""
    import os
    impl = cfg.trainer_impl
    if impl == "auto":
        impl = os.environ.get("SPARKNET_TRAINER_IMPL", "shard_map")
    if impl not in ("shard_map", "named"):
        raise ValueError(f"unknown trainer_impl {impl!r}: expected "
                         f"'auto', 'shard_map', or 'named'")
    if impl != "named" and cfg.state_sharding != "replicated":
        raise ValueError(
            f"state_sharding={cfg.state_sharding!r} needs the NamedSharding "
            f"trainer — set trainer_impl='named' (resolved: {impl!r})")
    return impl


def resolve_solver(cfg: RunConfig):
    """Apply cfg.solver_prototxt over cfg.solver if set."""
    if cfg.solver_prototxt:
        from ..model.prototxt import solver_from_prototxt_file
        from ..solver import SolverConfig
        cfg.solver = SolverConfig.from_dict(
            solver_from_prototxt_file(cfg.solver_prototxt))
    return cfg.solver


def probe_value(state: TrainState, net: CompiledNet):
    """First scalar of the first parametric layer's weights — the reference's
    divergence probe (`apps/CifarApp.scala:147` logged conv1 weight [0]).

    Single-process: returns a 0-d DEVICE scalar (an async slice — the loop
    fetches it one round later, so the probe never stalls the pipeline; the
    slice is enqueued before the next round's donation invalidates the
    state buffers). Multi-host: reads a locally-addressable shard to a host
    float (post-round params are replica-identical, any shard's value is
    THE value)."""
    leaf = state.params[net.param_layers()[0]]["w"]
    if hasattr(leaf, "addressable_shards") and not getattr(
            leaf, "is_fully_addressable", True):
        arr = np.asarray(leaf.addressable_shards[0].data)
        return float(arr.reshape(-1)[0])
    if hasattr(leaf, "devices"):
        return leaf[(0,) * leaf.ndim]
    return float(np.asarray(leaf).reshape(-1)[0])


def train(cfg: RunConfig, spec: NetSpec, train_ds: ArrayDataset,
          test_ds: Optional[ArrayDataset] = None,
          logger: Optional[Logger] = None,
          round_hook: Optional[Callable[[int, TrainState], None]] = None,
          batch_transform=None, eval_transform=None) -> TrainState:
    """Run the full distributed training loop per cfg (layer-IR backend).
    Returns final state."""
    log = logger or default_logger(cfg.workdir)
    precision.set_policy(cfg.precision)
    resolve_solver(cfg)
    # persistent compile cache (process-global): the initial round
    # compile AND every elastic trainer_factory rebuild hit it — a
    # relaunched/resized worker with a warm cache skips XLA entirely
    from ..utils.compile_cache import init_compile_cache
    cache = init_compile_cache(cfg.compile_cache_dir)
    if cache:
        log.log(f"persistent compile cache: {cache}")
    net = CompiledNet.compile(spec)
    mesh = make_mesh(cfg.n_devices)
    n_dev = int(np.prod(mesh.devices.shape))
    compute_health = cfg.health is not None and cfg.health.enabled
    elastic_tau = (cfg.elastic is not None and cfg.elastic.enabled
                   and cfg.elastic.tau_adapt)
    impl = resolve_trainer_impl(cfg)
    trainer_kw: Dict[str, Any] = {}
    trainer_cls = ParallelTrainer
    if impl == "named":
        trainer_cls = ShardedTrainer
        trainer_kw["state_sharding"] = cfg.state_sharding
    trainer = trainer_cls(net, cfg.solver, mesh, tau=cfg.tau,
                          mode=cfg.mode, compute_health=compute_health,
                          elastic_tau=elastic_tau,
                          donate_batches=cfg.donate_batches,
                          fused_boundary=cfg.fused_boundary,
                          ops=OpsImpl(lrn=cfg.lrn_impl,
                                      pool=cfg.pool_impl,
                                      interpret=cfg.ops_interpret),
                          **trainer_kw)
    log.log(f"mesh: {n_dev} devices; tau={cfg.tau} mode={cfg.mode} "
            f"local_batch={cfg.local_batch} precision={cfg.precision} "
            f"trainer={impl}"
            + (f" state_sharding={cfg.state_sharding}"
               if impl == "named" else ""))
    if batch_transform is None:
        train_ds = _to_device_layout(train_ds, net)
    if test_ds is not None and eval_transform is None:
        test_ds = _to_device_layout(test_ds, net)
    return run_loop(cfg, trainer, train_ds, test_ds, log,
                    batch_transform=batch_transform,
                    eval_transform=eval_transform,
                    probe=lambda s: probe_value(s, net),
                    round_hook=round_hook,
                    # ParallelTrainer.resized carries the whole trainer
                    # configuration (net/solver/τ/mode/health/elastic_tau)
                    # to the new mesh — the one resize construction path
                    trainer_factory=trainer.resized)


def prepare_round_batches(source, rnd: int, tau: int, seed: int,
                          batch_transform, compute_dt, retry: int = 0,
                          health: Optional[HealthConfig] = None,
                          first_pass: bool = True) -> Dict[str, Any]:
    """One round's host-side work: sample -> per-τ-slice preprocessing
    (e.g. fresh random crops; rng keyed (seed, round, slice) so resume
    reproduces identical crops) -> compute-dtype cast. The cast happens
    here, on the prefetch thread — at dispatch time it would serialize a
    full-batch astype into the pipelined path (`compute_dt` must be
    captured on the MAIN thread; the precision policy is thread-local).
    Module-level so `bench.py --e2e` times exactly this code path.

    `retry` is the health supervisor's rollback generation: a retried
    window must be deterministic-but-DIFFERENT, so stateless samplers
    (RoundSampler) draw from an offset logical round and the per-slice
    transform rng is re-keyed. Stateful streaming sources keep their true
    round index (their cursor bookkeeping is keyed on it) — continuing the
    stream already advances the data order. `health` enables the
    deterministic fault-injection hooks: on the FIRST pass over a
    configured round (`first_pass` — the loop tracks the highest round
    already executed, so a retried window is clean but LATER configured
    rounds still fire after an earlier rollback) the prepared batch is
    poisoned before the precision cast, so chaos tests exercise
    detect -> rollback -> recover without flakiness."""
    stateless = isinstance(source, RoundSampler) or \
        getattr(source, "stateless_rounds", False)
    data_rnd = rnd + retry * _RETRY_DATA_OFFSET if retry and stateless else rnd
    batches = source.next_round(round_index=data_rnd)
    if batch_transform is not None:
        slices = [batch_transform.convert_batch(
            {k: v[t] for k, v in batches.items()}, train=True,
            rng=np.random.default_rng((seed, data_rnd, retry, t)
                                      if retry else (seed, rnd, t)))
            for t in range(tau)]
        batches = {k: np.stack([s[k] for s in slices])
                   for k in slices[0]}
    if health is not None and health.enabled and first_pass:
        # injection is inert when the supervisor is off: poisoning a run
        # with nothing watching would recreate exactly the silent-NaN
        # failure mode this subsystem exists to prevent
        if rnd in health.inject_nan_rounds:
            batches = poison_batch(batches, "nan")
        elif rnd in health.inject_spike_rounds:
            batches = poison_batch(batches, "spike",
                                   scale=health.inject_spike_scale)
    return precision.cast_host_inputs(batches, compute_dt)


def run_loop(cfg: RunConfig, trainer, train_ds: ArrayDataset,
             test_ds: Optional[ArrayDataset], log: Logger,
             batch_transform=None, eval_transform=None,
             probe: Optional[Callable[[Any], float]] = None,
             round_hook=None, trainer_factory=None):
    """The reference app loop, generic over the trainer backend: any object
    with init_state/place/train_round/evaluate + n_devices (ParallelTrainer
    for the layer IR, GraphTrainer for serialized graphs — the same way
    CaffeSolver and TensorFlowNet sat behind one loop in the reference).

    Multi-host: `train_ds`/`test_ds` are this HOST's shards (apps key them
    on jax.process_index/process_count); the sampler draws windows for the
    locally-addressable devices only, and checkpointing allgathers the
    worker-local state so process 0 writes the global checkpoint (resume
    expects checkpoint_dir on a filesystem all hosts can read). Eval is a
    collective: all hosts must agree on test_ds presence and SIZE
    (ArrayDataset.host_shard splits are exactly equal; uneven sources must
    reconcile first — see imagenet_app._agree_eval_dataset).

    `train_ds` may instead be any round SOURCE — an object with
    `next_round(round_index=...)` (e.g. `data.streaming.StreamingRoundSource`
    for corpora larger than host RAM); sampling/decoding then happens in the
    source's own pipeline. Either way, host-side round preparation (sampling
    + `batch_transform` preprocessing) for round R+1 is overlapped with
    round R's device compute via a one-deep prefetch thread — the reference
    prepared batches inline on each executor and stalled the GPU every
    round.

    `trainer_factory(n_devices)` builds a replacement trainer over a
    resized mesh — the elastic-membership path (cfg.elastic +
    cfg.pod_dir): when the MembershipController declares a worker dead or
    adopts a joiner, the loop checkpoints at the τ boundary, rebuilds the
    compiled round via the factory, restores through the newest verified
    snapshot, and reshards the data. Without a factory (GraphTrainer
    callers) a single-host membership change checkpoints then raises
    ElasticRelaunch (exit 75) so the launcher relaunches at the new size;
    multi-host loops raise without the boundary save (see
    ElasticRelaunch) and resume from the newest periodic checkpoint."""
    n_dev = trainer.n_devices
    n_local = getattr(trainer, "n_local_devices", n_dev)
    # validated at LOOP ENTRY, not at the first save 25 rounds in — the
    # OpsImpl/ElasticConfig fail-at-build rule: a typo'd knob must not
    # cost a run its work (or, with checkpointing off, go unreported)
    if str(getattr(cfg, "checkpoint_sharded", "auto")) not in (
            "auto", "on", "off"):
        raise ValueError(
            f"checkpoint_sharded={cfg.checkpoint_sharded!r}: expected "
            f"'auto', 'on', or 'off'")
    if getattr(log, "worker", None) is None and jax.process_count() > 1:
        # stamp this process's JSONL records with its worker id so the
        # pod summary view can merge the N per-host files
        log.worker = jax.process_index()
    if hasattr(train_ds, "next_round"):
        source = train_ds
        log.log(f"train source: streaming ({n_dev} devices / {n_local} "
                f"local)" + (f"; test examples: {len(test_ds)}"
                             if test_ds else ""))
    else:
        source = RoundSampler(train_ds, n_local, cfg.local_batch, cfg.tau,
                              seed=cfg.seed)
        log.log(f"train examples: {len(train_ds)} on this host "
                f"({len(train_ds) // n_local} per worker; "
                f"{n_dev} devices / {n_local} local)"
                + (f"; test examples: {len(test_ds)}" if test_ds else ""))

    state = trainer.init_state(jax.random.PRNGKey(cfg.seed))
    start_round = 0
    resumed_extra: Dict[str, Any] = {}
    if cfg.checkpoint_dir and cfg.resume:
        last = ckpt.latest_step(cfg.checkpoint_dir)
        if last is not None:
            flat, start_round, extra = ckpt.restore_flat(cfg.checkpoint_dir)
            state, same_topo = _restore_state(trainer, state, flat, extra)
            if same_topo:
                log.log(f"resumed from checkpoint round {start_round}")
            else:
                log.log(f"ELASTIC resume from round {start_round}: "
                        f"{extra.get('n_devices', '?')} devices (tp="
                        f"{extra.get('tp', 1)}) -> {trainer.n_devices} "
                        f"(tp={getattr(trainer, 'tp', 1)})")
            _seek_stream(source, extra, log)
            resumed_extra = extra

    # unified telemetry: one per-run registry every meter/supervisor/
    # writer below registers into; the training process's own /metrics
    # (status server) and the per-round step-time breakdown render from
    # it. cfg.telemetry=False restores the pre-obs loop (the bench.py
    # --obs "disabled" arm measures exactly this switch) — unless a
    # status_port is also set, which is an explicit ask for the scrape
    # surface and therefore forces the registry (an empty /metrics would
    # silently betray the documented contract).
    registry = (MetricsRegistry()
                if cfg.telemetry or cfg.status_port is not None else None)
    g_round = g_loss = c_rounds = None
    g_round_s = g_wait_s = dev_tel = g_variants = None
    if registry is not None:
        register_build_info(registry)
        g_round = registry.gauge("sparknet_train_round",
                                 "last flushed round index")
        g_loss = registry.gauge("sparknet_train_loss",
                                "last flushed round loss")
        c_rounds = registry.counter("sparknet_train_rounds_total",
                                    "rounds dispatched")
        # per-worker straggler-attribution inputs: THIS worker's last
        # round wall time and residual data wait — the pod aggregator
        # compares them across workers (median+MAD) to name the slow host
        g_round_s = registry.gauge(
            "sparknet_train_round_seconds",
            "last round wall time on this worker")
        g_wait_s = registry.gauge(
            "sparknet_train_data_wait_seconds",
            "last round's residual data wait on this worker")
        # device telemetry (obs/device.py): HBM + live arrays sampled at
        # the flush cadence, compile events replayed + followed, and the
        # jitted round's cache size (churn = recompiles) live-read
        dev_tel = obs_device.DeviceTelemetry(registry)
        obs_device.attach_compile_metrics(registry)
        if hasattr(trainer, "compiled_variants"):
            g_variants = registry.gauge(
                "sparknet_train_round_compiled_variants",
                "jit-cache entries for the compiled round (1 = steady "
                "state; growth = recompiles)")
            g_variants.set_fn(trainer.compiled_variants)
    timers = PhaseTimers(registry=registry)
    if cfg.telemetry and hasattr(trainer, "phase_timers"):
        # h2d / dispatch split from inside train_round (ParallelTrainer).
        # Gated on telemetry so the disabled arm really is the pre-obs
        # round path (bench.py --obs compares against it).
        trainer.phase_timers = timers
    meter = ThroughputMeter(n_chips=n_dev, registry=registry)
    # round-keyed rngs: resume at round R reproduces the uninterrupted
    # schedule exactly (reference had no resume at all, SURVEY §5.3)
    base_rng = jax.random.PRNGKey(cfg.seed ^ 0xABCD)

    # capture on the MAIN thread: the precision policy is thread-local and
    # the prefetch thread would otherwise see the default
    compute_dt = precision.compute_dtype()

    # cfg.health=None means NO supervisor — same reading the trainer
    # construction sites use (compute_health=False), so the monitor and
    # the compiled round can't disagree about whether health is on
    health_cfg = (cfg.health if cfg.health is not None
                  else HealthConfig(enabled=False))
    monitor = (HealthMonitor(health_cfg, registry=registry)
               if health_cfg.enabled else None)
    # stage-2 background checkpoint writer (serialize+digest+persist off
    # the round loop's critical path; at most one snapshot in flight).
    # None = fully synchronous saves (cfg.checkpoint_async=False).
    ck_writer = (ckpt.AsyncCheckpointWriter(registry=registry)
                 if cfg.checkpoint_dir and cfg.checkpoint_async else None)
    # liveness heartbeat (process 0 writes; the launcher's watch probes
    # worker 0): one atomic JSON at the flush cadence — "slow vs sick"
    # without log parsing. Every beat is best-effort: a full disk must
    # degrade observability, not kill the run.
    heartbeat = (HeartbeatWriter(cfg.heartbeat_path, role="train",
                                 interval_s=cfg.heartbeat_every_s,
                                 registry=registry)
                 if cfg.heartbeat_path and jax.process_index() == 0
                 else None)
    # pod-scope telemetry (obs/pod.py): EVERY worker rewrites its own
    # heartbeat under the shared pod_dir prefix (local/NFS or gs://|s3://
    # — single small atomic object PUTs), carrying the per-worker round
    # wall time + data wait the aggregator's straggler attribution needs.
    # registry=None: the primary heartbeat above already owns the
    # sparknet_heartbeat_* counters; double-registering would double-count.
    pod_hb = (HeartbeatWriter(
        obs_pod.worker_heartbeat_path(cfg.pod_dir, jax.process_index()),
        role="train", interval_s=cfg.heartbeat_every_s)
        if cfg.pod_dir else None)
    # elastic membership (parallel/elastic.py): watch the pod heartbeats,
    # declare workers dead (stale + full-jitter re-probes, never one
    # missed beat) or joined, and drive a resize at the τ boundary. The
    # heartbeat prefix IS the liveness channel and the verified
    # checkpoint store IS the recovery channel, so both are required.
    elastic_cfg = (cfg.elastic
                   if cfg.elastic is not None and cfg.elastic.enabled
                   else None)
    membership = None
    if elastic_cfg is not None:
        if not cfg.pod_dir:
            raise ValueError(
                "cfg.elastic.enabled requires cfg.pod_dir: the per-worker "
                "heartbeats under it are how membership is observed")
        if not cfg.checkpoint_dir:
            raise ValueError(
                "cfg.elastic.enabled requires cfg.checkpoint_dir: a "
                "resize restores workers from the newest verified "
                "checkpoint")
        membership = MembershipController(
            elastic_cfg, cfg.pod_dir, self_worker=jax.process_index(),
            expected_workers=jax.process_count(), registry=registry)
    # host-side span capture (--trace-out): spans from the round loop,
    # the round-prep prefetch thread and the ckpt-write thread land on
    # per-thread lanes of ONE Chrome-trace timeline (obs/trace.py) —
    # written at loop exit, loadable in Perfetto next to the
    # cfg.profile_dir device trace
    tracer = (obs_trace.start_tracing()
              if cfg.trace_out and jax.process_index() == 0 else None)
    # live vitals for /healthz + /status on the training status server.
    # round_s / data_wait_s are the per-worker straggler inputs — the pod
    # aggregator reads them straight off /status without parsing metrics.
    # beat_ts is the LOOP's own freshness stamp (updated at each flush):
    # a hung round loop whose HTTP daemon thread still answers must read
    # as stale to the pod aggregator, not as alive-and-fresh
    vitals: Dict[str, Any] = {"role": "train", "round": start_round,
                              "status": "ok", "loss": None,
                              "worker": jax.process_index(),
                              "round_s": None, "data_wait_s": None,
                              "beat_ts": round(time.time(), 3)}
    # every process serves its own /metrics since the pod PR: each worker
    # is a scrape surface (the raw feed pod aggregation merges); on a
    # shared host use port 0 — each process binds its own ephemeral port
    status_srv = None
    if cfg.status_port is not None:
        try:
            status_srv = StatusServer(
                cfg.status_port, registry, host=cfg.status_host,
                healthz=lambda: (vitals["status"] not in ("nonfinite",),
                                 {k: v for k, v in vitals.items()}),
                status=lambda: {**vitals,
                                "rollbacks": (monitor.rollbacks
                                              if monitor else 0),
                                "phase_means": timers.summary()})
        except OSError as e:
            # a taken port (co-located processes sharing a fixed
            # status_port) degrades observability, never training —
            # use port 0 for one-ephemeral-port-per-process instead
            warnings.warn(f"status server failed to bind port "
                          f"{cfg.status_port}: {e}; continuing without",
                          RuntimeWarning)
        if status_srv is not None:
            cfg.status_address = status_srv.address
            if jax.process_index() == 0:
                log.log(f"train status server at "
                        f"http://{status_srv.address[0]}:"
                        f"{status_srv.address[1]}/metrics")
    # the SLO ledger's history sampler: the training process gets the
    # same /timeseries surface serve and router processes get, plus
    # JSONL shards for `sparknet-slo` retrospective reports
    history = None
    if cfg.history and registry is not None:
        from ..obs.history import HistoryConfig, MetricsHistory
        history = MetricsHistory(
            registry,
            HistoryConfig(sample_interval_s=cfg.history_interval_s,
                          persist_dir=cfg.history_dir),
            logger=log).start()
        if status_srv is not None:
            history.attach_http(status_srv)
    # worker 0 additionally serves the POD view over the shared heartbeat
    # prefix: merged /metrics + /pod/status with straggler attribution
    pod_srv = None
    if cfg.pod_port is not None and cfg.pod_dir and \
            jax.process_index() == 0:
        try:
            # one staleness rule: the aggregator's down/stale verdicts use
            # the SAME threshold the elastic controller evicts on
            pod_srv = obs_pod.PodAggregator(
                pod_dir=cfg.pod_dir,
                stale_after_s=(elastic_cfg.stale_after_s
                               if elastic_cfg is not None else 120.0)).serve(
                cfg.pod_port, host=cfg.status_host)
        except OSError as e:
            warnings.warn(f"pod status server failed to bind port "
                          f"{cfg.pod_port}: {e}; continuing without",
                          RuntimeWarning)
        else:
            cfg.pod_address = pod_srv.address
            log.log(f"pod status server at http://{pod_srv.address[0]}:"
                    f"{pod_srv.address[1]}/pod/status")

    def beat(step: int, status: str, force: bool = False, **kv) -> None:
        rollbacks = monitor.rollbacks if monitor is not None else 0
        if membership is not None:
            # membership epoch rides every beat so the pod view (and a
            # joiner reading the prefix) sees resizes without scraping
            kv.setdefault("membership_epoch", membership.epoch)
            kv.setdefault("n_members", len(membership.members))
        for hb, extra in ((heartbeat, kv),
                          (pod_hb, {**kv,
                                    "worker": jax.process_index(),
                                    "n_workers": jax.process_count(),
                                    "round_s": vitals.get("round_s"),
                                    "data_wait_s": vitals.get(
                                        "data_wait_s")})):
            if hb is None:
                continue
            try:
                hb.beat(step, status=status, force=force,
                        rollbacks=rollbacks, **extra)
            except OSError as e:
                warnings.warn(f"heartbeat write failed: {e}",
                              RuntimeWarning)

    def ckpt_barrier() -> None:
        """Settle the store before READING it: drain the in-flight write
        (re-raising its failure), and on a pod make every process wait for
        process 0's writer — a rollback target chosen while the newest
        snapshot is still uploading would diverge across hosts."""
        if ck_writer is not None:
            ck_writer.wait()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_write_barrier")
    # rollback generation: bumped per recovery; folds into the round rng
    # and the sampler's logical round so the retried window is
    # deterministic-but-different. retry == 0 reproduces the legacy
    # schedule bit-exactly (resume/replay invariants depend on that).
    # Recovery state RESUMES from the checkpoint: a preemption after a
    # rollback must not silently revert the LR backoff / retried data
    # order / rollback budget the supervisor configured.
    saved_health = resumed_extra.get("health", {})
    retry = int(saved_health.get("retry", 0))
    lr_scale = float(saved_health.get("lr_scale", 1.0))
    if monitor is not None:
        monitor.rollbacks = int(saved_health.get("rollbacks", 0))
    if retry or lr_scale != 1.0:
        log.log(f"health state resumed: retry={retry} "
                f"lr_scale={lr_scale} rollbacks="
                f"{saved_health.get('rollbacks', 0)}")
    supports_lr = bool(getattr(trainer, "supports_lr_scale", False))
    # highest round already dispatched THIS process: rounds at or below it
    # are retries/replays (fault injection only fires above it, so a
    # retried window is clean but later configured rounds still fire)
    high_water = start_round - 1
    # elastic bootstrap: seed membership from the heartbeats already on
    # the prefix (fresh ones only — leftovers of a previous incarnation
    # never count) and pin the devices-per-worker ratio every resize
    # preserves. An indivisible mesh disables LIVE resizing (membership
    # changes then checkpoint-and-relaunch), it never disables watching.
    devices_per_worker = None
    if membership is not None:
        membership.poll(start_round, force=True)
        # the SEEDED membership, not expected_workers: an extra worker
        # with a fresh beat at the first poll is a member from round 0,
        # and the devices-per-worker ratio pinned here must match the
        # membership the later resize events count against
        n_members = max(1, len(membership.members))
        if n_members < max(1, elastic_cfg.min_workers):
            # guard the relaunch loop: a pod relaunched (exit 75) at a
            # size already below min_workers must halt loudly HERE, not
            # bounce between relaunches forever
            raise TrainingHealthError(
                f"elastic: launched with {n_members} worker(s), below "
                f"min_workers={elastic_cfg.min_workers} — refusing to "
                f"start; the newest verified checkpoint resumes once "
                f"capacity returns.")
        if n_dev % n_members == 0:
            devices_per_worker = n_dev // n_members
        else:
            warnings.warn(
                f"elastic: {n_dev} devices over {n_members} workers is "
                f"not an integer devices-per-worker split — membership "
                f"changes will relaunch instead of resizing live",
                RuntimeWarning)
        vitals["membership_epoch"] = membership.epoch
        log.log(f"elastic membership: {sorted(membership.members)} "
                f"({n_members} worker(s), "
                f"{devices_per_worker or '?'} device(s)/worker; "
                f"stale_after={elastic_cfg.stale_after_s}s "
                f"min_workers={elastic_cfg.min_workers})")

    # double-buffered H2D: the prefetch stage not only samples/preprocesses
    # round R+1 but also PLACES it on device (same cast + sharding the
    # dispatch-time path applies — trainer.place_batches' documented
    # contract) while round R's XLA program runs, so train_round's `h2d`
    # phase measures ~0 in steady state. Gated on the knob AND trainer
    # capability (GraphTrainer places at dispatch, as before).
    h2d_prefetch = bool(getattr(cfg, "h2d_prefetch", False)
                        and hasattr(trainer, "place_batches"))

    def prepare_round(rnd: int, retry_: int,
                      first_pass: bool) -> Dict[str, Any]:
        # span: host-side round prep runs on the `round-prep_0` prefetch
        # thread — its own lane in the trace timeline, visualizing the
        # overlap with the device round
        with obs_trace.span("round_prep", round=rnd):
            batches = prepare_round_batches(source, rnd, cfg.tau, cfg.seed,
                                            batch_transform, compute_dt,
                                            retry=retry_, health=health_cfg,
                                            first_pass=first_pass)
            if h2d_prefetch:
                # compute_dt rides along: the precision policy is
                # thread-local and this runs on the round-prep thread
                with obs_trace.span("h2d_prefetch", round=rnd):
                    batches = trainer.place_batches(batches, compute_dt)
            return batches

    # step-time breakdown bookkeeping: per-round deltas of the phase
    # timers (data wait / H2D / compiled-round dispatch / checkpoint
    # stage-1 fetch), plus the collect (deferred loss fetch) and log
    # durations measured at flush. `_last_flush_ms[0]` carries the
    # previous flush's own cost into the next record — a flush cannot
    # time itself into the row it is writing.
    _last_flush_ms = [0.0]

    # async collect (r8): with cfg.collect_async the deferred fetch below
    # runs on a dedicated single-thread collector, so the round loop
    # NEVER blocks on boundary results — t_collect_ms in the breakdown
    # reads ~0 (the loop only enqueues a record) and the real off-thread
    # wait lands as t_collect_bg_ms. FIFO order preserves the JSONL/log
    # row ordering; every boundary (eval, checkpoint, recovery, resize,
    # loop exit) drains the queue first, so supervisor decisions and
    # row ordering are exactly the synchronous loop's, one cadence late
    # at worst — which the deferred fetch already was.
    collect_async = bool(getattr(cfg, "collect_async", False))

    def flush_round_log(rec) -> None:
        """Emit round R's metrics. `float(loss)` here is the pipeline's
        REAL synchronization — deferred one round so round R+1's dispatch
        overlaps round R's device execution (the reference fetched loss
        synchronously every round and stalled the accelerator; on a TPU the
        dispatch+fetch round trip is a large fraction of a round), and
        since r8 dispatched onto the collector thread (collect_async) so
        the loop never blocks on it at all. The health scalars ride the
        same deferred fetch: classification happens here, so anomaly
        detection costs no extra per-round sync and latches a recovery
        decision at the same log_every cadence."""
        t_flush0 = time.perf_counter()
        rnd_, loss_, probe_, health_, breakdown_ = rec
        t_c0 = time.perf_counter()
        loss_ = float(loss_)
        t_collect = time.perf_counter() - t_c0
        kv: Dict[str, Any] = {}
        if breakdown_ is not None:
            if collect_async:
                # the round loop's blocking share is the enqueue: ~0.
                # The fetch above still happened — on THIS collector
                # thread, overlapped with the device round — and is
                # attributed separately so a slow store of health
                # scalars stays visible.
                breakdown_["collect"] = 0.0
                breakdown_["collect_bg"] = t_collect
            else:
                breakdown_["collect"] = t_collect
            breakdown_["log"] = _last_flush_ms[0] / 1e3
            kv.update({f"t_{k}_ms": round(v * 1e3, 3)
                       for k, v in breakdown_.items()})
            # per-worker straggler-attribution feed: /status vitals, the
            # worker's own gauges, and (via beat below) the pod heartbeat
            vitals["round_s"] = round(breakdown_["round"], 6)
            vitals["data_wait_s"] = round(breakdown_["data"], 6)
            if g_round_s is not None:
                g_round_s.set(breakdown_["round"])
                g_wait_s.set(breakdown_["data"])
        if dev_tel is not None:
            dev_tel.sample()  # HBM + live arrays at the log_every cadence
        gnorm = nonf = None
        worker_txt = ""
        if health_ is not None:
            gnorm = float(health_["grad_norm"])
            nonf = float(health_["nonfinite"])
            kv["grad_norm"] = gnorm
            by_worker = health_.get("nonfinite_by_worker")
            if nonf and by_worker is not None:
                # attribution: which data-parallel worker's shard tripped
                # the flag — a consistently bad host/feed shows up as the
                # same index round after round (the [n_data] vector rides
                # the existing psum; see ParallelTrainer.last_health).
                # An all-zero vector means the anomaly has no owner (only
                # the post-average state is poisoned): flag, don't blame.
                vec = np.asarray(by_worker)
                if vec.max() > 0:
                    worst = int(np.argmax(vec))
                    kv["worst_worker"] = worst
                    kv["nonfinite_workers"] = int((vec > 0).sum())
                    worker_txt = (f"  worst worker: {worst} "
                                  f"({int(vec[worst])} flag(s), "
                                  f"{int((vec > 0).sum())}/{vec.size} "
                                  f"workers)")
        cls = None
        if monitor is not None:
            cls = monitor.observe(rnd_, loss_, grad_norm=gnorm,
                                  nonfinite_count=nonf or 0.0)
            if cls != "ok":
                kv["health"] = cls
        probe_txt = (f"  probe: {float(probe_):.6f}"
                     if probe_ is not None else "")
        health_txt = f"  HEALTH: {cls}" if cls not in (None, "ok") else ""
        log.log(f"round loss: {loss_:.4f}{probe_txt}{health_txt}"
                f"{worker_txt}", rnd_)
        log.metrics(rnd_, loss=loss_, images_per_sec_per_chip=round(
            meter.images_per_sec_per_chip(), 2), **kv)
        vitals["round"] = rnd_
        vitals["loss"] = _hb_float(loss_)
        vitals["status"] = cls or "ok"
        vitals["beat_ts"] = round(time.time(), 3)
        if g_round is not None:
            g_round.set(rnd_)
            if math.isfinite(loss_):
                g_loss.set(loss_)
        if tracer is not None:
            tracer.instant("flush", round=rnd_, loss=_hb_float(loss_))
        beat(rnd_, status=cls or "ok", force=(cls not in (None, "ok")),
             last_loss=_hb_float(loss_))
        if cls == "spike" and not monitor.rollback_needed:
            # every supervisor DECISION is an event record: this spike was
            # skipped (excluded from the stats window, training continues)
            log.event(rnd_, "spike_skip", loss=loss_)
        _last_flush_ms[0] = (time.perf_counter() - t_flush0) * 1e3

    # one-deep host prefetch: round R+1 is sampled/decoded/preprocessed on
    # this thread pool while round R's XLA program runs. The "sample" phase
    # then measures only the residual WAIT — ~0 when prep fully overlaps.
    prefetch = ThreadPoolExecutor(1, thread_name_prefix="round-prep")
    pending: Optional[Any] = None
    # pending (rnd, device_loss, device_probe, device_health) records,
    # flushed (= the loop's host sync) every cfg.log_every rounds —
    # holding device scalars is free; fetching one costs a full round trip.
    # A deque: list.pop(0) is O(n) per drain step, O(n^2) per flush — at
    # log_every=1 it is noise, but a high-K flush (or the abort-path drain
    # of a long deferred backlog) must not pay quadratic host time.
    deferred: deque = deque()
    collector = (ThreadPoolExecutor(1, thread_name_prefix="collect")
                 if collect_async else None)
    collect_pending: deque = deque()  # in-flight collector futures (FIFO)

    def flush_deferred(wait: bool = True) -> None:
        """Flush every deferred record: inline (synchronous collect), or
        by handing them to the collector thread. `wait=False` — the
        in-round path only — returns without joining, so the loop never
        blocks on a boundary result; every other call site drains (the
        deferred fetch's ordering/decision points), re-raising a
        collector failure loudly. A bounded in-flight window keeps a
        slow store from piling up device-scalar records."""
        if collector is None:
            while deferred:
                flush_round_log(deferred.popleft())
            return
        while deferred:
            collect_pending.append(
                collector.submit(flush_round_log, deferred.popleft()))
            while len(collect_pending) > max(4, 2 * log_every):
                collect_pending.popleft().result()
        if wait:
            while collect_pending:
                collect_pending.popleft().result()

    def recover(state):
        """Roll back to the newest VERIFIED non-anomalous checkpoint.
        Returns (restored_state, restored_round). Deterministic across
        hosts: the trigger scalars are mesh-reduced (identical on every
        process) and the checkpoint dir is shared, so every process picks
        the same target with no extra communication. Raises
        TrainingHealthError when the rollback budget is exhausted or no
        verified checkpoint exists to roll back to."""
        nonlocal retry, lr_scale, pending
        flush_deferred()  # drain in-flight records of the same incident
        reason = monitor.consume_rollback()  # raises once budget is spent
        if not cfg.checkpoint_dir:
            raise TrainingHealthError(
                f"training health: {reason} detected but no checkpoint_dir "
                f"is configured — nothing to roll back to. Enable "
                f"checkpointing or disable cfg.health.")
        ckpt_barrier()  # the in-flight write may BE the rollback target
        found = ckpt.restore_newest_verified(cfg.checkpoint_dir,
                                             skip_anomalous=True)
        if found is None:
            raise TrainingHealthError(
                f"training health: {reason} detected and no verified "
                f"non-anomalous checkpoint exists under "
                f"{cfg.checkpoint_dir!r} — cannot recover.")
        flat, ck_round, extra = found
        target = ck_round
        try:
            # the verified target may predate an elastic relaunch (old
            # topology): the shared dispatch re-tiles it like resume would
            state, _ = _restore_state(trainer, state, flat, extra)
        except ValueError as e:
            raise TrainingHealthError(
                f"training health: rollback target step {target} cannot "
                f"be loaded — {e}") from e
        retry += 1
        if supports_lr and health_cfg.lr_backoff != 1.0:
            lr_scale *= health_cfg.lr_backoff
        if pending is not None:
            if not pending.cancel():
                try:  # already running: WAIT — the prep thread must not
                    pending.result()  # race the retried round's inline
                except Exception:  # prep on the shared (streaming) source
                    pass
            pending = None
        log.event(ck_round, "rollback", reason=reason, target_step=target,
                  rollbacks=monitor.rollbacks, retry=retry,
                  lr_scale=round(lr_scale, 6))
        beat(ck_round, status="rollback", force=True, reason=reason)
        return state, ck_round

    def apply_resize(state, ev, rnd):
        """Membership changed: drive the safe resize at this τ boundary.

        Order matters: (1) drain the pipeline (deferred fetches, the
        prefetched next round, the in-flight checkpoint write), (2) write
        the boundary snapshot — BOTH the resize restore and the
        min_workers halt must leave a verified checkpoint behind, (3)
        halt loudly if the pod is too small, (4) rebuild the compiled
        round over the new worker set and restore every worker — survivor
        or joiner alike — from the newest verified checkpoint (params
        exact, momentum per the A/B-validated policy), (5) reshard the
        data partitions. Single-host loops that cannot resize (no
        factory / non-reshardable source) do (1)-(3) then raise
        ElasticRelaunch (exit 75) for the launcher. MULTI-HOST loops
        raise ElasticRelaunch before ANY of it: membership is observed
        per process, so the boundary save's collective could hang on a
        split membership view — the relaunch resumes from the newest
        periodic checkpoint instead. Degrade loudly, never hang on a
        collective a dead worker will not join. Returns (state, round)
        like recover()."""
        nonlocal trainer, trainer_factory, source, n_dev, n_local, pending
        flush_deferred()
        if pending is not None:
            if not pending.cancel():
                try:  # already running: wait it out (same rule recover
                    pending.result()  # applies — never race the source)
                except Exception:
                    pass
            pending = None
        if jax.process_count() > 1:
            # membership is observed PER PROCESS (jittered re-probes):
            # processes reach this decision at different rounds, so
            # entering a collective (the boundary checkpoint's
            # allgather) here could hang — the exact failure mode this
            # layer exists to prevent. Exit 75 instead; the launcher
            # relaunches the whole pod at the new size and resume picks
            # up the last periodic checkpoint.
            log.event(rnd, "resize", epoch=ev.epoch, dead=list(ev.dead),
                      joined=list(ev.joined), reasons=ev.reasons,
                      n_workers=ev.n_workers, relaunch=True)
            beat(rnd, status="resize", force=True,
                 dead=list(ev.dead), joined=list(ev.joined))
            if ev.n_workers < max(1, elastic_cfg.min_workers):
                # below min_workers, exit 75 would BOUNCE: the launcher
                # relaunches without a strike, the dead worker is still
                # dead, and the relaunched pod re-evicts its way back
                # here forever. Halt loudly instead — still no boundary
                # save (its collective could hang on a split membership
                # view); the newest periodic checkpoint is the resume
                # point.
                raise TrainingHealthError(
                    f"elastic: pod fell to {ev.n_workers} worker(s) "
                    f"(dead: {list(ev.dead)}), below min_workers="
                    f"{elastic_cfg.min_workers}. Resume from the newest "
                    f"periodic checkpoint under {cfg.checkpoint_dir!r} "
                    f"once capacity returns.")
            raise ElasticRelaunch(
                f"membership epoch {ev.epoch}: {ev.n_workers} worker(s) "
                f"(dead {list(ev.dead)}, joined {list(ev.joined)}); "
                f"multi-host pod relaunches at the new size")
        ckpt_barrier()
        with timers.phase("checkpoint"):
            _save_checkpoint(cfg, trainer, state, rnd, source=source,
                             last_round=rnd - 1,
                             anomalous=(monitor is not None and
                                        monitor.recently_anomalous(rnd)),
                             health_state=_health_state(retry, lr_scale,
                                                        monitor))
        log.event(rnd, "resize", epoch=ev.epoch, dead=list(ev.dead),
                  joined=list(ev.joined), reasons=ev.reasons,
                  n_workers=ev.n_workers)
        vitals["membership_epoch"] = ev.epoch
        beat(rnd, status="resize", force=True,
             dead=list(ev.dead), joined=list(ev.joined))
        if ev.n_workers < max(1, elastic_cfg.min_workers):
            raise TrainingHealthError(
                f"elastic: pod fell to {ev.n_workers} worker(s) "
                f"(dead: {list(ev.dead)}), below min_workers="
                f"{elastic_cfg.min_workers}. A verified checkpoint at "
                f"round {rnd} is saved under {cfg.checkpoint_dir!r} — "
                f"relaunch with capacity to continue.")
        new_n_dev = (devices_per_worker or 0) * ev.n_workers
        can_resize_live = (
            jax.process_count() == 1 and trainer_factory is not None
            and devices_per_worker is not None
            # TP shard assignment changes with the mesh: resized() would
            # raise — take the checkpoint-and-relaunch path instead
            and getattr(trainer, "tp", 1) == 1
            and 0 < new_n_dev <= len(jax.devices())
            and hasattr(source, "reshard"))
        if not can_resize_live:
            raise ElasticRelaunch(
                f"membership epoch {ev.epoch}: {ev.n_workers} worker(s) "
                f"(dead {list(ev.dead)}, joined {list(ev.joined)}); "
                f"checkpointed round {rnd}")
        old_trainer, old_state = trainer, state
        trainer = trainer_factory(new_n_dev)
        if hasattr(trainer, "resized"):
            # rebind the factory: the old one is a bound method of the
            # PREVIOUS trainer and would pin it (and its compiled round
            # executable) alive for the rest of the run
            trainer_factory = trainer.resized
        replaced_live = (hasattr(trainer, "adapt_live") and
                         getattr(old_trainer, "state_layout", "")
                         == "logical")
        if replaced_live:
            # NamedSharding trainer: the resize is a RE-PLACEMENT — the
            # live logical state (params topology-free, momentum rows
            # policy-mapped) moves straight onto the new mesh; the
            # boundary checkpoint just written stays the durable record
            # but the store is never read back
            state = trainer.adapt_live(
                old_state, momentum_policy=elastic_cfg.momentum_policy)
            ck_round = rnd
        else:
            found = ckpt.restore_newest_verified(cfg.checkpoint_dir)
            if found is None:
                raise TrainingHealthError(
                    f"elastic: membership changed but no verified "
                    f"checkpoint exists under {cfg.checkpoint_dir!r} to "
                    f"resize from.")
            flat, ck_round, extra = found
            state = trainer.adapt_state(
                flat, old_tp=int(extra.get("tp", 1)),
                momentum_policy=elastic_cfg.momentum_policy,
                old_layout=extra.get("layout", "replica"))
        del old_trainer, old_state
        source = source.reshard(trainer.n_local_devices)
        n_dev = trainer.n_devices
        n_local = trainer.n_local_devices
        meter.n_chips = n_dev
        if cfg.telemetry and hasattr(trainer, "phase_timers"):
            trainer.phase_timers = timers
        if g_variants is not None and hasattr(trainer, "compiled_variants"):
            g_variants.set_fn(trainer.compiled_variants)
        log.log(f"elastic resize: epoch {ev.epoch} -> {ev.n_workers} "
                f"worker(s) on {n_dev} device(s); "
                + (f"re-placed live state at round {ck_round}"
                   if replaced_live else
                   f"restored verified round {ck_round}")
                + (f"; evicted {list(ev.dead)}" if ev.dead else "")
                + (f"; joined {list(ev.joined)}" if ev.joined else ""))
        return state, ck_round

    def expand_tau(by_worker: Optional[Dict[str, int]]):
        """Per-worker τ budgets -> the per-DATA-GROUP vector the trainer
        takes (a worker may own several device groups). Multi-host: a
        group's owner is the process owning its devices (mesh order,
        model-minor under TP). Single process — the virtual-pod
        simulation, where every device belongs to process 0 — members
        own contiguous blocks of groups in sorted-id order, matching the
        devices-per-worker resize math. Unknown owners run full τ."""
        if not by_worker:
            return None
        from ..parallel.elastic import worker_sort_key
        n_data = getattr(trainer, "n_data", n_dev)
        tp = getattr(trainer, "tp", 1)
        if jax.process_count() > 1:
            flat = list(trainer.mesh.devices.flat)
            return [by_worker.get(str(flat[g * tp].process_index), cfg.tau)
                    for g in range(n_data)]
        order = sorted(membership.members, key=worker_sort_key)
        m = max(1, len(order))
        # balanced contiguous blocks (sizes differ by <= 1): identical to
        # the devices-per-worker split when n_data % m == 0, and never
        # lumps every remainder group onto the LAST worker's budget when
        # the mesh is indivisible
        return [by_worker.get(order[min(g * m // n_data, m - 1)], cfg.tau)
                for g in range(n_data)]

    # per-round phase deltas for the step-time breakdown rows: the phase
    # timers accumulate forever; this tracks the last-seen totals so each
    # round's record carries only its own share
    last_tot: Dict[str, float] = {}

    def _phase_delta(name: str) -> float:
        cur = timers.total.get(name, 0.0)
        d = cur - last_tot.get(name, 0.0)
        last_tot[name] = cur
        return d

    log_every = max(1, cfg.log_every)
    rnd = start_round
    loop_completed = False  # set on the normal exit path only: the
    # finally block must re-raise a failed background checkpoint write on
    # a clean run, but never mask the exception of an aborted one
    try:
        while rnd < cfg.max_rounds:
            if monitor is not None and monitor.rollback_needed:
                state, rnd = recover(state)
                continue
            if membership is not None:
                # the τ boundary: between rounds every worker's params
                # are synchronized, so this is the one safe resize point
                ev = membership.poll(rnd)
                if ev is not None:
                    state, rnd = apply_resize(state, ev, rnd)
                    continue
            if test_ds is not None and cfg.eval_every and \
                    rnd % cfg.eval_every == 0:
                # keep log/JSONL round-ordered: earlier loss rows must
                # precede round R's eval row (eval blocks on the in-flight
                # round anyway, so this costs no overlap)
                flush_deferred()
                if monitor is not None and monitor.rollback_needed:
                    continue  # don't eval a poisoned state
                with timers.phase("eval"):
                    acc = _evaluate(trainer, state, test_ds, cfg.eval_batch,
                                    n_local, transform=eval_transform)
                log.log(f"test accuracy: {acc:.4f}", rnd)
                log.metrics(rnd, test_accuracy=acc)

            with timers.phase("sample"):
                batches = (pending.result() if pending is not None
                           else prepare_round(rnd, retry,
                                              rnd > high_water))
            pending = None
            if rnd + 1 < cfg.max_rounds:
                pending = prefetch.submit(prepare_round, rnd + 1, retry,
                                          rnd + 1 > high_water)
            high_water = max(high_water, rnd)
            sub = jax.random.fold_in(base_rng, rnd)
            if retry:  # deterministic-but-different retried window
                sub = jax.random.fold_in(sub, retry)
            before = timers.total.get("train_round", 0.0)
            # trace ONE steady-state round (the first would trace compile)
            profile_this = cfg.profile_dir and rnd == start_round + 1
            with profiling.maybe_trace(cfg.profile_dir if profile_this
                                       else None):
                with timers.phase("train_round"):
                    tr_kw: Dict[str, Any] = {}
                    if supports_lr and lr_scale != 1.0:
                        tr_kw["lr_scale"] = lr_scale
                    if getattr(trainer, "elastic_tau", False) and \
                            membership is not None:
                        # heterogeneous pods: per-worker local-step
                        # budgets from the heartbeat round times (a
                        # traced input — adapting never recompiles),
                        # expanded to one entry per data group
                        tr_kw["tau_by_worker"] = expand_tau(
                            membership.tau_by_worker(cfg.tau))
                    state, loss = trainer.train_round(state, batches, sub,
                                                      **tr_kw)
                    # async probe slice MUST precede the next dispatch
                    # (donation invalidates the old state buffers)
                    probe_val = probe(state) if probe else None
                    if len(deferred) >= log_every:
                        # collect_async: enqueue only — the collector
                        # thread syncs on rounds <= rnd-1 while this
                        # loop dispatches ahead. Sync mode blocks here
                        # (the pre-r8 pipeline's one-round overlap).
                        flush_deferred(wait=False)
            if profile_this:
                log.log(f"profiler trace written to {cfg.profile_dir}", rnd)
            # steady state (log_every=1), this measures one device round:
            # dispatch of rnd + wait for rnd-1 (overlap of exactly one
            # round); with log_every=K the sync cost amortizes over K
            round_dt = timers.total["train_round"] - before
            n_images = cfg.tau * cfg.local_batch * n_dev
            meter.add(n_images, round_dt)
            breakdown = None
            if cfg.telemetry:
                d_sample = _phase_delta("sample")
                d_h2d = _phase_delta("h2d")
                d_disp = _phase_delta("dispatch")
                # checkpoint stage-1 accrues AFTER the record is appended,
                # so the delta seen here is the PREVIOUS round's fetch —
                # honest attribution: that stall delayed THIS round
                d_ck = _phase_delta("checkpoint")
                breakdown = {
                    "data": d_sample, "h2d": d_h2d,
                    # trainers without the h2d/dispatch split (GraphTrainer)
                    # report the whole timed round
                    "round": d_disp if d_disp > 0 else round_dt,
                    "ckpt_fetch": d_ck}
            if c_rounds is not None:
                c_rounds.inc()
            deferred.append((rnd, loss, probe_val,
                             getattr(trainer, "last_health", None),
                             breakdown))

            if cfg.checkpoint_dir and cfg.checkpoint_every and \
                    (rnd + 1) % cfg.checkpoint_every == 0:
                flush_deferred()  # keep log rows round-ordered; the
                if monitor is not None and monitor.rollback_needed:
                    continue  # NEVER checkpoint over good state with a
                    #           poisoned one; loop top recovers instead
                anomalous = (monitor is not None
                             and monitor.recently_anomalous(rnd))
                # the timed phase is the loop's BLOCKING stall only: the
                # device->host fetch (+ waiting out a still-running
                # previous write); stage 2 persists in the background
                with timers.phase("checkpoint"):
                    _save_checkpoint(cfg, trainer, state, rnd + 1,
                                     source=source, last_round=rnd,
                                     anomalous=anomalous,
                                     health_state=_health_state(
                                         retry, lr_scale, monitor),
                                     writer=ck_writer)
                if anomalous:
                    log.event(rnd, "anomalous_checkpoint",
                              checkpoint_step=rnd + 1)
                log.log("checkpoint saved" if ck_writer is None else
                        "checkpoint snapshotted (async write)", rnd)
            if round_hook:
                round_hook(rnd, state)
            rnd += 1
            if rnd >= cfg.max_rounds:
                # the final rounds' health records are still on device:
                # flush so an anomaly in the tail window triggers recovery
                # BEFORE the loop exits and the final checkpoint is written
                flush_deferred()
                if monitor is not None and monitor.rollback_needed:
                    state, rnd = recover(state)
        loop_completed = True
    finally:
        if deferred:  # loop aborted: drain the pending fetches
            try:
                flush_deferred()
            except Exception:
                pass
        if pending is not None:
            pending.cancel()
        prefetch.shutdown(wait=False, cancel_futures=True)
        if collector is not None:
            # drain the collector (its queue may hold the abort-path
            # records just submitted above); a failed flush must not
            # mask the propagating exception
            try:
                while collect_pending:
                    collect_pending.popleft().result()
            except Exception:
                pass
            collector.shutdown(wait=True)
        if hasattr(source, "close"):
            source.close()
        try:
            if ck_writer is not None:
                # loop exit barriers on the in-flight write: a RUNNING
                # stage-2 write always completes (the final checkpoint
                # below, and any reader of the dir after train() returns,
                # must see a settled store). On the normal path a failed
                # background write raises here; when another exception is
                # already propagating (loop_completed is still False) it
                # must not be masked — log and let the original win.
                try:
                    ck_writer.close(wait=True)
                except Exception as e:
                    if loop_completed:
                        raise
                    log.log(f"background checkpoint write failed during "
                            f"abort: {e}")
        finally:
            # obs teardown runs EVEN when the writer's failure is
            # re-raising: the port must unbind and the process-global
            # tracer must uninstall (a leaked active tracer would keep
            # swallowing every later span in this process)
            if history is not None:
                history.stop()
            if status_srv is not None:
                status_srv.stop()
            if pod_srv is not None:
                pod_srv.stop()
            if tracer is not None:
                # stop AFTER the writer drained: the final
                # checkpoint_write span must land on its lane. Writing
                # the file is observability, not training — it degrades,
                # never raises.
                obs_trace.stop_tracing()
                try:
                    n_ev = tracer.write(cfg.trace_out)
                    log.log(f"host trace written to {cfg.trace_out} "
                            f"({n_ev} events; load in Perfetto or "
                            f"chrome://tracing)")
                except OSError as e:
                    log.log(f"host trace write failed: {e}")

    if cfg.checkpoint_dir and start_round < cfg.max_rounds:
        # start_round >= max_rounds means the loop ran ZERO rounds (a
        # relaunch of a completed run): the restored checkpoint is already
        # the final state, and re-saving would overwrite it with no stream
        # cursor (cursor_at has seen no rounds), destroying the resume
        # position a later extended run needs
        _save_checkpoint(cfg, trainer, state, cfg.max_rounds, retain=False,
                         source=source, last_round=cfg.max_rounds - 1,
                         anomalous=(monitor is not None and
                                    monitor.recently_anomalous(
                                        cfg.max_rounds - 1)),
                         health_state=_health_state(retry, lr_scale,
                                                    monitor))
    if monitor is not None and (monitor.counts["spike"]
                                or monitor.counts["nonfinite"]):
        log.log(f"health summary: {monitor.counts['spike']} spikes, "
                f"{monitor.counts['nonfinite']} nonfinite rounds, "
                f"{monitor.rollbacks} rollbacks")
    beat(rnd, status="done", force=True)
    for hb in (heartbeat, pod_hb):
        if hb is not None:
            hb.flush()  # bounded wait so the done beat lands on buckets
    log.log(f"done; phase means: {timers.summary()}")
    return state


def _restore_state(trainer, state, flat: Dict[str, np.ndarray],
                   extra: Dict[str, Any]):
    """Load a restored flat checkpoint into the trainer's state layout:
    same-topology place, or the elastic adapt_state path. Returns
    (state, same_topology). Shared by resume and health rollback so the
    two cannot drift.

    The elastic path is keyed on the SAVED topology, never on a shape
    error: an architecture change on the same topology must fail loudly
    through unflatten_like, not be silently adapted. Pre-topology-metadata
    checkpoints carry no n_devices/tp keys; infer the saved device count
    from the leading replica axis of the 'it' counter (every state layout
    tiles it [n_devices]) instead of assuming same-topology and dying in
    unflatten_like."""
    tp_now = getattr(trainer, "tp", 1)
    saved_dev = extra.get("n_devices")
    if saved_dev is None and "it" in flat:
        it_arr = np.asarray(flat["it"])
        if it_arr.ndim:
            saved_dev = it_arr.shape[0]
    # the state LAYOUT is part of the topology: a logical (NamedSharding
    # trainer) checkpoint under a replica-axis trainer — or the reverse,
    # or a different state_sharding mode (momentum shape changes) — must
    # take the adapt path, not unflatten_like
    saved_layout = extra.get("layout", "replica")
    t_layout = getattr(trainer, "state_layout", "replica")
    same_topo = (int(saved_dev or trainer.n_devices) == trainer.n_devices
                 and int(extra.get("tp", tp_now)) == tp_now
                 and saved_layout == t_layout
                 and (extra.get("state_sharding", "replicated")
                      == getattr(trainer, "state_sharding", "replicated")))
    if same_topo:
        return trainer.place(ckpt.unflatten_like(state, flat)), True
    if not hasattr(trainer, "adapt_state"):
        raise ValueError(
            f"checkpoint topology {extra} != current "
            f"({trainer.n_devices} devices, tp={tp_now}) and this trainer "
            f"cannot adapt — resume on the original topology")
    # ELASTIC / cross-layout: params re-tiled exactly, momentum
    # reconstructed (adapt_state; old_layout routes the parse). Only the
    # layer-IR trainers declare state_layout and accept old_layout= —
    # GraphTrainer.adapt_state(flat, old_tp) predates layouts, and a
    # logical checkpoint has no graph-backend reading anyway.
    kw = {"old_tp": int(extra.get("tp", 1))}
    if hasattr(trainer, "state_layout"):
        kw["old_layout"] = saved_layout
    elif saved_layout != "replica":
        raise ValueError(
            f"checkpoint layout {saved_layout!r} needs a layer-IR trainer "
            f"to adapt; {type(trainer).__name__} only reads replica "
            f"checkpoints")
    return trainer.adapt_state(flat, **kw), False


def _stream_rows(source, last_round: Optional[int]) -> Optional[list]:
    """Per-host stream cursors after `last_round`, allgathered so process
    0's checkpoint covers every host's stream position: one entry per host,
    each a [[shard, entry, epochs], ...] list with one row PER READER
    (ParallelStreamingSource runs N concurrent readers per host; a single
    StreamingRoundSource is the N=1 case). None when the source is not
    seekable or the cursor is no longer retained. Collective when
    multi-host — every process calls _save_checkpoint already."""
    if last_round is None or not hasattr(source, "cursor_at"):
        return None
    cur = source.cursor_at(last_round)
    if cur is None:
        return None
    if not isinstance(cur, list):  # single-reader source
        cur = [cur]
    rows = np.asarray([[s, e, ep] for (s, e), ep in cur], np.int64)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        rows = np.asarray(multihost_utils.process_allgather(rows))
    else:
        rows = rows[None]
    return rows.tolist()


def _seek_stream(source, extra: Dict[str, Any], log: Logger) -> None:
    """Resume the stream position recorded in the checkpoint (per host, one
    cursor row per reader). Host-count OR reader-count changes restart the
    stream from shard 0 — the shard assignment itself changed, so old
    cursors are meaningless. Accepts the pre-r4 flat [shard, entry,
    epochs]-per-host format as a 1-reader cursor."""
    rows = extra.get("stream")
    if rows is None:
        return
    if len(rows) != jax.process_count():
        log.log(f"stream cursor in checkpoint covers {len(rows)} hosts, "
                f"now {jax.process_count()}: restarting stream at shard 0")
        return
    host_rows = rows[jax.process_index()]
    if host_rows and not isinstance(host_rows[0], list):
        host_rows = [host_rows]  # legacy flat single-reader row
    if hasattr(source, "seek_rows"):
        if not source.seek_rows(host_rows):
            log.log(f"stream cursor in checkpoint covers {len(host_rows)} "
                    f"readers, source has a different count: restarting "
                    f"stream at shard 0")
            return
    elif hasattr(source, "seek") and len(host_rows) == 1:
        shard, entry, epochs = host_rows[0]
        source.seek((shard, entry), epochs)
    else:
        return
    pos = ", ".join(f"shard {s} entry {e} (epoch {ep})"
                    for s, e, ep in host_rows)
    log.log(f"stream resumed at {pos}")


def _health_state(retry: int, lr_scale: float,
                  monitor: Optional[HealthMonitor]) -> Optional[Dict[str,
                                                                     Any]]:
    """Supervisor recovery state for the checkpoint `extra` — only when it
    differs from a fresh run's (vanilla checkpoints stay byte-identical to
    the pre-health format)."""
    rollbacks = monitor.rollbacks if monitor is not None else 0
    if not retry and lr_scale == 1.0 and not rollbacks:
        return None
    return {"retry": int(retry), "lr_scale": float(lr_scale),
            "rollbacks": int(rollbacks)}


def _sharded_save_enabled(cfg: RunConfig, trainer, state) -> bool:
    """Resolve cfg.checkpoint_sharded for this trainer/state. "auto":
    sharded for multi-device layer-IR trainers (the state carries
    NamedShardings to key the piece plan on); monolithic for the graph
    backend and single-device runs, where there is nothing to split.
    "on" forces and fails loudly where the plan has no shardings to read;
    "off" restores the monolithic fetch_global path wholesale."""
    knob = str(getattr(cfg, "checkpoint_sharded", "off"))
    if knob not in ("auto", "on", "off"):
        raise ValueError(f"checkpoint_sharded={knob!r}: expected "
                         f"'auto', 'on', or 'off'")
    if knob == "off":
        return False
    placed = hasattr(trainer, "mesh") and all(
        isinstance(leaf, jax.Array)
        for leaf in jax.tree_util.tree_leaves(state))
    if knob == "on":
        if not placed:
            raise ValueError(
                "checkpoint_sharded='on' needs a mesh trainer with "
                "device-placed state (the shard plan is keyed on each "
                "leaf's NamedSharding) — the graph backend and host "
                "states save monolithically")
        return True
    return (placed and getattr(trainer, "state_layout", None) is not None
            and trainer.n_devices > 1)


def _save_checkpoint(cfg: RunConfig, trainer, state, step: int,
                     retain: bool = True, source=None,
                     last_round: Optional[int] = None,
                     anomalous: bool = False,
                     health_state: Optional[Dict[str, Any]] = None,
                     writer: Optional[ckpt.AsyncCheckpointWriter] = None
                     ) -> None:
    """Two-stage checkpoint save. Stage 1 (here, blocking — every host
    must call this): snapshot the state to host buffers and the stream
    cursors. Since r8 the default stage 1 is GATHER-FREE
    (`fetch_state_shards`): each worker materializes only the distinct
    state pieces its own devices hold — never the full state on one host
    — and stage 2 writes them as parallel per-shard files with a
    manifest commit marker (`ckpt.save_sharded`). The monolithic
    `fetch_global` allgather remains the fallback (graph backend, one
    device, cfg.checkpoint_sharded="off"); restores read both layouts
    bit-identically. Stage 2 (serialize + digest + persist) is inline
    when `writer` is None, else handed to the background writer thread
    so the round loop resumes as soon as the host buffers exist — the
    snapshot is immutable numpy, so later rounds can't tear it. The
    saved logical bytes, digests, and tagging are IDENTICAL in both
    modes.

    The saved topology (device count, tp) lets a differently-sized job
    resume elastically; streaming sources also record their per-host
    stream cursor so resume seeks instead of re-streaming from shard 0.
    `anomalous=True` tags a checkpoint taken during an unhealthy training
    window (recent spike/nonfinite rounds) so the health supervisor's
    rollback skips it."""
    sharded = _sharded_save_enabled(cfg, trainer, state)
    snapshot = host_state = None
    if sharded:
        if jax.process_count() > 1:
            # multi-process stage-1 cleanup (decommit an overwritten
            # step, clear the step's stale files + commit reports,
            # sweep orphans) fenced on BOTH sides: first every process
            # drains its own in-flight stage-2 write and barriers (the
            # previous step's uncommitted shard files must never read
            # as sweepable orphans mid-write — writer.submit would
            # have waited anyway, the backpressure just lands a beat
            # earlier), then process 0 cleans, then a second barrier
            # orders the cleanup before any peer's stage-2 writes
            from jax.experimental import multihost_utils
            if writer is not None:
                writer.wait()
            multihost_utils.sync_global_devices(
                f"sharded_ckpt_drain_{step}")
            if jax.process_index() == 0:
                ckpt.prepare_sharded_step(cfg.checkpoint_dir, step)
            multihost_utils.sync_global_devices(
                f"sharded_ckpt_prepare_{step}")
        # gather-free stage 1: per-shard host pieces, async D2H first;
        # own_data deep-copies any piece view still aliasing a device
        # buffer (donation may reuse it under the async stage 2)
        from ..parallel.mesh import fetch_state_shards
        snapshot = fetch_state_shards(state, trainer.mesh)
    else:
        host_state = fetch_global(state)
        if writer is not None:
            # the background writer must OWN its bytes: np.asarray on a
            # CPU-backend jax array can be a zero-copy VIEW of the device
            # buffer, and the next round's jitted step DONATES that
            # buffer — the sync path finished serializing before the
            # donation could reuse it, but stage 2 overlaps later rounds.
            # One defensive memcpy of any non-owning leaf (~50 ms for a
            # 244 MB state, still ~1000x under the sync stall);
            # real-device fetches already own their memory and copy
            # nothing here. (The sharded path owns its pieces already —
            # fetch_state_shards' own_data default.)
            host_state = jax.tree.map(
                lambda a: a if a.flags["OWNDATA"] else np.array(a),
                host_state)
    stream = _stream_rows(source, last_round) if source is not None else None
    if jax.process_index() != 0 and not sharded:
        return  # monolithic: process 0 is the only writer; sharded:
        #         every process persists its own shard files

    def persist() -> None:
        # publish instant from the TRAINING loop's side: persist() runs
        # at the head of stage 2 (inline or on the writer thread), so
        # this is when the weights left the round loop. checkpoint.py
        # re-stamps the authoritative top-level commit_ts at meta-write
        # time; the serve fleet's freshness metric keys off that one,
        # this tag survives in extra for commit-latency forensics.
        extra = {"n_devices": trainer.n_devices,
                 "tp": getattr(trainer, "tp", 1),
                 "publish_t": round(time.time(), 3)}
        layout = getattr(trainer, "state_layout", "replica")
        if layout != "replica":
            # NamedSharding trainer: logical leaves (no [n_devices] axis).
            # Stamped so restore routes between the layouts; the momentum
            # SHAPE additionally depends on the state_sharding mode
            # ([n_data] worker rows vs one ZeRO-averaged tree). Replica
            # checkpoints stay byte-identical to the pre-r7 format.
            extra["layout"] = layout
            extra["state_sharding"] = getattr(trainer, "state_sharding",
                                              "replicated")
        if stream is not None:
            extra["stream"] = stream
        if anomalous:
            extra["anomalous"] = True
        if health_state is not None:
            extra["health"] = health_state
        if sharded:
            ckpt.save_sharded(
                cfg.checkpoint_dir, snapshot, step=step, extra=extra,
                metrics=writer.note_write if writer is not None else None)
        else:
            ckpt.save(cfg.checkpoint_dir, host_state, step=step,
                      extra=extra)
        if retain and jax.process_index() == 0:
            try:
                ckpt.retain(cfg.checkpoint_dir, keep=3)
            except Exception as e:
                # retention is best-effort (its own delete paths already
                # warn-and-continue): a store blip during the protect
                # scan's reads must not surface as a FATAL writer error
                # when the checkpoint itself saved fine — the next save
                # re-runs retention. The propagation inside retain still
                # matters: it aborts the scan BEFORE deleting anything.
                warnings.warn(f"checkpoint retention failed (snapshot "
                              f"step-{step} saved OK): {e}",
                              RuntimeWarning)

    if writer is not None:
        writer.submit(persist)
    else:
        persist()


def _to_device_layout(ds: ArrayDataset, net: CompiledNet) -> ArrayDataset:
    """One-time NCHW -> NHWC conversion for 4D inputs that arrive in the
    reference's Caffe layout (same disambiguation as JaxNet input_layout
    'auto')."""
    arrays = dict(ds.arrays)
    for name, want in net.input_shapes.items():
        arr = arrays.get(name)
        if arr is None or arr.ndim != 4:
            continue
        want_el = tuple(want[1:])
        if tuple(arr.shape[1:]) != want_el and \
                (arr.shape[2], arr.shape[3], arr.shape[1]) == want_el:
            arrays[name] = np.ascontiguousarray(
                np.transpose(arr, (0, 2, 3, 1)))
    return ArrayDataset(arrays)


def _evaluate(trainer, state, test_ds: ArrayDataset, eval_batch: int,
              n_dev: int, transform=None) -> float:
    """Distributed eval (reference `CifarApp.scala:107-124`), covering every
    example except at most n_dev-1 trailing ones (batches must split evenly
    across devices): the tail past the last full eval_batch is evaluated as
    one smaller batch (a second compiled shape, amortized across rounds) and
    weighted by its real size.

    `transform` preprocesses each eval batch lazily (train=False — e.g.
    center crop + mean subtract on raw uint8 pixels), so only one batch of
    float32 pixels ever exists at a time — the whole-split float32
    materialization would be ~6x the uint8 corpus."""
    eval_batch = min(eval_batch, len(test_ds))
    eval_batch = max(n_dev, (eval_batch // n_dev) * n_dev)
    if len(test_ds) < eval_batch:
        raise ValueError(
            f"test set ({len(test_ds)}) smaller than {n_dev} devices' "
            f"minimum eval batch")

    def run(lo: int, n: int) -> float:
        batch = {k: v[lo:lo + n] for k, v in test_ds.arrays.items()}
        if transform is not None:
            batch = transform.convert_batch(batch, train=False)
        return trainer.evaluate(state, batch) * n

    total, count = 0.0, 0
    n_full = (len(test_ds) // eval_batch) * eval_batch
    for i in range(0, n_full, eval_batch):
        total += run(i, eval_batch)
        count += eval_batch
    tail = ((len(test_ds) - n_full) // n_dev) * n_dev
    if tail:
        total += run(n_full, tail)
        count += tail
    return total / max(count, 1)
