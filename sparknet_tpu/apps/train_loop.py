"""The canonical training driver: the reference's app loop, mesh-native.

Reference shape (`apps/CifarApp.scala:100-149`):
    while true:
      broadcast weights; set on workers        -> (free: device-resident)
      every Nth round: distributed eval        -> trainer.evaluate (psum)
      foreachPartition: τ local solver steps   -> trainer.train_round (scan)
      collect + average weights on driver      -> (inside round: pmean)
      log conv1[0] divergence probe            -> probe_value()

Additions the reference lacked (SURVEY §5.3-5.5): checkpoint/resume of the
full TrainState + round counter, metrics JSONL, per-phase timing, and a
termination condition (max_rounds instead of `while(true)`).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..model.net import CompiledNet
from ..model.spec import NetSpec
from ..parallel.mesh import fetch_global, make_mesh
from ..parallel.trainer import ParallelTrainer, TrainState
from ..data.dataset import ArrayDataset, RoundSampler
from ..utils import checkpoint as ckpt
from ..utils import profiling
from ..utils.config import RunConfig
from ..utils.logger import Logger, default_logger
from ..utils.metrics import PhaseTimers, ThroughputMeter
from .. import precision


def resolve_spec(cfg: RunConfig, **input_shapes) -> NetSpec:
    """cfg.model -> NetSpec: a zoo builder name, or a .prototxt path
    (capability parity: the reference's apps loaded prototxt data files,
    `apps/CifarApp.scala:83-88`)."""
    from .. import zoo
    from ..model.prototxt import net_from_prototxt_file
    if cfg.model.endswith(".prototxt"):
        return net_from_prototxt_file(
            cfg.model, input_shapes=input_shapes or None)
    builders = {
        "cifar10_quick": lambda: zoo.cifar10_quick(batch=cfg.local_batch),
        "caffenet": lambda: zoo.caffenet(batch=cfg.local_batch,
                                         crop=cfg.crop or 227,
                                         n_classes=cfg.n_classes),
        "lenet": lambda: zoo.lenet(batch=cfg.local_batch),
        "adult_mlp": lambda: zoo.adult_mlp(batch=cfg.local_batch),
    }
    if cfg.model not in builders:
        raise ValueError(f"unknown model {cfg.model!r}: expected a .prototxt "
                         f"path or one of {sorted(builders)}")
    return builders[cfg.model]()


def resolve_solver(cfg: RunConfig):
    """Apply cfg.solver_prototxt over cfg.solver if set."""
    if cfg.solver_prototxt:
        from ..model.prototxt import solver_from_prototxt_file
        from ..solver import SolverConfig
        cfg.solver = SolverConfig.from_dict(
            solver_from_prototxt_file(cfg.solver_prototxt))
    return cfg.solver


def probe_value(state: TrainState, net: CompiledNet):
    """First scalar of the first parametric layer's weights — the reference's
    divergence probe (`apps/CifarApp.scala:147` logged conv1 weight [0]).

    Single-process: returns a 0-d DEVICE scalar (an async slice — the loop
    fetches it one round later, so the probe never stalls the pipeline; the
    slice is enqueued before the next round's donation invalidates the
    state buffers). Multi-host: reads a locally-addressable shard to a host
    float (post-round params are replica-identical, any shard's value is
    THE value)."""
    leaf = state.params[net.param_layers()[0]]["w"]
    if hasattr(leaf, "addressable_shards") and not getattr(
            leaf, "is_fully_addressable", True):
        arr = np.asarray(leaf.addressable_shards[0].data)
        return float(arr.reshape(-1)[0])
    if hasattr(leaf, "devices"):
        return leaf[(0,) * leaf.ndim]
    return float(np.asarray(leaf).reshape(-1)[0])


def train(cfg: RunConfig, spec: NetSpec, train_ds: ArrayDataset,
          test_ds: Optional[ArrayDataset] = None,
          logger: Optional[Logger] = None,
          round_hook: Optional[Callable[[int, TrainState], None]] = None,
          batch_transform=None, eval_transform=None) -> TrainState:
    """Run the full distributed training loop per cfg (layer-IR backend).
    Returns final state."""
    log = logger or default_logger(cfg.workdir)
    precision.set_policy(cfg.precision)
    resolve_solver(cfg)
    net = CompiledNet.compile(spec)
    mesh = make_mesh(cfg.n_devices)
    n_dev = int(np.prod(mesh.devices.shape))
    trainer = ParallelTrainer(net, cfg.solver, mesh, tau=cfg.tau,
                              mode=cfg.mode)
    log.log(f"mesh: {n_dev} devices; tau={cfg.tau} mode={cfg.mode} "
            f"local_batch={cfg.local_batch} precision={cfg.precision}")
    if batch_transform is None:
        train_ds = _to_device_layout(train_ds, net)
    if test_ds is not None and eval_transform is None:
        test_ds = _to_device_layout(test_ds, net)
    return run_loop(cfg, trainer, train_ds, test_ds, log,
                    batch_transform=batch_transform,
                    eval_transform=eval_transform,
                    probe=lambda s: probe_value(s, net),
                    round_hook=round_hook)


def prepare_round_batches(source, rnd: int, tau: int, seed: int,
                          batch_transform, compute_dt) -> Dict[str, Any]:
    """One round's host-side work: sample -> per-τ-slice preprocessing
    (e.g. fresh random crops; rng keyed (seed, round, slice) so resume
    reproduces identical crops) -> compute-dtype cast. The cast happens
    here, on the prefetch thread — at dispatch time it would serialize a
    full-batch astype into the pipelined path (`compute_dt` must be
    captured on the MAIN thread; the precision policy is thread-local).
    Module-level so `bench.py --e2e` times exactly this code path."""
    batches = source.next_round(round_index=rnd)
    if batch_transform is not None:
        slices = [batch_transform.convert_batch(
            {k: v[t] for k, v in batches.items()}, train=True,
            rng=np.random.default_rng((seed, rnd, t)))
            for t in range(tau)]
        batches = {k: np.stack([s[k] for s in slices])
                   for k in slices[0]}
    return precision.cast_host_inputs(batches, compute_dt)


def run_loop(cfg: RunConfig, trainer, train_ds: ArrayDataset,
             test_ds: Optional[ArrayDataset], log: Logger,
             batch_transform=None, eval_transform=None,
             probe: Optional[Callable[[Any], float]] = None,
             round_hook=None):
    """The reference app loop, generic over the trainer backend: any object
    with init_state/place/train_round/evaluate + n_devices (ParallelTrainer
    for the layer IR, GraphTrainer for serialized graphs — the same way
    CaffeSolver and TensorFlowNet sat behind one loop in the reference).

    Multi-host: `train_ds`/`test_ds` are this HOST's shards (apps key them
    on jax.process_index/process_count); the sampler draws windows for the
    locally-addressable devices only, and checkpointing allgathers the
    worker-local state so process 0 writes the global checkpoint (resume
    expects checkpoint_dir on a filesystem all hosts can read). Eval is a
    collective: all hosts must agree on test_ds presence and SIZE
    (ArrayDataset.host_shard splits are exactly equal; uneven sources must
    reconcile first — see imagenet_app._agree_eval_dataset).

    `train_ds` may instead be any round SOURCE — an object with
    `next_round(round_index=...)` (e.g. `data.streaming.StreamingRoundSource`
    for corpora larger than host RAM); sampling/decoding then happens in the
    source's own pipeline. Either way, host-side round preparation (sampling
    + `batch_transform` preprocessing) for round R+1 is overlapped with
    round R's device compute via a one-deep prefetch thread — the reference
    prepared batches inline on each executor and stalled the GPU every
    round."""
    n_dev = trainer.n_devices
    n_local = getattr(trainer, "n_local_devices", n_dev)
    if hasattr(train_ds, "next_round"):
        source = train_ds
        log.log(f"train source: streaming ({n_dev} devices / {n_local} "
                f"local)" + (f"; test examples: {len(test_ds)}"
                             if test_ds else ""))
    else:
        source = RoundSampler(train_ds, n_local, cfg.local_batch, cfg.tau,
                              seed=cfg.seed)
        log.log(f"train examples: {len(train_ds)} on this host "
                f"({len(train_ds) // n_local} per worker; "
                f"{n_dev} devices / {n_local} local)"
                + (f"; test examples: {len(test_ds)}" if test_ds else ""))

    state = trainer.init_state(jax.random.PRNGKey(cfg.seed))
    start_round = 0
    if cfg.checkpoint_dir and cfg.resume:
        last = ckpt.latest_step(cfg.checkpoint_dir)
        if last is not None:
            flat, start_round, extra = ckpt.restore_flat(cfg.checkpoint_dir)
            tp_now = getattr(trainer, "tp", 1)
            # the elastic path is keyed on the SAVED topology, never on a
            # shape error: an architecture change on the same topology must
            # fail loudly through unflatten_like, not be silently adapted.
            # Pre-topology-metadata checkpoints carry no n_devices/tp keys;
            # infer the saved device count from the leading replica axis of
            # the 'it' counter (every state layout tiles it [n_devices])
            # instead of assuming same-topology and dying in unflatten_like.
            saved_dev = extra.get("n_devices")
            if saved_dev is None and "it" in flat:
                it_arr = np.asarray(flat["it"])
                if it_arr.ndim:
                    saved_dev = it_arr.shape[0]
            same_topo = (
                int(saved_dev or trainer.n_devices) == trainer.n_devices
                and int(extra.get("tp", tp_now)) == tp_now)
            if same_topo:
                state = trainer.place(ckpt.unflatten_like(state, flat))
                log.log(f"resumed from checkpoint round {start_round}")
            else:
                if not hasattr(trainer, "adapt_state"):
                    raise ValueError(
                        f"checkpoint topology {extra} != current "
                        f"({trainer.n_devices} devices, tp={tp_now}) and "
                        f"this trainer cannot adapt — resume on the "
                        f"original topology")
                # ELASTIC resume: params re-tiled exactly, momentum
                # averaged (ParallelTrainer.adapt_state)
                state = trainer.adapt_state(flat,
                                            old_tp=int(extra.get("tp", 1)))
                log.log(f"ELASTIC resume from round {start_round}: "
                        f"{extra.get('n_devices', '?')} devices (tp="
                        f"{extra.get('tp', 1)}) -> {trainer.n_devices} "
                        f"(tp={tp_now})")
            _seek_stream(source, extra, log)

    timers = PhaseTimers()
    meter = ThroughputMeter(n_chips=n_dev)
    # round-keyed rngs: resume at round R reproduces the uninterrupted
    # schedule exactly (reference had no resume at all, SURVEY §5.3)
    base_rng = jax.random.PRNGKey(cfg.seed ^ 0xABCD)

    # capture on the MAIN thread: the precision policy is thread-local and
    # the prefetch thread would otherwise see the default
    compute_dt = precision.compute_dtype()

    def prepare_round(rnd: int) -> Dict[str, np.ndarray]:
        return prepare_round_batches(source, rnd, cfg.tau, cfg.seed,
                                     batch_transform, compute_dt)

    def flush_round_log(rec) -> None:
        """Emit round R's metrics. `float(loss)` here is the pipeline's
        REAL synchronization — deferred one round so round R+1's dispatch
        overlaps round R's device execution (the reference fetched loss
        synchronously every round and stalled the accelerator; on a TPU the
        dispatch+fetch round trip is a large fraction of a round)."""
        rnd_, loss_, probe_ = rec
        loss_ = float(loss_)
        probe_txt = (f"  probe: {float(probe_):.6f}"
                     if probe_ is not None else "")
        log.log(f"round loss: {loss_:.4f}{probe_txt}", rnd_)
        log.metrics(rnd_, loss=loss_, images_per_sec_per_chip=round(
            meter.images_per_sec_per_chip(), 2))

    # one-deep host prefetch: round R+1 is sampled/decoded/preprocessed on
    # this thread pool while round R's XLA program runs. The "sample" phase
    # then measures only the residual WAIT — ~0 when prep fully overlaps.
    prefetch = ThreadPoolExecutor(1, thread_name_prefix="round-prep")
    pending: Optional[Any] = None
    # pending (rnd, device_loss, device_probe) records, flushed (= the
    # loop's host sync) every cfg.log_every rounds — holding device
    # scalars is free; fetching one costs a full round trip
    deferred: list = []

    def flush_deferred() -> None:
        while deferred:
            flush_round_log(deferred.pop(0))

    log_every = max(1, cfg.log_every)
    try:
        for rnd in range(start_round, cfg.max_rounds):
            if test_ds is not None and cfg.eval_every and \
                    rnd % cfg.eval_every == 0:
                # keep log/JSONL round-ordered: earlier loss rows must
                # precede round R's eval row (eval blocks on the in-flight
                # round anyway, so this costs no overlap)
                flush_deferred()
                with timers.phase("eval"):
                    acc = _evaluate(trainer, state, test_ds, cfg.eval_batch,
                                    n_local, transform=eval_transform)
                log.log(f"test accuracy: {acc:.4f}", rnd)
                log.metrics(rnd, test_accuracy=acc)

            with timers.phase("sample"):
                batches = (pending.result() if pending is not None
                           else prepare_round(rnd))
            if rnd + 1 < cfg.max_rounds:
                pending = prefetch.submit(prepare_round, rnd + 1)
            sub = jax.random.fold_in(base_rng, rnd)
            before = timers.total.get("train_round", 0.0)
            # trace ONE steady-state round (the first would trace compile)
            profile_this = cfg.profile_dir and rnd == start_round + 1
            with profiling.maybe_trace(cfg.profile_dir if profile_this
                                       else None):
                with timers.phase("train_round"):
                    state, loss = trainer.train_round(state, batches, sub)
                    # async probe slice MUST precede the next dispatch
                    # (donation invalidates the old state buffers)
                    probe_val = probe(state) if probe else None
                    if len(deferred) >= log_every:
                        flush_deferred()  # sync on rounds <= rnd-1
            if profile_this:
                log.log(f"profiler trace written to {cfg.profile_dir}", rnd)
            # steady state (log_every=1), this measures one device round:
            # dispatch of rnd + wait for rnd-1 (overlap of exactly one
            # round); with log_every=K the sync cost amortizes over K
            round_dt = timers.total["train_round"] - before
            n_images = cfg.tau * cfg.local_batch * n_dev
            meter.add(n_images, round_dt)
            deferred.append((rnd, loss, probe_val))

            if cfg.checkpoint_dir and cfg.checkpoint_every and \
                    (rnd + 1) % cfg.checkpoint_every == 0:
                flush_deferred()  # keep log rows round-ordered; the
                with timers.phase("checkpoint"):  # save syncs anyway
                    _save_checkpoint(cfg, trainer, state, rnd + 1,
                                     source=source, last_round=rnd)
                log.log("checkpoint saved", rnd)
            if round_hook:
                round_hook(rnd, state)
        flush_deferred()
    finally:
        if deferred:  # loop aborted: drain the pending fetches
            try:
                flush_deferred()
            except Exception:
                pass
        if pending is not None:
            pending.cancel()
        prefetch.shutdown(wait=False, cancel_futures=True)
        if hasattr(source, "close"):
            source.close()

    if cfg.checkpoint_dir and start_round < cfg.max_rounds:
        # start_round >= max_rounds means the loop ran ZERO rounds (a
        # relaunch of a completed run): the restored checkpoint is already
        # the final state, and re-saving would overwrite it with no stream
        # cursor (cursor_at has seen no rounds), destroying the resume
        # position a later extended run needs
        _save_checkpoint(cfg, trainer, state, cfg.max_rounds, retain=False,
                         source=source, last_round=cfg.max_rounds - 1)
    log.log(f"done; phase means: {timers.summary()}")
    return state


def _stream_rows(source, last_round: Optional[int]) -> Optional[list]:
    """Per-host stream cursors after `last_round`, allgathered so process
    0's checkpoint covers every host's stream position: one entry per host,
    each a [[shard, entry, epochs], ...] list with one row PER READER
    (ParallelStreamingSource runs N concurrent readers per host; a single
    StreamingRoundSource is the N=1 case). None when the source is not
    seekable or the cursor is no longer retained. Collective when
    multi-host — every process calls _save_checkpoint already."""
    if last_round is None or not hasattr(source, "cursor_at"):
        return None
    cur = source.cursor_at(last_round)
    if cur is None:
        return None
    if not isinstance(cur, list):  # single-reader source
        cur = [cur]
    rows = np.asarray([[s, e, ep] for (s, e), ep in cur], np.int64)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        rows = np.asarray(multihost_utils.process_allgather(rows))
    else:
        rows = rows[None]
    return rows.tolist()


def _seek_stream(source, extra: Dict[str, Any], log: Logger) -> None:
    """Resume the stream position recorded in the checkpoint (per host, one
    cursor row per reader). Host-count OR reader-count changes restart the
    stream from shard 0 — the shard assignment itself changed, so old
    cursors are meaningless. Accepts the pre-r4 flat [shard, entry,
    epochs]-per-host format as a 1-reader cursor."""
    rows = extra.get("stream")
    if rows is None:
        return
    if len(rows) != jax.process_count():
        log.log(f"stream cursor in checkpoint covers {len(rows)} hosts, "
                f"now {jax.process_count()}: restarting stream at shard 0")
        return
    host_rows = rows[jax.process_index()]
    if host_rows and not isinstance(host_rows[0], list):
        host_rows = [host_rows]  # legacy flat single-reader row
    if hasattr(source, "seek_rows"):
        if not source.seek_rows(host_rows):
            log.log(f"stream cursor in checkpoint covers {len(host_rows)} "
                    f"readers, source has a different count: restarting "
                    f"stream at shard 0")
            return
    elif hasattr(source, "seek") and len(host_rows) == 1:
        shard, entry, epochs = host_rows[0]
        source.seek((shard, entry), epochs)
    else:
        return
    pos = ", ".join(f"shard {s} entry {e} (epoch {ep})"
                    for s, e, ep in host_rows)
    log.log(f"stream resumed at {pos}")


def _save_checkpoint(cfg: RunConfig, trainer, state, step: int,
                     retain: bool = True, source=None,
                     last_round: Optional[int] = None) -> None:
    """Allgather (a collective — every host must call this) then write from
    process 0 only. Momentum is worker-local, so the gather is substantive,
    not a replica read. The saved topology (device count, tp) lets a
    differently-sized job resume elastically; streaming sources also
    record their per-host stream cursor so resume seeks instead of
    re-streaming from shard 0."""
    host_state = fetch_global(state)
    stream = _stream_rows(source, last_round) if source is not None else None
    if jax.process_index() == 0:
        extra = {"n_devices": trainer.n_devices,
                 "tp": getattr(trainer, "tp", 1)}
        if stream is not None:
            extra["stream"] = stream
        ckpt.save(cfg.checkpoint_dir, host_state, step=step, extra=extra)
        if retain:
            ckpt.retain(cfg.checkpoint_dir, keep=3)


def _to_device_layout(ds: ArrayDataset, net: CompiledNet) -> ArrayDataset:
    """One-time NCHW -> NHWC conversion for 4D inputs that arrive in the
    reference's Caffe layout (same disambiguation as JaxNet input_layout
    'auto')."""
    arrays = dict(ds.arrays)
    for name, want in net.input_shapes.items():
        arr = arrays.get(name)
        if arr is None or arr.ndim != 4:
            continue
        want_el = tuple(want[1:])
        if tuple(arr.shape[1:]) != want_el and \
                (arr.shape[2], arr.shape[3], arr.shape[1]) == want_el:
            arrays[name] = np.ascontiguousarray(
                np.transpose(arr, (0, 2, 3, 1)))
    return ArrayDataset(arrays)


def _evaluate(trainer, state, test_ds: ArrayDataset, eval_batch: int,
              n_dev: int, transform=None) -> float:
    """Distributed eval (reference `CifarApp.scala:107-124`), covering every
    example except at most n_dev-1 trailing ones (batches must split evenly
    across devices): the tail past the last full eval_batch is evaluated as
    one smaller batch (a second compiled shape, amortized across rounds) and
    weighted by its real size.

    `transform` preprocesses each eval batch lazily (train=False — e.g.
    center crop + mean subtract on raw uint8 pixels), so only one batch of
    float32 pixels ever exists at a time — the whole-split float32
    materialization would be ~6x the uint8 corpus."""
    eval_batch = min(eval_batch, len(test_ds))
    eval_batch = max(n_dev, (eval_batch // n_dev) * n_dev)
    if len(test_ds) < eval_batch:
        raise ValueError(
            f"test set ({len(test_ds)}) smaller than {n_dev} devices' "
            f"minimum eval batch")

    def run(lo: int, n: int) -> float:
        batch = {k: v[lo:lo + n] for k, v in test_ds.arrays.items()}
        if transform is not None:
            batch = transform.convert_batch(batch, train=False)
        return trainer.evaluate(state, batch) * n

    total, count = 0.0, 0
    n_full = (len(test_ds) // eval_batch) * eval_batch
    for i in range(0, n_full, eval_batch):
        total += run(i, eval_batch)
        count += eval_batch
    tail = ((len(test_ds) - n_full) // n_dev) * n_dev
    if tail:
        total += run(n_full, tail)
        count += tail
    return total / max(count, 1)
