"""ImageNet training on the SERIALIZED-GRAPH backend — the reference's
`apps/TFImageNetApp.scala`: an AlexNet graph with in-graph Momentum
optimizer trained inside the distributed τ-averaging loop (batch 256, τ=10
at TFImageNetApp.scala:119, eval every 10), fed by the sharded-tar ImageNet
ingest with mean-subtract + random-crop + CHW->HWC preprocessing
(the reference's ImageNetTensorFlowPreprocessor, Preprocessor.scala:150-178).

The graph can be:
  - (default) our portable generator `build_alexnet_graph()` — the analogue
    of the reference generating `alexnet_graph.pb` with `alexnet_graph.py`;
  - `--graph path.pb` — a frozen TF GraphDef (e.g. the reference's own
    `models/tensorflow/alexnet/alexnet_graph.pb`), trained through its
    imported in-graph optimizer;
  - `--graph path.json` — a portable GraphDef JSON produced elsewhere.

Corpus modes (cache vs stream) and multi-host sharding are shared with
`imagenet_app` — same --stream/--ram-budget-mb/--val-limit knobs.
"""
from __future__ import annotations

import argparse
import functools

from ..backend import build_alexnet_graph
from ..parallel import initialize_multihost
from ..utils.config import RunConfig
from .graph_common import load_graph, train_graph
from .imagenet_app import add_data_args, prepare_data


def default_config() -> RunConfig:
    return RunConfig(model="graph:alexnet", n_classes=1000,
                     data_dir="data/imagenet", crop=227, tau=10,
                     local_batch=256, eval_every=10, max_rounds=1000)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--graph", default=None,
                   help=".pb (TF GraphDef) or .json (portable) graph file")
    add_data_args(p)
    args = p.parse_args(argv)
    initialize_multihost()  # BEFORE any other JAX use
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)
    # label_shape=() -> (B,) flat int labels (the TF-graph convention; the
    # Caffe path uses (1,) -> (B,1))
    train_raw, test_ds, pp_train, pp_eval = prepare_data(
        cfg, args, label_shape=(), app_name="graph_imagenet_app")

    graph = load_graph(args.graph, functools.partial(
        build_alexnet_graph, batch=cfg.local_batch, n_classes=cfg.n_classes))
    crop = cfg.crop or 227
    train_graph(cfg, graph, train_raw, test_ds, batch_transform=pp_train,
                eval_transform=pp_eval, expect_data_shape=(crop, crop, 3))


if __name__ == "__main__":
    main()
