"""ImageNet training app — reference `apps/ImageNetApp.scala` equivalent.

Reference defaults preserved: batch 256, τ=5, eval every 10 rounds, 256×256
input with 227×227 random crop + mean-image subtraction, CaffeNet solver
lr 0.01 step(0.1 @100k) / momentum 0.9 / wd 0.0005
(`ImageNetApp.scala:24-30,127,107`; `models/bvlc_reference_caffenet/
solver.prototxt`).

Ingest: sharded-tar loader (host-sharded), native C++ JPEG plane when built.
Mean image is computed over the decoded corpus (the reference did a
full-image RDD reduce, `ImageNetApp.scala:66-69`).

Two corpus modes, chosen by `--stream {auto,always,never}` against
`--ram-budget-mb`:
  - cached: decode this host's shards once into RAM; rounds draw random
    windows (reference `repartition().cache()` semantics). Fast resample,
    RAM-bounded.
  - streaming: never materialize — a background thread decodes the shard
    stream round-by-round (`data.streaming.StreamingRoundSource`), the
    reference's actual ImageNet data motion (one-partition-per-tar,
    `loaders/ImageNetLoader.scala:59-91`); host RAM holds ~3 rounds of
    pixels regardless of corpus size, and decode overlaps device compute.
"""
from __future__ import annotations

import argparse
import sys
from typing import Tuple

import numpy as np

from ..data import imagenet
from ..data.dataset import ArrayDataset
from ..data.preprocess import ImagePreprocessor, compute_mean_image
from ..data.streaming import (StreamingRoundSource, make_parallel_source,
                              streaming_sum_count)
from ..parallel import initialize_multihost
from ..parallel.mesh import host_id_count
from ..schema import Field, Schema
from ..solver import SolverConfig
from ..utils.config import RunConfig
from .train_loop import train


def default_config() -> RunConfig:
    return RunConfig(
        model="caffenet", n_classes=1000,
        solver=SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=0.0005,
                            lr_policy="step", gamma=0.1, stepsize=100000,
                            max_iter=450000),
        data_dir="data/imagenet", crop=227, tau=5, local_batch=256,
        eval_every=10, max_rounds=1000, precision="bfloat16")


def host_loader(cfg: RunConfig, split_prefix: str, label_file: str,
                host_id: int = 0, host_count: int = 1
                ) -> imagenet.ShardedTarLoader:
    shards = imagenet.host_shards(
        imagenet.list_shards(cfg.data_dir, prefix=split_prefix),
        host_id, host_count)
    labels = imagenet.load_label_map(f"{cfg.data_dir}/{label_file}")
    return imagenet.ShardedTarLoader(shards, labels, height=256, width=256)


def load_corpus(cfg: RunConfig, split_prefix: str, label_file: str,
                host_id: int = 0, host_count: int = 1,
                limit: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    return host_loader(cfg, split_prefix, label_file,
                       host_id, host_count).load_all(limit or None)


def _should_stream(mode: str, n_host_images: float, budget_mb: int,
                   height: int = 256, width: int = 256) -> bool:
    """auto: estimate this host's decoded-corpus peak RAM. Force-resize
    makes every decoded image exactly height*width*3 bytes regardless of
    its JPEG size, and load_all's list-then-stack doubles the peak, so the
    estimate is (images on THIS host) * bytes/image * 2."""
    if mode in ("always", "never"):
        return mode == "always"
    decoded = n_host_images * (height * width * 3) * 2
    return decoded > budget_mb * (1 << 20)


def _host_image_estimate(loader, cfg: RunConfig, prefix: str,
                         pc: int) -> float:
    """This host's share of the labeled images, weighted by its assigned
    shards' BYTE share rather than 1/host_count: i::k shard assignment can
    be uneven, and the label map counts images that may live in other
    hosts' tars (r2 review). Byte share is a far better proxy for image
    count than count/pc — within one corpus, JPEG size variation averages
    out across whole shards."""
    n_total = len(loader.label_map)
    if pc == 1:
        return float(n_total)
    try:
        all_bytes = sum(imagenet.path_size(p) for p in
                        imagenet.list_shards(cfg.data_dir, prefix=prefix))
        mine = sum(imagenet.path_size(p) for p in loader.shard_paths)
    except OSError:
        return n_total / pc
    if all_bytes <= 0:
        return n_total / pc
    return n_total * (mine / all_bytes)


def _corpus_id(cfg: RunConfig, prefix: str, train_loader, pc: int) -> str:
    """Identity of the train corpus the mean sidecar was computed from:
    label count + the GLOBAL shard listing (name:size per shard — every
    host lists the same data_dir, so the id agrees across processes even
    though each host decodes only its own shards). Single-process only, a
    loader built outside the data_dir convention (tests) may fall back to
    its own shard paths; multi-host the listing must succeed — a per-host
    fallback would hash each host's i::k subset, hosts would disagree on
    the id, and a partial sidecar match would strand the others in
    _combine_mean's collective."""
    import hashlib
    import os

    from ..data import imagenet
    try:
        shards = imagenet.list_shards(cfg.data_dir, prefix=prefix)
    except OSError:
        if pc > 1:
            raise
        shards = train_loader.shard_paths
    sig = ";".join(
        f"{os.path.basename(p)}:{imagenet.path_size(p)}" for p in shards)
    return hashlib.sha1(
        f"{len(train_loader.label_map)}|{sig}".encode()).hexdigest()


def _load_or_compute_mean(cfg: RunConfig, train_loader, pi: int, pc: int,
                          app_name: str, prefix: str = "train.") -> np.ndarray:
    """The streamed-corpus global mean image, persisted as a sidecar next to
    the checkpoints: the mean is a property of the dataset, so re-deriving
    it on every launch cost a full extra decode pass over the corpus
    (flagged in the r2 review). First launch computes + writes
    (atomically, process 0); every later launch — including resume —
    loads. The sidecar records the corpus identity (shard names/sizes +
    label count): re-sharding or extending the corpus under the same
    checkpoint_dir recomputes loudly instead of silently mean-subtracting
    another dataset's statistics. No checkpoint_dir -> no persistence."""
    import os

    side = (os.path.join(cfg.checkpoint_dir, "mean_image.npz")
            if cfg.checkpoint_dir else None)
    corpus = _corpus_id(cfg, prefix, train_loader, pc)
    if side and os.path.exists(side):
        with np.load(side) as z:
            saved = str(z["corpus_id"]) if "corpus_id" in z else None
            mean = z["mean"]
        if saved == corpus:
            print(f"{app_name}: mean image loaded from {side} "
                  f"(skipping the corpus pass)", file=sys.stderr)
            return mean.astype(np.float32)
        print(f"{app_name}: {side} was computed from a DIFFERENT corpus "
              f"(saved id {saved} != {corpus}) — recomputing the mean",
              file=sys.stderr)
    elif side:
        legacy = os.path.join(cfg.checkpoint_dir, "mean_image.npy")
        if os.path.exists(legacy):
            # un-id'd sidecar from before the corpus stamp: migrate rather
            # than silently repaying the full-corpus decode pass. Stamping
            # with the CURRENT id matches the legacy trust level (it had
            # no staleness check at all).
            mean = np.load(legacy).astype(np.float32)
            if pi == 0:
                tmp = side + ".tmp"
                with open(tmp, "wb") as f:
                    np.savez(f, mean=mean, corpus_id=np.array(corpus))
                os.replace(tmp, side)
            print(f"{app_name}: migrated legacy sidecar {legacy} -> {side}",
                  file=sys.stderr)
            return mean
    # one streaming pass for the global mean reduce; never holds more
    # than one decoded image + a float64 accumulator per worker thread.
    # Fanned out over the host's shards like the training ingest — the
    # serial pass decoded the whole corpus at one reader's rate
    workers = max(cfg.ingest_sources, min(8, os.cpu_count() or 1))
    s, n = streaming_sum_count(train_loader, workers=workers)
    mean = _combine_mean(s, float(n), pc)
    if side and pi == 0:
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        tmp = side + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, mean=mean, corpus_id=np.array(corpus))
        os.replace(tmp, side)
    return mean


def _combine_mean(local_sum: np.ndarray, local_count: float,
                  host_count: int) -> np.ndarray:
    """Global mean image from per-host (sum, count). The reference reduced
    full images across the whole RDD (`ImageNetApp.scala:66-69`); per-host
    means would silently diverge the preprocessing, so hosts combine the
    weighted sums."""
    if host_count == 1:
        return (local_sum / local_count).astype(np.float32)
    from jax.experimental import multihost_utils
    local = np.stack([local_sum,
                      np.full(local_sum.shape, float(local_count))])
    gathered = multihost_utils.process_allgather(local)  # [pc, 2, ...]
    total, count = gathered[:, 0].sum(axis=0), gathered[:, 1].sum(axis=0)
    return (total / count).astype(np.float32)


def _global_mean_image(images: np.ndarray, host_count: int) -> np.ndarray:
    if host_count == 1:
        return compute_mean_image(images)
    return _combine_mean(images.sum(axis=0, dtype=np.float64),
                         float(len(images)), host_count)


def _agree_eval_dataset(test_ds, host_count: int):
    """Make every host agree on the eval workload. trainer.evaluate is a
    COLLECTIVE: if hosts hold different val sizes (uneven tar shards), they
    would run different numbers of eval calls and deadlock the pod. Truncate
    all hosts to the global minimum size; if any host has nothing, eval is
    disabled everywhere."""
    if host_count == 1:
        return test_ds
    from jax.experimental import multihost_utils
    sizes = multihost_utils.process_allgather(
        np.asarray(len(test_ds) if test_ds is not None else 0))
    m = int(np.min(sizes))
    if m == 0:
        return None
    return ArrayDataset({k: v[:m] for k, v in test_ds.arrays.items()})


def add_data_args(p: argparse.ArgumentParser) -> None:
    """The ImageNet corpus CLI surface, shared with graph_imagenet_app."""
    p.add_argument("--config", help="RunConfig JSON path")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--train-prefix", default="train.")
    p.add_argument("--val-prefix", default="val.")
    p.add_argument("--train-labels", default="train.txt")
    p.add_argument("--val-labels", default="val.txt")
    p.add_argument("--stream", choices=("auto", "always", "never"),
                   default="auto", help="corpus mode: stream shards vs "
                   "cache decoded pixels in RAM (auto: by --ram-budget-mb)")
    p.add_argument("--ram-budget-mb", type=int, default=8192,
                   help="decoded-corpus RAM budget per host for --stream=auto")
    p.add_argument("--val-limit", type=int, default=0,
                   help="cap resident val examples per host (0 = all); the "
                   "val split is held as uint8, ~192 KiB per image")
    p.add_argument("overrides", nargs="*")


def prepare_data(cfg: RunConfig, args, label_shape: Tuple[int, ...] = (1,),
                 app_name: str = "imagenet_app"):
    """Everything between the parsed CLI and the training loop, shared by
    the layer-IR and serialized-graph ImageNet apps: host-sharded loaders
    (shards i::k to host i of k — the reference's one-Spark-partition-per-
    tar, keyed by process index), the cache-vs-stream decision, the global
    mean reduce, preprocessors, the train source, and the val dataset.

    label_shape: per-example label field shape — (1,) for the Caffe path
    ((B,1) batches), () for TF-convention graphs ((B,) flat labels).
    Returns (train_source, test_ds, pp_train, pp_eval).
    """
    pi, pc = host_id_count()
    train_loader = host_loader(cfg, args.train_prefix, args.train_labels,
                               host_id=pi, host_count=pc)
    streaming = _should_stream(
        args.stream,
        _host_image_estimate(train_loader, cfg, args.train_prefix, pc),
        args.ram_budget_mb)
    if streaming:
        images = labels = None
        mean = (_load_or_compute_mean(cfg, train_loader, pi, pc, app_name,
                                      prefix=args.train_prefix)
                if cfg.subtract_mean else None)
        print(f"{app_name}: streaming corpus on host {pi} "
              f"({len(train_loader.shard_paths)} shards)", file=sys.stderr)
    else:
        images, labels = train_loader.load_all()
        mean = _global_mean_image(images, pc) if cfg.subtract_mean else None
    crop = cfg.crop or 227
    # schema describes the preprocessor OUTPUT: NHWC device layout
    schema = Schema(Field("data", "float32", (crop, crop, 3)),
                    Field("label", "int32", label_shape))
    # emit the compute dtype straight from the native plane's OpenMP loop:
    # the loop-side cast then no-ops (cast_host_inputs skips non-f32)
    out_dt = "bfloat16" if cfg.precision == "bfloat16" else "float32"
    pp_train = ImagePreprocessor(schema, mean_image=mean, crop=crop,
                                 seed=cfg.seed, out_dtype=out_dt)
    pp_eval = ImagePreprocessor(schema, mean_image=mean, crop=crop,
                                seed=cfg.seed, out_dtype=out_dt)

    # Preprocessing happens per-round on the sampled window (crop is
    # per-epoch random): the loop's prefetch thread applies pp_train to each
    # round while the previous round trains. Streaming mode swaps the RAM
    # dataset for the background-decode source; the loop is identical.
    if streaming:
        import jax
        n_local = (jax.local_device_count() if cfg.n_devices is None
                   else max(1, cfg.n_devices // pc))
        if cfg.ingest_sources > 1:
            # N concurrent readers over this host's shards j::N — the
            # reference's task-per-tar parallel decode
            # (`loaders/ImageNetLoader.scala:28-41`), per host. The
            # effective count is agreed GLOBALLY (min shards any host
            # holds, floor(total/pc)): hosts with uneven i::k splits must
            # not end up with different reader counts, or the checkpoint's
            # cursor allgather receives ragged arrays and the collective
            # dies mid-run.
            total = len(imagenet.list_shards(cfg.data_dir,
                                             prefix=args.train_prefix))
            eff = max(1, min(cfg.ingest_sources, total // pc))
            # ParallelStreamingSource requires n_sources | round_examples;
            # the clamp above can land on a non-divisor (e.g. 112 shards /
            # 16 hosts -> eff=7 vs round 5120) even when the operator's
            # request was valid. Round DOWN to the nearest divisor (1 is
            # always reachable) instead of aborting on a computed value.
            round_examples = n_local * cfg.local_batch * cfg.tau
            while round_examples % eff:
                eff -= 1
            if eff != cfg.ingest_sources:
                print(f"{app_name}: ingest_sources reduced "
                      f"{cfg.ingest_sources} -> {eff} "
                      f"(shards={total}, hosts={pc}, "
                      f"round={round_examples})", file=sys.stderr)
            train_raw = make_parallel_source(
                train_loader.shard_paths, train_loader.label_map,
                n_local, cfg.local_batch, cfg.tau, eff,
                height=256, width=256)
            print(f"{app_name}: {train_raw.n_sources} parallel shard "
                  f"readers", file=sys.stderr)
        else:
            # the loader re-opens its tars on each iteration, so the mean
            # pass and the training stream share it (+ skipped counter)
            train_raw = StreamingRoundSource(train_loader, n_local,
                                             cfg.local_batch, cfg.tau)
    else:
        train_raw = ArrayDataset({"data": images, "label": labels[:, None]})
    try:
        # --val-limit caps DECODING, not just the slice: a post-hoc [:n]
        # view would pin the fully decoded split in RAM
        val_images, val_labels = load_corpus(cfg, args.val_prefix,
                                             args.val_labels,
                                             host_id=pi, host_count=pc,
                                             limit=args.val_limit)
        # RAW uint8 — pp_eval runs per eval batch inside the loop, so the
        # resident val cost is bounded by the uint8 pixels (the float32
        # conversion of the whole split would be ~6x larger)
        test_ds = ArrayDataset({"data": val_images,
                                "label": val_labels[:, None]})
    except (FileNotFoundError, ValueError) as e:
        # no val split — or fewer val tars than hosts left THIS host empty.
        # Say WHY: a malformed val.txt also lands here and must not look
        # like "no val data" on a multi-day run.
        print(f"{app_name}: eval disabled on host {pi}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        test_ds = None
    test_ds = _agree_eval_dataset(test_ds, pc)
    return train_raw, test_ds, pp_train, pp_eval


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    add_data_args(p)
    args = p.parse_args(argv)
    initialize_multihost()  # BEFORE any other JAX use (mesh.py:49)
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)
    train_raw, test_ds, pp_train, pp_eval = prepare_data(cfg, args)

    from .train_loop import resolve_spec
    crop = cfg.crop = cfg.crop or 227
    spec = resolve_spec(cfg, data=(cfg.local_batch, 3, crop, crop),
                        label=(cfg.local_batch, 1))
    train(cfg, spec, train_raw, test_ds, batch_transform=pp_train,
          eval_transform=pp_eval)


if __name__ == "__main__":
    main()
