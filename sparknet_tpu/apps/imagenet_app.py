"""ImageNet training app — reference `apps/ImageNetApp.scala` equivalent.

Reference defaults preserved: batch 256, τ=5, eval every 10 rounds, 256×256
input with 227×227 random crop + mean-image subtraction, CaffeNet solver
lr 0.01 step(0.1 @100k) / momentum 0.9 / wd 0.0005
(`ImageNetApp.scala:24-30,127,107`; `models/bvlc_reference_caffenet/
solver.prototxt`).

Ingest: sharded-tar loader (host-sharded), native C++ JPEG plane when built.
Mean image is computed over the decoded corpus (the reference did a
full-image RDD reduce, `ImageNetApp.scala:66-69`). The decoded uint8 corpus
is cached in host RAM and rounds sample windows from it — suitable up to
RAM-sized subsets; a streaming re-decode path for full-ImageNet-on-one-host
is future work (at pod scale, per-host shard assignment keeps each host's
slice RAM-sized).
"""
from __future__ import annotations

import argparse
import sys
from typing import Tuple

import numpy as np

from ..data import imagenet
from ..data.dataset import ArrayDataset
from ..data.preprocess import ImagePreprocessor, compute_mean_image
from ..parallel import initialize_multihost
from ..parallel.mesh import host_id_count
from ..schema import Field, Schema
from ..solver import SolverConfig
from ..utils.config import RunConfig
from .train_loop import train


def default_config() -> RunConfig:
    return RunConfig(
        model="caffenet", n_classes=1000,
        solver=SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=0.0005,
                            lr_policy="step", gamma=0.1, stepsize=100000,
                            max_iter=450000),
        data_dir="data/imagenet", crop=227, tau=5, local_batch=256,
        eval_every=10, max_rounds=1000, precision="bfloat16")


def load_corpus(cfg: RunConfig, split_prefix: str, label_file: str,
                host_id: int = 0, host_count: int = 1
                ) -> Tuple[np.ndarray, np.ndarray]:
    shards = imagenet.host_shards(
        imagenet.list_shards(cfg.data_dir, prefix=split_prefix),
        host_id, host_count)
    labels = imagenet.load_label_map(f"{cfg.data_dir}/{label_file}")
    loader = imagenet.ShardedTarLoader(shards, labels, height=256, width=256)
    return loader.load_all()


def _global_mean_image(images: np.ndarray, host_count: int) -> np.ndarray:
    """Mean image over the GLOBAL train set. The reference reduced full
    images across the whole RDD (`ImageNetApp.scala:66-69`); with host-
    sharded corpora each host contributes its (sum, count) and the weighted
    mean is identical on every host — per-host means would silently diverge
    the preprocessing."""
    if host_count == 1:
        return compute_mean_image(images)
    from jax.experimental import multihost_utils
    local = np.stack([images.sum(axis=0, dtype=np.float64),
                      np.full(images.shape[1:], float(len(images)))])
    gathered = multihost_utils.process_allgather(local)  # [pc, 2, ...]
    total, count = gathered[:, 0].sum(axis=0), gathered[:, 1].sum(axis=0)
    return (total / count).astype(np.float32)


def _agree_eval_dataset(test_ds, host_count: int):
    """Make every host agree on the eval workload. trainer.evaluate is a
    COLLECTIVE: if hosts hold different val sizes (uneven tar shards), they
    would run different numbers of eval calls and deadlock the pod. Truncate
    all hosts to the global minimum size; if any host has nothing, eval is
    disabled everywhere."""
    if host_count == 1:
        return test_ds
    from jax.experimental import multihost_utils
    sizes = multihost_utils.process_allgather(
        np.asarray(len(test_ds) if test_ds is not None else 0))
    m = int(np.min(sizes))
    if m == 0:
        return None
    return ArrayDataset({k: v[:m] for k, v in test_ds.arrays.items()})


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="RunConfig JSON path")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--train-prefix", default="train.")
    p.add_argument("--val-prefix", default="val.")
    p.add_argument("--train-labels", default="train.txt")
    p.add_argument("--val-labels", default="val.txt")
    p.add_argument("overrides", nargs="*")
    args = p.parse_args(argv)
    initialize_multihost()  # BEFORE any other JAX use (mesh.py:49)
    cfg = (RunConfig.from_json(args.config) if args.config
           else default_config())
    if args.data_dir:
        cfg.data_dir = args.data_dir
    cfg = cfg.with_overrides(*args.overrides)

    # each host streams only ITS tar shards (shards i::k to host i of k —
    # the reference's one-Spark-partition-per-tar, keyed by process index)
    pi, pc = host_id_count()
    images, labels = load_corpus(cfg, args.train_prefix, args.train_labels,
                                 host_id=pi, host_count=pc)
    mean = _global_mean_image(images, pc) if cfg.subtract_mean else None
    crop = cfg.crop or 227
    # schema describes the preprocessor OUTPUT: NHWC device layout
    schema = Schema(Field("data", "float32", (crop, crop, 3)),
                    Field("label", "int32", (1,)))
    pp_train = ImagePreprocessor(schema, mean_image=mean, crop=crop,
                                 seed=cfg.seed)
    pp_eval = ImagePreprocessor(schema, mean_image=mean, crop=crop,
                                seed=cfg.seed)

    # Preprocessing happens per-round on the sampled window (crop is
    # per-epoch random); wrap the sampler output via a dataset of raw uint8
    # and a round_transform in the loop by pre-transforming eagerly here.
    train_raw = ArrayDataset({"data": images, "label": labels[:, None]})
    try:
        val_images, val_labels = load_corpus(cfg, args.val_prefix,
                                             args.val_labels,
                                             host_id=pi, host_count=pc)
        test_ds = ArrayDataset(pp_eval.convert_batch(
            {"data": val_images, "label": val_labels[:, None]}, train=False))
    except (FileNotFoundError, ValueError) as e:
        # no val split — or fewer val tars than hosts left THIS host empty.
        # Say WHY: a malformed val.txt also lands here and must not look
        # like "no val data" on a multi-day run.
        print(f"imagenet_app: eval disabled on host {pi}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        test_ds = None
    test_ds = _agree_eval_dataset(test_ds, pc)

    from .train_loop import resolve_spec
    cfg.crop = crop
    spec = resolve_spec(cfg, data=(cfg.local_batch, 3, crop, crop),
                        label=(cfg.local_batch, 1))
    train(cfg, spec, train_raw, test_ds, batch_transform=pp_train)


if __name__ == "__main__":
    main()
