"""Adult/Census tabular app (reference test-fixture workload:
`models/adult/adult.prototxt` + `LoadAdultDataSpec.scala`), extended to a
trainable 2-class MLP."""
from __future__ import annotations

import argparse

from ..data.adult import AdultLoader
from ..data.dataset import ArrayDataset
from ..parallel import initialize_multihost
from ..parallel.mesh import host_id_count
from ..model.spec import (Filler, InnerProductParam, InputSpec, LayerSpec,
                          NetSpec)
from ..solver import SolverConfig
from ..utils.config import RunConfig
from ..zoo import _heads, _ip, _relu
from .train_loop import train


def adult_net(batch: int, n_features: int) -> NetSpec:
    """adult.prototxt's MLP with a loss/accuracy head for training."""
    return NetSpec(
        name="adult",
        inputs=(InputSpec("C0", (batch, n_features)),
                InputSpec("label", (batch, 1), "int32")),
        layers=(
            _ip("ip", "C0", 10, filler=Filler(type="xavier")),
            _relu("relu", "ip"),
            _ip("ip2", "ip", 2, filler=Filler(type="xavier")),
        ) + _heads("ip2"),
    )


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data", required=True, help="adult.data CSV path")
    p.add_argument("overrides", nargs="*")
    args = p.parse_args(argv)
    initialize_multihost()  # BEFORE any other JAX use (mesh.py:49)
    cfg = RunConfig(
        model="adult",
        solver=SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="fixed"),
        tau=5, local_batch=64, eval_every=5, max_rounds=50,
    ).with_overrides(*args.overrides)
    loader = AdultLoader(args.data)
    full = loader.batch_dict()
    # held-out eval: last 20% (the reference's adult path had no eval at all)
    n = len(loader.labels)
    split = max(1, int(n * 0.8))
    train_ds = ArrayDataset({k: v[:split] for k, v in full.items()})
    test_ds = ArrayDataset({k: v[split:] for k, v in full.items()})
    pi, pc = host_id_count()
    train_ds, test_ds = train_ds.host_shard(pi, pc), test_ds.host_shard(pi, pc)
    n_features = loader.features.shape[1]
    train(cfg, adult_net(cfg.local_batch, n_features), train_ds, test_ds)


if __name__ == "__main__":
    main()
