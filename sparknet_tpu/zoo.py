"""Model zoo: programmatic NetSpec builders for the reference's model set.

Mirrors the architectures of the reference zoo (reference `models/`):
  - cifar10_quick  <- models/cifar10/cifar10_quick_train_test.prototxt
  - caffenet       <- models/bvlc_reference_caffenet/train_val.prototxt
                      (AlexNet variant: 5 conv + 2 LRN + 3 FC + dropout)
  - lenet          <- models/tensorflow/mnist/mnist_graph.py (LeNet-style)
  - adult_mlp      <- models/adult/adult.prototxt

Specs are built in code (the TPU-native "declarative model" is data either
way); the prototxt importer covers file-based definition parity.
"""
from __future__ import annotations

from typing import Optional, Tuple

from .model.spec import (AccuracyParam, ConvolutionParam, DropoutParam,
                         Filler, InnerProductParam, InputSpec, LayerSpec,
                         LRNParam, NetSpec, ParamSpec, PoolingParam)

_GAUSS = lambda std: Filler(type="gaussian", std=std)
_CONST = lambda v=0.0: Filler(type="constant", value=v)
_LRMULT = (ParamSpec(lr_mult=1.0), ParamSpec(lr_mult=2.0))
# AlexNet convention: bias lr_mult 2, bias decay 0
_LRMULT_WD = (ParamSpec(lr_mult=1.0, decay_mult=1.0),
              ParamSpec(lr_mult=2.0, decay_mult=0.0))


def _conv(name, bottom, n_out, k, *, stride=1, pad=0, group=1, std=0.01,
          bias=0.0, params=_LRMULT):
    return LayerSpec(
        name=name, type="Convolution", bottoms=(bottom,), tops=(name,),
        params=params,
        conv=ConvolutionParam(num_output=n_out, kernel_size=k, stride=stride,
                              pad=pad, group=group, weight_filler=_GAUSS(std),
                              bias_filler=_CONST(bias)))


def _relu(name, blob):
    return LayerSpec(name=name, type="ReLU", bottoms=(blob,), tops=(blob,))


def _pool(name, bottom, mode, k, stride):
    return LayerSpec(name=name, type="Pooling", bottoms=(bottom,), tops=(name,),
                     pool=PoolingParam(pool=mode, kernel_size=k, stride=stride))


def _lrn(name, bottom, *, local_size=5, alpha=1e-4, beta=0.75):
    return LayerSpec(name=name, type="LRN", bottoms=(bottom,), tops=(name,),
                     lrn=LRNParam(local_size=local_size, alpha=alpha, beta=beta))


def _ip(name, bottom, n_out, *, std=0.01, bias=0.0, filler=None,
        params=_LRMULT):
    return LayerSpec(
        name=name, type="InnerProduct", bottoms=(bottom,), tops=(name,),
        params=params,
        inner_product=InnerProductParam(
            num_output=n_out,
            weight_filler=filler or _GAUSS(std),
            bias_filler=_CONST(bias)))


def _dropout(name, blob, ratio=0.5):
    return LayerSpec(name=name, type="Dropout", bottoms=(blob,), tops=(blob,),
                     dropout=DropoutParam(dropout_ratio=ratio))


def _heads(logits_blob, label_blob="label"):
    return (
        LayerSpec(name="prob", type="Softmax", bottoms=(logits_blob,),
                  tops=("prob",)),
        LayerSpec(name="accuracy", type="Accuracy",
                  bottoms=(logits_blob, label_blob), tops=("accuracy",),
                  accuracy=AccuracyParam()),
        LayerSpec(name="loss", type="SoftmaxWithLoss",
                  bottoms=(logits_blob, label_blob), tops=("loss",)),
    )


def cifar10_quick(batch: int = 100) -> NetSpec:
    """3×(conv5x5 pad2 + pool3/2) + 2 FC, CIFAR-10."""
    return NetSpec(
        name="CIFAR10_quick",
        inputs=(InputSpec("data", (batch, 3, 32, 32)),
                InputSpec("label", (batch, 1), "int32")),
        layers=(
            _conv("conv1", "data", 32, 5, pad=2, std=0.0001),
            _pool("pool1", "conv1", "MAX", 3, 2),
            _relu("relu1", "pool1"),
            _conv("conv2", "pool1", 32, 5, pad=2, std=0.01),
            _relu("relu2", "conv2"),
            _pool("pool2", "conv2", "AVE", 3, 2),
            _conv("conv3", "pool2", 64, 5, pad=2, std=0.01),
            _relu("relu3", "conv3"),
            _pool("pool3", "conv3", "AVE", 3, 2),
            _ip("ip1", "pool3", 64, std=0.1),
            _ip("ip2", "ip1", 10, std=0.1),
        ) + _heads("ip2"),
    )


def caffenet(batch: int = 256, crop: int = 227,
             n_classes: int = 1000) -> NetSpec:
    """BVLC reference CaffeNet (AlexNet variant), the flagship model."""
    return NetSpec(
        name="CaffeNet",
        inputs=(InputSpec("data", (batch, 3, crop, crop)),
                InputSpec("label", (batch, 1), "int32")),
        layers=(
            _conv("conv1", "data", 96, 11, stride=4, std=0.01,
                  params=_LRMULT_WD),
            _relu("relu1", "conv1"),
            _pool("pool1", "conv1", "MAX", 3, 2),
            _lrn("norm1", "pool1"),
            _conv("conv2", "norm1", 256, 5, pad=2, group=2, std=0.01, bias=1.0,
                  params=_LRMULT_WD),
            _relu("relu2", "conv2"),
            _pool("pool2", "conv2", "MAX", 3, 2),
            _lrn("norm2", "pool2"),
            _conv("conv3", "norm2", 384, 3, pad=1, std=0.01,
                  params=_LRMULT_WD),
            _relu("relu3", "conv3"),
            _conv("conv4", "conv3", 384, 3, pad=1, group=2, std=0.01, bias=1.0,
                  params=_LRMULT_WD),
            _relu("relu4", "conv4"),
            _conv("conv5", "conv4", 256, 3, pad=1, group=2, std=0.01, bias=1.0,
                  params=_LRMULT_WD),
            _relu("relu5", "conv5"),
            _pool("pool5", "conv5", "MAX", 3, 2),
            _ip("fc6", "pool5", 4096, std=0.005, bias=1.0, params=_LRMULT_WD),
            _relu("relu6", "fc6"),
            _dropout("drop6", "fc6"),
            _ip("fc7", "fc6", 4096, std=0.005, bias=1.0, params=_LRMULT_WD),
            _relu("relu7", "fc7"),
            _dropout("drop7", "fc7"),
            _ip("fc8", "fc7", n_classes, std=0.01, params=_LRMULT_WD),
        ) + _heads("fc8"),
    )


def lenet(batch: int = 64) -> NetSpec:
    """LeNet-style MNIST convnet (conv5x5x32 + conv5x5x64 + fc512 + fc10),
    mirroring the reference's TF mnist graph."""
    return NetSpec(
        name="LeNet",
        inputs=(InputSpec("data", (batch, 1, 28, 28)),
                InputSpec("label", (batch, 1), "int32")),
        layers=(
            _conv("conv1", "data", 32, 5, pad=2, std=0.1),
            _relu("relu1", "conv1"),
            _pool("pool1", "conv1", "MAX", 2, 2),
            _conv("conv2", "pool1", 64, 5, pad=2, std=0.1),
            _relu("relu2", "conv2"),
            _pool("pool2", "conv2", "MAX", 2, 2),
            _ip("fc1", "pool2", 512, std=0.1, bias=0.1),
            _relu("relu3", "fc1"),
            _ip("fc2", "fc1", 10, std=0.1, bias=0.1),
        ) + _heads("fc2"),
    )


def adult_mlp(batch: int = 64, n_features: int = 1) -> NetSpec:
    """Tiny tabular net (test fixture parity: models/adult/adult.prototxt)."""
    return NetSpec(
        name="adult",
        inputs=(InputSpec("C0", (batch, n_features)),),
        layers=(
            _ip("ip", "C0", 10, filler=Filler(type="xavier")),
            LayerSpec(name="prob", type="Softmax", bottoms=("ip",),
                      tops=("prob",)),
        ),
    )
