"""Caffe-semantics SGD solver as a jitted functional update.

The reference's SGD lived entirely inside native Caffe
(`FloatSGDSolver.ApplyUpdate`, wrapped at reference `libs/CaffeSolver.scala:11-18`):
momentum, lr policy, per-blob lr_mult/decay_mult, weight decay, all configured
by `SolverParameter` prototxt. Here the same semantics are a pure function
over a pytree, so the whole train step (forward + backward + update) compiles
to one XLA executable and the optimizer state is first-class, checkpointable
data.

Caffe SGD update rule (SGDSolver<Dtype>::ComputeUpdateValue semantics):

    local_rate  = rate(iter) * lr_mult
    local_decay = weight_decay * decay_mult
    V <- momentum * V + local_rate * (grad + local_decay * W)
    W <- W - V

LR policies (Caffe `GetLearningRate`): fixed, step, exp, inv, multistep, poly,
sigmoid.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .model.net import CompiledNet, PyTree
from .model.spec import ParamSpec


@dataclass(frozen=True)
class SolverConfig:
    base_lr: float = 0.01
    lr_policy: str = "fixed"
    gamma: float = 0.1
    stepsize: int = 100000
    stepvalue: Tuple[int, ...] = ()
    power: float = 1.0
    max_iter: int = 10000
    momentum: float = 0.9
    weight_decay: float = 0.0
    iter_size: int = 1
    # Storage dtype for the velocity (momentum history). "float32" is
    # Caffe-exact. "bfloat16" is an OPT-IN speed knob: each step still
    # computes the update in f32 and applies the UNROUNDED velocity to the
    # weights — only the stored history is rounded — but it halves the
    # optimizer-state HBM stream that bounds the fc tail (PERF.md: fc6/7/8
    # wgrad+update fusions run at the memory roofline streaming f32 state).
    # Not the default because accuracy-parity (PARITY.md) is pinned to the
    # exact rule.
    velocity_dtype: str = "float32"

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SolverConfig":
        solver_type = d.get("type", "SGD")
        if solver_type not in ("SGD",):
            raise ValueError(
                f"unsupported solver type {solver_type!r} (only SGD with "
                f"momentum is implemented — fail loudly rather than silently "
                f"training with different dynamics)")
        fields = {f.name for f in dataclasses.fields(SolverConfig)}
        kw = {k: v for k, v in d.items() if k in fields}
        if "stepvalue" in kw:
            kw["stepvalue"] = tuple(kw["stepvalue"])
        return SolverConfig(**kw)


def learning_rate(cfg: SolverConfig, it: jnp.ndarray) -> jnp.ndarray:
    """rate(iter) for every Caffe lr_policy; `it` may be traced."""
    it = it.astype(jnp.float32)
    p = cfg.lr_policy
    if p == "fixed":
        return jnp.asarray(cfg.base_lr, jnp.float32)
    if p == "step":
        current = jnp.floor(it / cfg.stepsize)
        return cfg.base_lr * jnp.power(cfg.gamma, current)
    if p == "exp":
        return cfg.base_lr * jnp.power(cfg.gamma, it)
    if p == "inv":
        return cfg.base_lr * jnp.power(1.0 + cfg.gamma * it, -cfg.power)
    if p == "multistep":
        if not cfg.stepvalue:
            return jnp.asarray(cfg.base_lr, jnp.float32)
        steps = jnp.asarray(cfg.stepvalue, jnp.float32)
        current = jnp.sum(it[None] >= steps)
        return cfg.base_lr * jnp.power(cfg.gamma, current.astype(jnp.float32))
    if p == "poly":
        return cfg.base_lr * jnp.power(1.0 - it / cfg.max_iter, cfg.power)
    if p == "sigmoid":
        return cfg.base_lr / (1.0 + jnp.exp(-cfg.gamma * (it - cfg.stepsize)))
    raise ValueError(f"unknown lr_policy {p!r}")


@jax.tree_util.register_dataclass
@dataclass
class SolverState:
    """Optimizer state pytree: momentum history + iteration counter.

    NOTE (parity): in the reference, momentum history is worker-local native
    state that never crosses the wire — only net blobs are averaged
    (`libs/CaffeNet.scala:123-137`). The distributed trainer preserves that:
    it averages `params`, never `SolverState.momentum`.
    """

    momentum: PyTree
    it: jnp.ndarray  # scalar int32 iteration counter


class SgdSolver:
    """Functional SGD solver bound to a CompiledNet.

    `step` is the analogue of the reference's `Solver.step(rowIt)`
    (`libs/CaffeSolver.scala:15-18`): forward + backward + ApplyUpdate, except
    compiled into a single XLA executable (donated args, so updates are
    in-place on device).
    """

    def __init__(self, net: CompiledNet, cfg: SolverConfig,
                 loss_blob: str = "loss"):
        if cfg.velocity_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"velocity_dtype {cfg.velocity_dtype!r}: expected 'float32' "
                f"(Caffe-exact) or 'bfloat16' (opt-in, see SolverConfig)")
        self.net = net
        self.cfg = cfg
        self.loss_blob = loss_blob
        self._lr_mults, self._decay_mults = _param_multipliers(net)
        self._step = jax.jit(self._step_impl, donate_argnums=(0, 1))

    # -- state --------------------------------------------------------------

    def init_state(self, params: PyTree) -> SolverState:
        vdt = jnp.dtype(self.cfg.velocity_dtype)
        zeros = jax.tree.map(lambda w: jnp.zeros(w.shape, vdt), params)
        return SolverState(momentum=zeros, it=jnp.zeros((), jnp.int32))

    # -- single-step update (pure) ------------------------------------------

    def update(self, params: PyTree, state: SolverState, grads: PyTree,
               lr_scale: Any = 1.0) -> Tuple[PyTree, SolverState]:
        """Apply one Caffe-SGD update given precomputed grads (pure fn).

        `lr_scale` is a runtime (traceable) multiplier on the policy rate —
        the health supervisor's LR-backoff knob. It is an input, not a
        config field, so backing off after a rollback does NOT recompile
        the round (SolverConfig values are baked in at trace time)."""
        rate = learning_rate(self.cfg, state.it) * lr_scale

        def upd(path_key, w, v, g):
            lr_mult, decay_mult = path_key
            local_rate = rate * lr_mult
            local_decay = self.cfg.weight_decay * decay_mult
            # compute in the weight dtype (f32); only the STORED history is
            # in velocity_dtype — the weight sees the unrounded velocity
            v_new = (self.cfg.momentum * v.astype(w.dtype)
                     + local_rate * (g + local_decay * w))
            return w - v_new, v_new.astype(v.dtype)

        new_params: PyTree = {}
        new_mom: PyTree = {}
        for lname, lparams in params.items():
            new_params[lname], new_mom[lname] = {}, {}
            for pname, w in lparams.items():
                mults = self._lr_mults[lname][pname], self._decay_mults[lname][pname]
                nw, nv = upd(mults, w, state.momentum[lname][pname],
                             grads[lname][pname])
                new_params[lname][pname] = nw
                new_mom[lname][pname] = nv
        return new_params, SolverState(momentum=new_mom, it=state.it + 1)

    def _step_impl(self, params, state, batch, rng):
        k = self.cfg.iter_size
        if k == 1:
            (loss, blobs), grads = jax.value_and_grad(
                lambda p: self.net.loss_fn(self.loss_blob)(p, batch, rng),
                has_aux=True)(params)
        else:
            # Caffe iter_size semantics (SGDSolver::Step): accumulate grads
            # over iter_size micro-batches, normalize by 1/iter_size, ONE
            # ApplyUpdate, ONE iteration-counter bump. The incoming batch
            # carries iter_size × net-batch examples on the leading axis.
            micro = {kk: v.reshape((k, v.shape[0] // k) + v.shape[1:])
                     for kk, v in batch.items()}
            rngs = jax.random.split(rng, k)

            def accum(carry, xs):
                mb, sub = xs
                l, g = jax.value_and_grad(
                    lambda p: self.net.loss_fn(self.loss_blob)(
                        p, mb, sub)[0])(params)
                acc_l, acc_g = carry
                return (acc_l + l / k,
                        jax.tree.map(lambda a, b: a + b / k, acc_g, g)), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            from .parallel.mesh import scan_unroll
            (loss, grads), _ = jax.lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), (micro, rngs),
                unroll=scan_unroll(k))
        new_params, new_state = self.update(params, state, grads)
        return new_params, new_state, loss

    # -- public API ---------------------------------------------------------

    def step(self, params: PyTree, state: SolverState,
             batch: Dict[str, jnp.ndarray], rng: Optional[jax.Array] = None
             ) -> Tuple[PyTree, SolverState, jnp.ndarray]:
        """One jitted train step (one UPDATE: with iter_size=k the batch
        must hold k x net-batch examples — k accumulation micro-batches).
        Returns (params, state, loss)."""
        if rng is None:
            rng = jax.random.fold_in(jax.random.PRNGKey(0), int(state.it))
        k = self.cfg.iter_size
        if k > 1:
            for kk, v in batch.items():
                if v.shape[0] % k:
                    raise ValueError(
                        f"{kk}: batch dim {v.shape[0]} not divisible by "
                        f"iter_size {k} (pass iter_size x net-batch "
                        f"examples per step)")
        return self._step(params, state, batch, rng)


def _param_multipliers(net: CompiledNet):
    """Per-blob lr_mult/decay_mult from LayerSpec.params.

    Caffe convention (reference prototxts, e.g.
    `models/cifar10/cifar10_quick_train_test.prototxt` `param { lr_mult: 1 }
    param { lr_mult: 2 }`): first ParamSpec is the weight, second the bias.
    Missing specs default to 1.0.
    """
    lr: Dict[str, Dict[str, float]] = {}
    decay: Dict[str, Dict[str, float]] = {}
    for layer in net.spec.layers:
        from .model.layers import LAYER_IMPLS
        if LAYER_IMPLS[layer.type][0] is None:
            continue
        specs = list(layer.params) + [ParamSpec()] * (2 - len(layer.params))
        lr[layer.name] = {"w": specs[0].lr_mult, "b": specs[1].lr_mult}
        decay[layer.name] = {"w": specs[0].decay_mult, "b": specs[1].decay_mult}
    return lr, decay
