"""Declarative model IR: the TPU-native equivalent of Caffe's NetParameter.

The reference framework consumed Caffe prototxt (parsed natively via
`ReadProtoFromTextFileOrDie`, see reference `apps/CifarApp.scala:83-88`) and TF
GraphDefs. Here the IR is a plain-Python dataclass graph that a compiler
(`sparknet_tpu.model.net`) lowers to a pure JAX `apply(params, batch)` function.

Layer set = exactly what the reference model zoo uses
(reference `models/*.prototxt`): Convolution, Pooling, LRN, ReLU, InnerProduct,
Softmax, SoftmaxWithLoss, Accuracy, Dropout — plus Input declarations.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Filler:
    """Parameter initializer spec (Caffe `FillerParameter` semantics).

    type: "constant" (value), "gaussian" (std), "xavier" (uniform +-sqrt(3/fan_in)),
    "uniform" (min/max), "msra" (He normal).
    """

    type: str = "constant"
    value: float = 0.0
    std: float = 0.01
    mean: float = 0.0
    min: float = 0.0
    max: float = 1.0


@dataclass(frozen=True)
class ParamSpec:
    """Per-blob training hyperparameters (Caffe `ParamSpec`)."""

    lr_mult: float = 1.0
    decay_mult: float = 1.0


@dataclass(frozen=True)
class ConvolutionParam:
    num_output: int = 0
    kernel_size: int = 1
    stride: int = 1
    pad: int = 0
    group: int = 1
    bias_term: bool = True
    weight_filler: Filler = field(default_factory=Filler)
    bias_filler: Filler = field(default_factory=Filler)


@dataclass(frozen=True)
class PoolingParam:
    pool: str = "MAX"  # MAX | AVE
    kernel_size: int = 1
    stride: int = 1
    pad: int = 0
    global_pooling: bool = False


@dataclass(frozen=True)
class LRNParam:
    local_size: int = 5
    alpha: float = 1.0
    beta: float = 0.75
    k: float = 1.0
    norm_region: str = "ACROSS_CHANNELS"


@dataclass(frozen=True)
class InnerProductParam:
    num_output: int = 0
    bias_term: bool = True
    weight_filler: Filler = field(default_factory=Filler)
    bias_filler: Filler = field(default_factory=Filler)


@dataclass(frozen=True)
class DropoutParam:
    dropout_ratio: float = 0.5


@dataclass(frozen=True)
class AccuracyParam:
    top_k: int = 1


@dataclass(frozen=True)
class LayerSpec:
    name: str
    type: str
    bottoms: Tuple[str, ...] = ()
    tops: Tuple[str, ...] = ()
    params: Tuple[ParamSpec, ...] = ()
    include_phase: Optional[str] = None  # None = both; "TRAIN" | "TEST"
    conv: Optional[ConvolutionParam] = None
    pool: Optional[PoolingParam] = None
    lrn: Optional[LRNParam] = None
    inner_product: Optional[InnerProductParam] = None
    dropout: Optional[DropoutParam] = None
    accuracy: Optional[AccuracyParam] = None


@dataclass(frozen=True)
class InputSpec:
    """A declared net input (Caffe `input:` + `input_shape` blocks).

    Shape is the Caffe-declared shape: (N, C, H, W) for images, (N, D) for
    tabular/labels. Batch dim included, as in the reference prototxts.
    """

    name: str
    shape: Tuple[int, ...]
    dtype: str = "float32"


@dataclass(frozen=True)
class NetSpec:
    name: str
    inputs: Tuple[InputSpec, ...]
    layers: Tuple[LayerSpec, ...]

    def input_names(self) -> List[str]:
        return [i.name for i in self.inputs]

    def layer_by_name(self, name: str) -> LayerSpec:
        for l in self.layers:
            if l.name == name:
                return l
        raise KeyError(name)

    def layers_for_phase(self, phase: str) -> List[LayerSpec]:
        return [
            l
            for l in self.layers
            if l.include_phase is None or l.include_phase == phase
        ]

    def replace(self, **kw) -> "NetSpec":
        return dataclasses.replace(self, **kw)


# Layer types that carry trainable parameters.
PARAMETRIC_LAYER_TYPES = ("Convolution", "InnerProduct")


def validate(spec: NetSpec) -> None:
    """Structural validation: every bottom must be produced before use."""
    available = set(spec.input_names())
    for l in spec.layers:
        for b in l.bottoms:
            if b not in available:
                raise ValueError(
                    f"layer {l.name!r}: bottom {b!r} not produced by any "
                    f"earlier layer or input (have {sorted(available)})"
                )
        available.update(l.tops)
