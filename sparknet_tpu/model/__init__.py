from .spec import NetSpec, LayerSpec, InputSpec, Filler  # noqa: F401
from .net import CompiledNet  # noqa: F401
