"""Weight-only int8 quantization for the serve path.

Per-channel symmetric absmax quantization of the parametric layers'
weight matrices (Convolution HWIO, InnerProduct (in, out) — the output
channel is the LAST axis in both layouts, so one rule covers both):

    scale[o] = max(|w[..., o]|) / 127        (per output channel)
    w_q[..., o] = round(w[..., o] / scale[o])  in int8

Symmetric means the zero point is identically 0 and is elided from the
stored pytree — the scale vector IS the whole side-car. Dequantization at
use is `w_q * scale` cast to the activation dtype (bfloat16 by default:
int8 weights at rest + bf16 activations in flight, the Pope et al. 2022
serving recipe); XLA fuses the dequant multiply into the consuming
conv/matmul, so the weight never materializes in f32.

This is a SERVING transform: `ModelManager` quantizes at checkpoint load
time (`QuantConfig` on ServeConfig) and gates the install on a parity
canary against the f32 forward — training state never sees these leaves.
Biases stay in f32 (they're O(channels) bytes and add directly into the
accumulator).

Quantized layer params look like `{"w_q": int8[..., O], "w_scale":
f32[O], "b": f32[O]}` in place of `{"w": f32[..., O], "b": ...}`; the
layer impls in `model/layers.py` dispatch on the `w_q` key, so a params
pytree is self-describing and the f32 path is untouched.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

#: layer param trees carrying one of these keys are quantized leaves
QUANT_KEYS = ("w_q", "w_scale")


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """Knobs for the quantized serve path (ServeConfig.quant surfaces
    these; `mode="int8"` is the only wire format today).

    act: activation dtype for quantized forwards — "bfloat16" (default:
    halves host->device input bytes and runs the MXU fast path) or
    "float32" (debug: isolates weight-quant error from activation
    rounding).

    rtol/atol: the calibrated parity tolerance the load-time canary
    enforces between the quantized and f32 forwards on the same batch
    (the PR 7 Pallas-pin pattern, promoted from test-time to load-time:
    a quantization whose outputs drift past this NEVER SERVES — the
    manager rolls back and rejects the checkpoint). Defaults calibrated
    on the zoo serve models' prob/logit outputs under int8+bf16
    (tests/test_quant.py pins them per model; worst measured drift is
    ~0.05 on fresh-init lenet probs — near-uniform logits are the
    adversarial case — while a corrupted scale lands >0.3, so the gate
    separates cleanly)."""

    mode: str = "int8"
    act: str = "bfloat16"
    rtol: float = 0.05
    atol: float = 0.08

    def __post_init__(self) -> None:
        # the OpsImpl/ElasticConfig rule: a typo'd knob fails at config
        # construction, not at the first forward's trace
        if self.mode != "int8":
            raise ValueError(f"unknown quant mode {self.mode!r}: "
                             f"expected 'int8'")
        if self.act not in ("bfloat16", "float32"):
            raise ValueError(f"unknown quant act dtype {self.act!r}: "
                             f"expected 'bfloat16' or 'float32'")
        if self.rtol < 0 or self.atol < 0:
            raise ValueError("quant rtol/atol must be >= 0")

    def act_dtype(self):
        return jnp.bfloat16 if self.act == "bfloat16" else jnp.float32

    @staticmethod
    def coerce(v: Any) -> Optional["QuantConfig"]:
        """ServeConfig/CLI sugar: None, a mode string ("int8"), a dict of
        fields, or a QuantConfig -> QuantConfig | None."""
        if v is None or isinstance(v, QuantConfig):
            return v
        if isinstance(v, str):
            return QuantConfig(mode=v)
        if isinstance(v, dict):
            return QuantConfig(**v)
        raise ValueError(f"quant must be None, a mode string, a dict, or "
                         f"a QuantConfig (got {type(v).__name__})")


def quantize_leaf(w: np.ndarray) -> Dict[str, jnp.ndarray]:
    """One weight tensor -> {"w_q": int8, "w_scale": f32 per out channel}.
    The scale floor keeps an all-zero channel from dividing by zero (its
    quantized rows are exactly zero either way)."""
    w = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    scale = np.maximum(absmax / 127.0, np.float32(1e-12)).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"w_q": jnp.asarray(q), "w_scale": jnp.asarray(scale)}


def quantize_params(params: Dict[str, Dict[str, Any]],
                    cfg: Optional[QuantConfig] = None
                    ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """A JaxNet params pytree -> its weight-only-quantized twin. Every
    >=2-D "w" leaf (conv HWIO / inner-product (in,out)) becomes the
    (w_q, w_scale) pair; biases and 1-D leaves ride along in f32. The
    input pytree is not mutated."""
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for lname, lp in params.items():
        out[lname] = {}
        for pname, leaf in lp.items():
            if pname == "w" and np.ndim(leaf) >= 2:
                out[lname].update(quantize_leaf(np.asarray(leaf)))
            else:
                out[lname][pname] = jnp.asarray(leaf)
    return out


def is_quantized(params: Dict[str, Dict[str, Any]]) -> bool:
    """True when any layer of the pytree carries quantized leaves."""
    return any("w_q" in lp for lp in params.values())


def dequantize_params(qparams: Dict[str, Dict[str, Any]]
                      ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """The f32 reconstruction (tests / export): w = w_q * w_scale. NOT
    the serving path — layers dequantize lazily inside the forward."""
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for lname, lp in qparams.items():
        out[lname] = {}
        if "w_q" in lp:
            out[lname]["w"] = (lp["w_q"].astype(jnp.float32)
                               * lp["w_scale"])
        for pname, leaf in lp.items():
            if pname not in QUANT_KEYS:
                out[lname][pname] = leaf
    return out
