"""Caffe prototxt (protobuf text-format) importer.

Parity with the reference's native prototxt path
(`ReadProtoFromTextFileOrDie` at reference `apps/CifarApp.scala:83-84`,
`libs/CaffeNet.scala:22-26`): parse the text format into a generic message
tree, then interpret the NetParameter / SolverParameter subset used by the
reference model zoo (`models/cifar10/*.prototxt`,
`models/bvlc_reference_caffenet/*.prototxt`, `models/adult/adult.prototxt`)
into `NetSpec` / a solver-config dict.

The parser is a small hand-rolled recursive-descent tokenizer: no protobuf
runtime or compiled descriptors needed, and it accepts any well-formed
text-format message (unknown fields are preserved in the generic tree and
ignored by the interpreters).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple, Union

from .spec import (
    AccuracyParam,
    ConvolutionParam,
    DropoutParam,
    Filler,
    InnerProductParam,
    InputSpec,
    LayerSpec,
    LRNParam,
    NetSpec,
    ParamSpec,
    PoolingParam,
    validate,
)

# ---------------------------------------------------------------------------
# Generic text-format parsing
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<comment>\#[^\n]*)
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<punct>[{}:])
      | (?P<atom>[^\s{}:"#]+)
    )
    """,
    re.VERBOSE,
)


def _tokenize(text: str) -> List[str]:
    tokens: List[str] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"prototxt: unexpected character at offset {pos}: "
                             f"{text[pos:pos+20]!r}")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        tokens.append(m.group(m.lastgroup))
    return tokens


Message = Dict[str, List[Any]]  # field name -> list of values (scalars or sub-messages)


def _coerce_scalar(tok: str) -> Union[str, int, float, bool]:
    if tok.startswith('"'):
        return tok[1:-1].encode().decode("unicode_escape")
    if tok == "true":
        return True
    if tok == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok  # enum identifier (e.g. MAX, AVE, TRAIN)


def parse_message(text: str) -> Message:
    """Parse protobuf text-format into a dict of field -> list of values."""
    tokens = _tokenize(text)
    pos = 0

    def parse_body(stop_at_brace: bool) -> Message:
        nonlocal pos
        msg: Message = {}
        while pos < len(tokens):
            tok = tokens[pos]
            if tok == "}":
                if not stop_at_brace:
                    raise ValueError("prototxt: unbalanced '}'")
                pos += 1
                return msg
            field = tok
            pos += 1
            if pos >= len(tokens):
                raise ValueError(f"prototxt: dangling field {field!r}")
            if tokens[pos] == ":":
                pos += 1
                if pos < len(tokens) and tokens[pos] == "{":
                    # `field: { ... }` is also legal text format
                    pos += 1
                    value: Any = parse_body(True)
                else:
                    value = _coerce_scalar(tokens[pos])
                    pos += 1
            elif tokens[pos] == "{":
                pos += 1
                value = parse_body(True)
            else:
                raise ValueError(
                    f"prototxt: expected ':' or '{{' after field {field!r}, "
                    f"got {tokens[pos]!r}")
            msg.setdefault(field, []).append(value)
        if stop_at_brace:
            raise ValueError("prototxt: missing '}'")
        return msg

    return parse_body(False)


def _one(msg: Message, field: str, default=None):
    vals = msg.get(field)
    if not vals:
        return default
    return vals[-1]  # text-format: last occurrence of a singular field wins


# ---------------------------------------------------------------------------
# NetParameter interpretation
# ---------------------------------------------------------------------------


def _filler(msg: Message | None) -> Filler:
    if not msg:
        return Filler()
    return Filler(
        type=_one(msg, "type", "constant"),
        value=float(_one(msg, "value", 0.0)),
        std=float(_one(msg, "std", 0.01)),
        mean=float(_one(msg, "mean", 0.0)),
        min=float(_one(msg, "min", 0.0)),
        max=float(_one(msg, "max", 1.0)),
    )


def _square_geometry(block: "Message", layer_name: str, block_name: str,
                     base: str, default: int) -> int:
    """Resolve `<base>` vs `<base>_h`/`<base>_w` (Caffe allows either form).
    Square h==w values fold into the base field; genuinely RECTANGULAR
    geometry fails loudly — importing it with defaults would train a
    structurally wrong net (same stance as unknown layer types and non-SGD
    solvers)."""
    bv = _one(block, base)
    stem = base[:-5] if base.endswith("_size") else base  # kernel_size -> kernel_h
    hv, wv = _one(block, f"{stem}_h"), _one(block, f"{stem}_w")
    if hv is None and wv is None:
        return int(bv) if bv is not None else default
    if hv is None or wv is None or int(hv) != int(wv):
        raise ValueError(
            f"layer {layer_name!r}: {block_name} {stem}_h/{stem}_w = "
            f"({hv}, {wv}) is rectangular — recognized but not implemented "
            f"(square geometry only); refusing to import a structurally "
            f"different net silently")
    if bv is not None and int(bv) != int(hv):
        raise ValueError(
            f"layer {layer_name!r}: {block_name} specifies both {base}={bv} "
            f"and {base}_h/{base}_w={hv} with conflicting values")
    return int(hv)


def _layer_from_msg(m: Message) -> LayerSpec:
    name = _one(m, "name", "")
    ltype = _one(m, "type", "")
    bottoms = tuple(m.get("bottom", []))
    tops = tuple(m.get("top", []))
    params = tuple(
        ParamSpec(
            lr_mult=float(_one(p, "lr_mult", 1.0)),
            decay_mult=float(_one(p, "decay_mult", 1.0)),
        )
        for p in m.get("param", [])
    )
    include_phase = None
    for inc in m.get("include", []):
        phase = _one(inc, "phase")
        if phase is not None:
            include_phase = str(phase)

    kw: Dict[str, Any] = {}
    cp = _one(m, "convolution_param")
    if cp:
        if int(_one(cp, "dilation", 1)) != 1:
            raise ValueError(
                f"layer {name!r}: convolution_param.dilation is recognized "
                f"but not implemented — refusing to import a structurally "
                f"different net silently")
        kw["conv"] = ConvolutionParam(
            num_output=int(_one(cp, "num_output", 0)),
            kernel_size=_square_geometry(cp, name, "convolution_param",
                                         "kernel_size", 1),
            stride=_square_geometry(cp, name, "convolution_param",
                                    "stride", 1),
            pad=_square_geometry(cp, name, "convolution_param", "pad", 0),
            group=int(_one(cp, "group", 1)),
            bias_term=bool(_one(cp, "bias_term", True)),
            weight_filler=_filler(_one(cp, "weight_filler")),
            bias_filler=_filler(_one(cp, "bias_filler")),
        )
    pp = _one(m, "pooling_param")
    if pp:
        kw["pool"] = PoolingParam(
            pool=str(_one(pp, "pool", "MAX")),
            kernel_size=_square_geometry(pp, name, "pooling_param",
                                         "kernel_size", 1),
            stride=_square_geometry(pp, name, "pooling_param", "stride", 1),
            pad=_square_geometry(pp, name, "pooling_param", "pad", 0),
            global_pooling=bool(_one(pp, "global_pooling", False)),
        )
    lp = _one(m, "lrn_param")
    if lp:
        kw["lrn"] = LRNParam(
            local_size=int(_one(lp, "local_size", 5)),
            alpha=float(_one(lp, "alpha", 1.0)),
            beta=float(_one(lp, "beta", 0.75)),
            k=float(_one(lp, "k", 1.0)),
            norm_region=str(_one(lp, "norm_region", "ACROSS_CHANNELS")),
        )
    ip = _one(m, "inner_product_param")
    if ip:
        kw["inner_product"] = InnerProductParam(
            num_output=int(_one(ip, "num_output", 0)),
            bias_term=bool(_one(ip, "bias_term", True)),
            weight_filler=_filler(_one(ip, "weight_filler")),
            bias_filler=_filler(_one(ip, "bias_filler")),
        )
    dp = _one(m, "dropout_param")
    if dp:
        kw["dropout"] = DropoutParam(
            dropout_ratio=float(_one(dp, "dropout_ratio", 0.5)))
    ap = _one(m, "accuracy_param")
    if ap:
        kw["accuracy"] = AccuracyParam(top_k=int(_one(ap, "top_k", 1)))
    if ltype == "Dropout" and "dropout" not in kw:
        kw["dropout"] = DropoutParam()
    if ltype == "Accuracy" and "accuracy" not in kw:
        kw["accuracy"] = AccuracyParam()
    ccp = _one(m, "concat_param")
    if ccp:
        axis = _one(ccp, "axis", _one(ccp, "concat_dim", 1))
        if int(axis) != 1:
            raise ValueError(
                f"layer {name!r}: Concat axis {axis} is recognized but only "
                f"channel concat (axis 1) is implemented — refusing to "
                f"import a structurally different net silently")

    return LayerSpec(
        name=name,
        type=ltype,
        bottoms=bottoms,
        tops=tops,
        params=params,
        include_phase=include_phase,
        **kw,
    )


_SKIP_LAYER_TYPES = {"Data", "ImageData", "HDF5Data"}  # data layers -> net inputs


def net_from_prototxt(text: str) -> NetSpec:
    """Interpret a NetParameter text proto into a NetSpec.

    Handles both in-memory input declarations (`input:` + `input_shape`, as in
    the reference's cifar10/adult prototxts) and `Data`-type layers (as in
    bvlc_reference_caffenet/train_val.prototxt), which become declared inputs
    since this framework feeds batches directly.
    """
    msg = parse_message(text)
    name = _one(msg, "name", "net")

    inputs: List[InputSpec] = []
    input_names = list(msg.get("input", []))
    shapes = msg.get("input_shape", [])
    # legacy `input_dim` flat form: 4 dims per input
    flat_dims = [int(d) for d in msg.get("input_dim", [])]
    for i, iname in enumerate(input_names):
        if i < len(shapes):
            dims = tuple(int(d) for d in shapes[i].get("dim", []))
        elif flat_dims:
            dims = tuple(flat_dims[i * 4:(i + 1) * 4])
        else:
            raise ValueError(f"input {iname!r} has no declared shape")
        dtype = "int32" if iname == "label" else "float32"
        inputs.append(InputSpec(name=iname, shape=dims, dtype=dtype))

    layers: List[LayerSpec] = []
    for lm in msg.get("layer", []) + msg.get("layers", []):
        spec = _layer_from_msg(lm)
        if spec.type in _SKIP_LAYER_TYPES:
            # Data layer: its tops become net inputs. Shape is unknown from the
            # prototxt alone (lives in transform_param / data source); callers
            # pass shapes via `data_layer_shapes`.
            for top in spec.tops:
                if top not in [i.name for i in inputs]:
                    dtype = "int32" if top == "label" else "float32"
                    inputs.append(InputSpec(name=top, shape=(), dtype=dtype))
            continue
        layers.append(spec)

    spec = NetSpec(name=name, inputs=tuple(inputs), layers=tuple(layers))
    return spec


def net_from_prototxt_file(path: str, *,
                           input_shapes: Dict[str, Tuple[int, ...]] | None = None,
                           phase: str | None = None) -> NetSpec:
    with open(path) as f:
        spec = net_from_prototxt(f.read())
    if input_shapes:
        new_inputs = tuple(
            InputSpec(i.name, tuple(input_shapes.get(i.name, i.shape)), i.dtype)
            for i in spec.inputs)
        spec = spec.replace(inputs=new_inputs)
    missing = [i.name for i in spec.inputs if not i.shape]
    if missing:
        raise ValueError(
            f"net {spec.name!r}: inputs {missing} need shapes "
            f"(pass input_shapes=...)")
    if phase is not None:
        spec = spec.replace(layers=tuple(spec.layers_for_phase(phase)))
    validate(spec)
    return spec


# ---------------------------------------------------------------------------
# SolverParameter interpretation
# ---------------------------------------------------------------------------


def solver_from_prototxt(text: str) -> Dict[str, Any]:
    """Parse a SolverParameter text proto into a plain config dict.

    Covers the fields the reference solvers use
    (`models/cifar10/cifar10_quick_solver.prototxt:12-20`,
    `models/bvlc_reference_caffenet/solver.prototxt:2-11`):
    base_lr, momentum, weight_decay, lr_policy, gamma, stepsize, power,
    max_iter, display, snapshot, net.
    """
    msg = parse_message(text)
    out: Dict[str, Any] = {}
    for key in ("base_lr", "momentum", "weight_decay", "gamma", "power"):
        v = _one(msg, key)
        if v is not None:
            out[key] = float(v)
    for key in ("stepsize", "max_iter", "display", "snapshot", "iter_size"):
        v = _one(msg, key)
        if v is not None:
            out[key] = int(v)
    for key in ("lr_policy", "net", "snapshot_prefix", "type", "solver_mode"):
        v = _one(msg, key)
        if v is not None:
            out[key] = str(v)
    if "stepvalue" in msg:
        out["stepvalue"] = [int(v) for v in msg["stepvalue"]]
    return out


def solver_from_prototxt_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return solver_from_prototxt(f.read())
