"""WeightCollection: the host-side unit of weight exchange, Caffe layout.

Parity with reference `libs/WeightCollection.scala` (and
`TensorFlowWeightCollection.scala`): an ordered mapping
layer name -> list of blobs (numpy, Caffe shapes: conv OIHW, inner-product
(out, in), biases 1-D), with `add`, `scalar_divide`, `check_equal` — the
operations the driver used for parameter averaging
(`apps/CifarApp.scala:145-146`).

On TPU the averaging itself happens on device (`lax.pmean`); this class exists
for the host-side API surface: checkpoint I/O, cross-framework import/export,
and tests. Conversions to/from the device pytree (TPU layouts HWIO / (in,out))
live in `caffe_compat`.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

import numpy as np


class WeightCollection:
    def __init__(self, weights: Dict[str, List[np.ndarray]],
                 layer_names: List[str] | None = None):
        self.weights = {k: [np.asarray(b, dtype=np.float32) for b in v]
                        for k, v in weights.items()}
        self.layer_names = list(layer_names or weights.keys())

    def __getitem__(self, name: str) -> List[np.ndarray]:
        return self.weights[name]

    def __contains__(self, name: str) -> bool:
        return name in self.weights

    def __iter__(self) -> Iterator[str]:
        return iter(self.layer_names)

    def blobs(self) -> Iterator[Tuple[str, int, np.ndarray]]:
        for name in self.layer_names:
            for j, blob in enumerate(self.weights[name]):
                yield name, j, blob

    def scalar_divide(self, v: float) -> None:
        """In-place divide (reference `WeightCollection.scala:9-15`)."""
        for name in self.layer_names:
            for blob in self.weights[name]:
                blob /= v

    @staticmethod
    def add(a: "WeightCollection", b: "WeightCollection") -> "WeightCollection":
        """Elementwise sum with shape checks (`WeightCollection.scala:19-38`)."""
        assert a.layer_names == b.layer_names, (
            f"layer sets differ: {a.layer_names} vs {b.layer_names}")
        out: Dict[str, List[np.ndarray]] = {}
        for name in a.layer_names:
            ab, bb = a.weights[name], b.weights[name]
            assert len(ab) == len(bb), f"{name}: blob count differs"
            for x, y in zip(ab, bb):
                assert x.shape == y.shape, (
                    f"{name}: shape mismatch {x.shape} vs {y.shape}")
            out[name] = [x + y for x, y in zip(ab, bb)]
        return WeightCollection(out, a.layer_names)

    @staticmethod
    def check_equal(a: "WeightCollection", b: "WeightCollection",
                    tol: float = 1e-6) -> bool:
        """Tolerant equality (`WeightCollection.scala:40-59`)."""
        if a.layer_names != b.layer_names:
            return False
        for name in a.layer_names:
            ab, bb = a.weights[name], b.weights[name]
            if len(ab) != len(bb):
                return False
            for x, y in zip(ab, bb):
                if x.shape != y.shape or not np.allclose(x, y, atol=tol):
                    return False
        return True

    # -- serialization (npz) -------------------------------------------------

    def save(self, path: str) -> None:
        arrays = {f"{name}/{j}": blob for name, j, blob in self.blobs()}
        arrays["__layer_names__"] = np.array(self.layer_names)
        np.savez(path, **arrays)

    @staticmethod
    def load(path: str) -> "WeightCollection":
        with np.load(path, allow_pickle=False) as z:
            layer_names = [str(s) for s in z["__layer_names__"]]
            weights: Dict[str, List[np.ndarray]] = {n: [] for n in layer_names}
            keys = sorted((k for k in z.files if k != "__layer_names__"),
                          key=lambda k: (k.rsplit("/", 1)[0],
                                         int(k.rsplit("/", 1)[1])))
            for k in keys:
                name, _ = k.rsplit("/", 1)
                weights[name].append(z[k])
        return WeightCollection(weights, layer_names)
