"""NetSpec -> pure JAX function compiler.

The reference's equivalent is Caffe's native net builder (`FloatNet` built from
a `NetParameter`, wrapped at reference `libs/CaffeNet.scala:28-68`). Here the
"net" is data: a `CompiledNet` holds
  - `init_params(key) -> params` (pytree: {layer_name: {"w": ..., "b": ...}})
  - `apply(params, batch, train=, rng=) -> {blob_name: array}`
and everything downstream (`jit`, `grad`, `shard_map`) composes functionally.

Layout: 4D inputs are declared NCHW in prototxt but consumed NHWC on device;
`CompiledNet.input_shapes` reports the NHWC shapes the caller must feed.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .layers import LAYER_IMPLS, ApplyCtx, OpsImpl, Params
from .quant import QuantConfig
from .spec import InputSpec, NetSpec, validate

PyTree = Dict[str, Params]

#: CompiledNet.compile memo: identical NetSpecs (frozen, hashable) compile
#: once per process — the spec-level half of the compile-cache story
_SPEC_MEMO: Dict[NetSpec, "CompiledNet"] = {}


def _to_nhwc_shape(shape: Tuple[int, ...]) -> Tuple[int, ...]:
    if len(shape) == 4:
        n, c, h, w = shape
        return (n, h, w, c)
    return shape


_DTYPES = {"float32": jnp.float32, "int32": jnp.int32, "bfloat16": jnp.bfloat16}


@dataclasses.dataclass(frozen=True)
class CompiledNet:
    spec: NetSpec
    #: blob name -> NHWC device shape for every net input
    input_shapes: Dict[str, Tuple[int, ...]]
    #: blob name -> dtype string
    input_dtypes: Dict[str, str]
    #: blob name -> shape for every top produced in TRAIN phase (() = scalar)
    blob_shapes: Dict[str, Tuple[int, ...]]
    #: names of output blobs (tops never consumed by a later layer), per phase
    output_names: Tuple[str, ...]

    # -- construction -------------------------------------------------------

    @staticmethod
    def compile(spec: NetSpec) -> "CompiledNet":
        # stamped as a compile event (obs/device.py): every spec compile
        # lands in the process-wide record, so jit-cache churn driven by
        # repeated net construction is scrapeable, not invisible.
        # Identical specs (frozen dataclasses, hashable) return the
        # memoized CompiledNet — router lanes, elastic rebuilds, and
        # serve hot-swap retraces of the same architecture skip
        # re-validation, and the event records cache_hit="true". A memo
        # MISS stamps cache_hit=None ("unknown"): spec compilation is
        # pure Python — the persistent XLA cache neither applies to it
        # nor should claim it — so its real duration still lands in the
        # compile-seconds histogram while never counting as a fresh-XLA
        # miss against the warm-replica acceptance.
        import time as _time

        from ..obs.device import note_compile
        try:
            cached = _SPEC_MEMO.get(spec)
        except TypeError:  # unhashable spec (hand-built with lists)
            cached = None
        if cached is not None:
            note_compile("net", 0.0, cache_hit=True)
            return cached
        t0 = _time.perf_counter()
        net = CompiledNet._compile(spec)
        note_compile("net", _time.perf_counter() - t0)
        try:
            _SPEC_MEMO[spec] = net
        except TypeError:
            pass
        return net

    @staticmethod
    def _compile(spec: NetSpec) -> "CompiledNet":
        validate(spec)
        input_shapes = {i.name: _to_nhwc_shape(i.shape) for i in spec.inputs}
        input_dtypes = {i.name: i.dtype for i in spec.inputs}
        blob_shapes: Dict[str, Tuple[int, ...]] = dict(input_shapes)
        consumed: set = set()
        produced: List[str] = list(input_shapes)
        for layer in spec.layers:
            if layer.type not in LAYER_IMPLS:
                raise ValueError(f"unsupported layer type {layer.type!r} "
                                 f"(layer {layer.name!r})")
            _, _, infer = LAYER_IMPLS[layer.type]
            in_shapes = tuple(blob_shapes[b] for b in layer.bottoms)
            out_shapes = infer(layer, in_shapes)
            for t, s in zip(layer.tops, out_shapes):
                blob_shapes[t] = s
                produced.append(t)
            consumed.update(b for b in layer.bottoms if b not in layer.tops)
        outputs = tuple(
            dict.fromkeys(t for t in produced
                          if t not in consumed and t not in input_shapes))
        return CompiledNet(spec=spec, input_shapes=input_shapes,
                           input_dtypes=input_dtypes, blob_shapes=blob_shapes,
                           output_names=outputs)

    # -- parameters ---------------------------------------------------------

    def init_params(self, key: jax.Array) -> PyTree:
        params: PyTree = {}
        shapes: Dict[str, Tuple[int, ...]] = dict(self.input_shapes)
        for layer in self.spec.layers:
            init, _, infer = LAYER_IMPLS[layer.type]
            in_shapes = tuple(shapes[b] for b in layer.bottoms)
            if init is not None:
                key, sub = jax.random.split(key)
                params[layer.name] = init(sub, layer, in_shapes)
            for t, s in zip(layer.tops, infer(layer, in_shapes)):
                shapes[t] = s
        return params

    def param_layers(self) -> List[str]:
        return [l.name for l in self.spec.layers
                if LAYER_IMPLS[l.type][0] is not None]

    # -- execution ----------------------------------------------------------

    def apply(self, params: PyTree, batch: Dict[str, jnp.ndarray], *,
              train: bool = False, rng: Optional[jax.Array] = None,
              phase: Optional[str] = None, tp_axis: Optional[str] = None,
              tp_size: int = 1, ops: Optional[OpsImpl] = None,
              quant: Optional[QuantConfig] = None
              ) -> Dict[str, jnp.ndarray]:
        """Run the net. `batch` maps input blob names to NHWC arrays.

        Returns every blob produced (inputs excluded), so callers can read
        hidden activations by name — parity with the reference's
        `forward(rowIt, dataBlobNames)` path (`libs/CaffeNet.scala:101-107`)
        used by FeaturizerApp.

        tp_axis/tp_size: run tensor-parallel (inside shard_map over that
        mesh axis) with column-sharded InnerProduct weights — see ApplyCtx.

        ops: kernel-implementation selection for LRN/pooling (OpsImpl;
        None = "auto" dispatch — Pallas kernels on TPU, portable paths
        elsewhere).

        quant: serving-side weight-only quantization config (model/
        quant.py). `params` may then hold int8 `w_q` + per-channel
        `w_scale` leaves in place of `w` for Convolution/InnerProduct
        layers; the layer impls dequantize at use into the quant
        activation dtype. With f32 `w` leaves this knob changes nothing —
        the f32 path is untouched by construction.
        """
        phase = phase or ("TRAIN" if train else "TEST")
        ctx = ApplyCtx(train=train, rng=rng, tp_axis=tp_axis,
                       tp_size=tp_size, ops=ops or OpsImpl(),
                       quant=quant)
        blobs: Dict[str, jnp.ndarray] = dict(batch)
        all_tops = set()
        for layer in self.spec.layers_for_phase(phase):
            _, apply_fn, _ = LAYER_IMPLS[layer.type]
            inputs = tuple(blobs[b] for b in layer.bottoms)
            outputs = apply_fn(layer, params.get(layer.name), inputs, ctx)
            for t, v in zip(layer.tops, outputs):
                blobs[t] = v
                all_tops.add(t)
        for name in batch:
            if name not in all_tops:
                blobs.pop(name, None)
        return blobs

    def loss_fn(self, loss_blob: str = "loss",
                tp_axis: Optional[str] = None, tp_size: int = 1,
                ops: Optional[OpsImpl] = None):
        """Returns `f(params, batch, rng) -> (loss, aux_blobs)` for jax.grad."""

        def f(params, batch, rng=None):
            blobs = self.apply(params, batch, train=True, rng=rng,
                               tp_axis=tp_axis, tp_size=tp_size, ops=ops)
            return blobs[loss_blob], blobs

        return f

    def example_batch(self, key: Optional[jax.Array] = None,
                      batch_size: Optional[int] = None) -> Dict[str, jnp.ndarray]:
        """Synthesize a correctly-shaped random batch (for tests/AOT warmup)."""
        key = key if key is not None else jax.random.PRNGKey(0)
        batch = {}
        for name, shape in self.input_shapes.items():
            if batch_size is not None:
                shape = (batch_size,) + tuple(shape[1:])
            key, sub = jax.random.split(key)
            if self.input_dtypes[name] == "int32":
                batch[name] = jax.random.randint(sub, shape, 0, 10, jnp.int32)
            else:
                batch[name] = jax.random.normal(sub, shape, jnp.float32)
        return batch
