"""Device-pytree <-> Caffe-layout WeightCollection conversion.

The device stores TPU-first layouts (conv HWIO, inner-product (in, out) with
NCHW-flatten row ordering); Caffe stores OIHW and (out, in). These conversions
are exact permutations, so a get_weights -> set_weights round trip is
bit-identical — the property the reference's sync loop depended on
(`libs/CaffeNet.scala:123-150`).
"""
from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from .net import CompiledNet, PyTree
from .weights import WeightCollection


def params_to_collection(net: CompiledNet, params: PyTree) -> WeightCollection:
    """Device pytree -> Caffe-layout host WeightCollection."""
    weights: Dict[str, List[np.ndarray]] = {}
    order: List[str] = []
    for layer in net.spec.layers:
        if layer.name not in params:
            continue
        order.append(layer.name)
        lp = params[layer.name]
        blobs: List[np.ndarray] = []
        w = np.asarray(lp["w"], dtype=np.float32)
        if layer.type == "Convolution":
            blobs.append(np.transpose(w, (3, 2, 0, 1)))  # HWIO -> OIHW
        elif layer.type == "InnerProduct":
            blobs.append(np.ascontiguousarray(w.T))  # (in,out) -> (out,in)
        else:
            blobs.append(w)
        if "b" in lp:
            blobs.append(np.asarray(lp["b"], dtype=np.float32))
        weights[layer.name] = blobs
    return WeightCollection(weights, order)


def collection_to_params(net: CompiledNet, coll: WeightCollection) -> PyTree:
    """Caffe-layout WeightCollection -> device pytree (with shape asserts)."""
    params: PyTree = {}
    for layer in net.spec.layers:
        if layer.name not in coll:
            continue
        blobs = coll[layer.name]
        lp: Dict[str, jnp.ndarray] = {}
        w = blobs[0]
        if layer.type == "Convolution":
            if w.ndim != 4:
                raise ValueError(f"{layer.name}: conv weight must be 4-D "
                                 f"OIHW, got {w.shape}")
            lp["w"] = jnp.asarray(np.transpose(w, (2, 3, 1, 0)))  # OIHW -> HWIO
        elif layer.type == "InnerProduct":
            # legacy .caffemodel IP weights arrive 4-D (1,1,out,in); a
            # num_output=1 legacy blob canonicalized to a (in,) vector
            if w.ndim == 4:
                if w.shape[:2] != (1, 1):
                    raise ValueError(f"{layer.name}: 4-D inner-product "
                                     f"weight {w.shape} is not (1,1,out,in)")
                w = w.reshape(w.shape[2:])
            elif w.ndim == 1:
                w = w.reshape(1, -1)
            lp["w"] = jnp.asarray(np.ascontiguousarray(w.T))
        else:
            lp["w"] = jnp.asarray(w)
        if len(blobs) > 1:
            lp["b"] = jnp.asarray(blobs[1].reshape(-1))
        params[layer.name] = lp
    return params
