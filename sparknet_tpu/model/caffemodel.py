"""Binary `.caffemodel` (Caffe NetParameter protobuf) import/export.

Capability parity with reference `libs/CaffeNet.scala`:
  - `copyTrainedLayersFrom` (152-157): load trained blobs from a binary
    NetParameter file -> here `load_caffemodel` -> `WeightCollection`
    (Caffe layouts: conv OIHW, inner-product (out,in)); feed a net via
    `caffe_compat.collection_to_params` / `JaxNet.set_weights`.
  - `saveWeightsToFile` (159-165: net.ToProto -> WriteProtoToBinaryFile)
    -> here `save_caffemodel`.

No protoc and no Caffe dependency: decoding reuses the generic protobuf
wire parser from `backend/tf_import.py` (the same decoder that reads TF
GraphDefs), plus a ~40-line wire ENCODER for export.

Proto schema subset (field numbers from caffe.proto):
  NetParameter:     name=1  layers=2 (V1LayerParameter)  layer=100 (LayerParameter)
  LayerParameter:   name=1  type=2 (string)  blobs=7
  V1LayerParameter: blobs=6  name=4  type=5 (enum)
  BlobProto:        num=1 channels=2 height=3 width=4 (legacy 4-D shape)
                    data=5 (packed float)  shape=7 (BlobShape)  double_data=8
  BlobShape:        dim=1 (packed int64)
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..backend.tf_import import _read_varint, parse_wire
from .weights import WeightCollection

# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------


def _packed_varints(entries) -> List[int]:
    out: List[int] = []
    for wt, v in entries:
        if wt == 2:  # packed
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(x - (1 << 64) if x > (1 << 63) else x)
        else:
            out.append(v - (1 << 64) if v > (1 << 63) else v)
    return out


def _parse_blob(buf: bytes) -> np.ndarray:
    f = parse_wire(buf)
    floats: List[float] = []
    data = f.get(5, [])
    arrs = []
    for wt, v in data:
        if wt == 2:  # packed floats
            arrs.append(np.frombuffer(v, dtype="<f4"))
        else:  # individual fixed32
            arrs.append(np.array([struct.unpack("<f", v)[0]], np.float32))
    if arrs:
        arr = np.concatenate(arrs).astype(np.float32)
    elif 8 in f:  # double_data
        darrs = [np.frombuffer(v, dtype="<f8") for wt, v in f[8] if wt == 2]
        arr = (np.concatenate(darrs) if darrs else
               np.array([], np.float64)).astype(np.float32)
    else:
        arr = np.array([], np.float32)
    # shape: BlobShape (field 7) wins; else legacy num/channels/height/width
    if 7 in f:
        dims = _packed_varints(parse_wire(f[7][-1][1]).get(1, []))
    else:
        legacy = [f.get(i) for i in (1, 2, 3, 4)]
        dims = [v[-1][1] for v in legacy if v is not None]
        # Caffe keeps legacy blobs 4-D (Blob::FromProto); only pure VECTORS
        # ((1,1,1,N) biases) canonicalize to (N,). Stripping leading 1s from
        # anything wider would corrupt e.g. a num_output=1 conv (1,C,H,W) —
        # layer-aware reshaping happens in caffe_compat, which knows types.
        if len(dims) > 1 and int(np.prod(dims[:-1])) == 1:
            dims = dims[-1:]
    if dims:
        if int(np.prod(dims)) != arr.size:
            raise ValueError(f"blob shape {dims} != {arr.size} values")
        arr = arr.reshape(dims)
    return arr


def load_caffemodel(data: bytes) -> WeightCollection:
    """Binary NetParameter -> WeightCollection (Caffe blob layouts).
    Parameter-free layers (ReLU, Pooling, ...) carry no blobs and are
    omitted, mirroring reference getWeights' per-layer blob copy
    (CaffeNet.scala:123-137)."""
    f = parse_wire(data)
    weights: Dict[str, List[np.ndarray]] = {}
    order: List[str] = []
    # new-style `layer` (100) preferred; fall back to V1 `layers` (2)
    for field_no, name_no, blob_no in ((100, 1, 7), (2, 4, 6)):
        for _, layer_buf in f.get(field_no, []):
            lf = parse_wire(layer_buf)
            name_entries = lf.get(name_no)
            if not name_entries:
                continue
            name = name_entries[-1][1].decode("utf-8", "replace")
            blobs = [_parse_blob(b) for _, b in lf.get(blob_no, [])]
            if not blobs:
                continue
            if name in weights:
                continue  # layer field preferred over layers duplicate
            weights[name] = blobs
            order.append(name)
        if weights:
            break
    if not weights:
        raise ValueError("no parametrized layers found in NetParameter "
                         "(not a .caffemodel, or weights-free net)")
    return WeightCollection(weights, order)


def load_caffemodel_file(path: str) -> WeightCollection:
    with open(path, "rb") as fh:
        return load_caffemodel(fh.read())


# ---------------------------------------------------------------------------
# Encode
# ---------------------------------------------------------------------------


def _varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field_no: int, wire_type: int) -> bytes:
    return _varint((field_no << 3) | wire_type)


def _len_delim(field_no: int, payload: bytes) -> bytes:
    return _tag(field_no, 2) + _varint(len(payload)) + payload


def _encode_blob(arr: np.ndarray) -> bytes:
    dims = b"".join(_varint(int(d)) for d in arr.shape)
    blob_shape = _len_delim(7, _len_delim(1, dims))  # BlobShape{dim packed}
    data = arr.astype("<f4").tobytes()               # packed float data=5
    return _len_delim(5, data) + blob_shape


def _encode_layer(name: str, blobs: List[np.ndarray]) -> bytes:
    payload = _len_delim(1, name.encode()) + _len_delim(2, b"Parameter")
    for b in blobs:
        payload += _len_delim(7, _encode_blob(b))
    return payload


def save_caffemodel(coll: WeightCollection, path: str,
                    net_name: str = "sparknet_tpu") -> None:
    """WeightCollection -> binary NetParameter file readable by Caffe's
    CopyTrainedLayersFrom (blob matching in Caffe is BY LAYER NAME, so the
    layer `type` here is cosmetic)."""
    out = _len_delim(1, net_name.encode())
    for name in coll.layer_names:
        out += _len_delim(100, _encode_layer(name, coll[name]))
    with open(path, "wb") as fh:
        fh.write(out)
