"""JAX implementations of the Caffe layer set, NHWC / TPU-first.

Each layer type provides:
  - `init_<type>(key, layer, in_shapes) -> params dict` (parametric layers)
  - `apply_<type>(layer, params, inputs, ctx) -> outputs tuple`
  - `infer_<type>(layer, in_shapes) -> out_shapes tuple`

Layout: image tensors are NHWC on device (TPU-native minor-dim = channels →
lanes). Parameter storage is also TPU-first: conv weights HWIO, inner-product
weights (in, out). Caffe-layout import/export (OIHW, (out, in) with
NCHW-flatten ordering) lives in `sparknet_tpu.model.caffe_compat` so that
`.caffemodel`-style weights round-trip exactly.

Semantics parity notes are per-layer, citing the reference's model zoo usage
(files under /root/reference/models/) since the actual kernels lived in
native Caffe (see reference `libs/CaffeNet.scala:91,118`).
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.pooling import caffe_pool_output_size, global_pool2d, pool2d
from ..ops.lrn import lrn as lrn_op
from .. import precision
from .quant import QuantConfig
from .spec import Filler, LayerSpec

Params = Dict[str, jnp.ndarray]


def tp_shards_layer(layer: "LayerSpec", tp_size: int) -> bool:
    """THE tensor-parallel sharding convention, shared by the forward pass
    (ApplyCtx.tp_shards) and the trainer's state construction
    (ParallelTrainer._tp_sharded_layers): an InnerProduct layer is
    column-sharded iff tp_size divides its num_output; everything else is
    replicated across the model axis."""
    return (tp_size > 1 and layer.type == "InnerProduct"
            and layer.inner_product.num_output % tp_size == 0)


@dataclasses.dataclass(frozen=True)
class OpsImpl:
    """Kernel-implementation selection for the ops the layer IR routes
    through hand-written Pallas TPU kernels (RunConfig.lrn_impl /
    pool_impl surface these as config knobs; ApplyCtx threads them to the
    layer applications).

    lrn:  "auto" (Pallas on TPU, fused-elementwise elsewhere), "pallas",
          "fused", or "window" (the XLA reduce_window fallback).
    pool: "auto" (Pallas MAX-pool backward on TPU when the shape gate
          passes, XLA select-and-scatter elsewhere), "pallas", or "xla".
          Default "xla": the last measured TPU A/B (r3) had the kernel
          LOSING 10% end to end; "auto" is the opt-in re-tested by the
          bench.py --mfu row pair — flip the default once BENCH_r06's
          TPU rows justify it (PERF.md §r6 Status).
    interpret: run the Pallas kernels under the Pallas INTERPRETER — the
          CPU parity-test mode ("auto" then resolves to the kernels on
          CPU too, so tier-1 pins the exact layer-path wiring TPU runs).
    """

    lrn: str = "auto"
    pool: str = "xla"
    interpret: bool = False

    def __post_init__(self) -> None:
        # fail at construction (config parse / trainer build), not at the
        # first train_round's trace deep inside jit — same rule PR 6
        # applied to ElasticConfig
        if self.lrn not in ("auto", "pallas", "fused", "window"):
            raise ValueError(f"unknown lrn impl {self.lrn!r}: expected "
                             f"'auto', 'pallas', 'fused', or 'window'")
        if self.pool not in ("auto", "pallas", "xla"):
            raise ValueError(f"unknown pool impl {self.pool!r}: expected "
                             f"'auto', 'pallas', or 'xla'")


@dataclasses.dataclass
class ApplyCtx:
    """Per-call context threaded through layer application.

    tp_axis/tp_size: tensor-parallel mesh axis (inside shard_map). When set,
    InnerProduct layers whose num_output is divisible by tp_size hold COLUMN
    SHARDS of their weights ((in, out/tp_size), bias (out/tp_size,)) and
    all_gather the output features; other layers are replicated
    (`tp_shards_layer` is the single source of truth for the convention).

    ops: kernel-implementation selection (OpsImpl) for LRN / pooling —
    the Pallas-vs-XLA lever of the r6 MFU push.

    quant: serve-side weight-only quantization config (model/quant.py) —
    sets the activation dtype quantized layers dequantize into. Only
    consulted when a layer's params carry the (w_q, w_scale) pair; the
    f32 path never reads it.
    """

    train: bool = False
    rng: Optional[jax.Array] = None
    tp_axis: Optional[str] = None
    tp_size: int = 1
    ops: OpsImpl = dataclasses.field(default_factory=OpsImpl)
    quant: Optional[QuantConfig] = None

    def tp_shards(self, layer: "LayerSpec") -> bool:
        return self.tp_axis is not None and tp_shards_layer(layer,
                                                            self.tp_size)

    def fold(self, name: str) -> jax.Array:
        assert self.rng is not None, "dropout in train mode needs an rng key"
        # crc32, not hash(): Python string hashing is randomized per process,
        # which would make dropout masks irreproducible across runs/hosts.
        return jax.random.fold_in(self.rng, zlib.crc32(name.encode()))


# ---------------------------------------------------------------------------
# Fillers (Caffe FillerParameter semantics)
# ---------------------------------------------------------------------------


def fill(key: jax.Array, filler: Filler, shape: Tuple[int, ...],
         fan_in: int) -> jnp.ndarray:
    t = filler.type
    if t == "constant":
        return jnp.full(shape, filler.value, dtype=jnp.float32)
    if t == "gaussian":
        return filler.mean + filler.std * jax.random.normal(key, shape)
    if t == "xavier":
        scale = float(np.sqrt(3.0 / fan_in))
        return jax.random.uniform(key, shape, minval=-scale, maxval=scale)
    if t == "msra":
        std = float(np.sqrt(2.0 / fan_in))
        return std * jax.random.normal(key, shape)
    if t == "uniform":
        return jax.random.uniform(key, shape, minval=filler.min,
                                  maxval=filler.max)
    raise ValueError(f"unknown filler type {t!r}")


# ---------------------------------------------------------------------------
# Quantized-weight resolution (shared by Convolution / InnerProduct)
# ---------------------------------------------------------------------------


def resolve_weight(params: Params, x: jnp.ndarray, ctx: ApplyCtx):
    """(x, w, matmul precision, preferred_element_type) for either weight
    layout. The f32 path is byte-for-byte the pre-quant code: policy cast
    + policy precision. The quantized path (int8 `w_q` + per-channel
    `w_scale`, installed by the serve ModelManager) dequantizes into the
    quant activation dtype — `w_q * scale` fuses into the consuming
    conv/matmul under XLA — casts the activations to match, and runs
    DEFAULT precision with no forced f32 output (the bf16 MXU fast
    path; accumulation still happens in f32 inside the unit)."""
    if "w_q" in params:
        qc = ctx.quant or QuantConfig()
        dt = qc.act_dtype()
        w = (params["w_q"].astype(jnp.float32)
             * params["w_scale"]).astype(dt)
        return x.astype(dt), w, lax.Precision.DEFAULT, None
    return (precision.cast_in(x), precision.cast_in(params["w"]),
            precision.matmul_precision(), precision.preferred_out())


# ---------------------------------------------------------------------------
# Convolution
# ---------------------------------------------------------------------------


def infer_convolution(layer: LayerSpec, in_shapes):
    (n, h, w, c), = in_shapes[:1]
    p = layer.conv
    oh = (h + 2 * p.pad - p.kernel_size) // p.stride + 1
    ow = (w + 2 * p.pad - p.kernel_size) // p.stride + 1
    return ((n, oh, ow, p.num_output),)


def init_convolution(key, layer: LayerSpec, in_shapes) -> Params:
    p = layer.conv
    c_in = in_shapes[0][-1]
    fan_in = (c_in // p.group) * p.kernel_size * p.kernel_size
    wkey, bkey = jax.random.split(key)
    # HWIO with I = c_in / group (XLA grouped-conv convention).
    w = fill(wkey, p.weight_filler,
             (p.kernel_size, p.kernel_size, c_in // p.group, p.num_output),
             fan_in)
    params = {"w": w}
    if p.bias_term:
        params["b"] = fill(bkey, p.bias_filler, (p.num_output,), fan_in)
    return params


#: grouped-conv lowering: "native" (feature_group_count — the measured
#: default) or "split" (explicit per-group convs + concat) — an A/B lever
#: for the 64%-of-MXU-peak grouped convs (PERF.md r4 experiment)
CONV_GROUP_IMPL = "native"


def _s2d_eligible(p, cin: int) -> bool:
    """Space-to-depth rewrite gate: strided, ungrouped, unpadded convs with
    few input channels — i.e. an image-stem conv like CaffeNet's conv1
    (11x11/4 over RGB), whose 3-channel contraction wastes >90% of the MXU.
    The rewrite is EXACT (see apply_convolution) and measured ~1.45x faster
    for conv1 fwd+wgrad on v5e; convs that are already MXU-friendly
    (cin*s*s > 128) or touch padding/groups keep the direct form."""
    return (p.stride > 1 and p.group == 1 and p.pad == 0
            and cin * p.stride * p.stride <= 128)


def _space_to_depth(x: jnp.ndarray, s: int) -> jnp.ndarray:
    n, h, w, c = x.shape
    return x.reshape(n, h // s, s, w // s, s, c).transpose(
        0, 1, 3, 2, 4, 5).reshape(n, h // s, w // s, s * s * c)


def apply_convolution(layer: LayerSpec, params: Params, inputs, ctx: ApplyCtx):
    p = layer.conv
    (x,) = inputs
    x, w, mm_precision, mm_out = resolve_weight(params, x, ctx)
    cin = x.shape[-1]
    if _s2d_eligible(p, cin):
        # EXACT stride-s -> stride-1 rewrite: group the input into s x s
        # patches on the channel axis and regroup the kernel taps the same
        # way. Transformed output row p' contracts input rows
        # s*p' .. s*p'+K-1 with taps 0..K-1, where taps >= k and image rows
        # >= H are zero padding that only ever meet each other — so the
        # first oh x ow outputs equal the direct conv bit-for-bit (same
        # products, same K-sized contraction tree per channel group). The
        # MXU then contracts s*s*cin channels instead of cin.
        s, k = p.stride, p.kernel_size
        n, h, iw, _ = x.shape
        K = k + ((-k) % s)                # kernel taps padded to s multiple
        oh = (h - k) // s + 1
        ow = (iw - k) // s + 1

        def img_pad(size, out):          # to an s multiple that covers the
            need = max(0, s * (out - 1) + K - size)  # last window's taps
            return need + ((-(size + need)) % s)

        xs = _space_to_depth(
            jnp.pad(x, ((0, 0), (0, img_pad(h, oh)),
                        (0, img_pad(iw, ow)), (0, 0))), s)
        wpad = jnp.pad(w, ((0, K - k), (0, K - k), (0, 0), (0, 0)))
        ks = wpad.reshape(K // s, s, K // s, s, cin, w.shape[-1]).transpose(
            0, 2, 1, 3, 4, 5).reshape(K // s, K // s, s * s * cin,
                                      w.shape[-1])
        y = lax.conv_general_dilated(
            xs, ks, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=mm_precision,
            preferred_element_type=mm_out,
        )[:, :oh, :ow]
    elif p.group > 1 and CONV_GROUP_IMPL == "split":
        # A/B lever (PERF.md r4): grouped convs as EXPLICIT per-group convs
        # + concat, versus XLA's native feature_group_count lowering. Same
        # math (disjoint channel blocks), different schedule.
        xs = jnp.split(x, p.group, axis=-1)
        ws = jnp.split(w, p.group, axis=-1)
        y = jnp.concatenate([
            lax.conv_general_dilated(
                xg, wg, window_strides=(p.stride, p.stride),
                padding=((p.pad, p.pad), (p.pad, p.pad)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=mm_precision,
                preferred_element_type=mm_out)
            for xg, wg in zip(xs, ws)], axis=-1)
    else:
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(p.stride, p.stride),
            padding=((p.pad, p.pad), (p.pad, p.pad)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=p.group,
            precision=mm_precision,
            preferred_element_type=mm_out,
        )
    if "b" in params:
        y = y + params["b"].astype(y.dtype)
    return (y,)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


def infer_pooling(layer: LayerSpec, in_shapes):
    n, h, w, c = in_shapes[0]
    p = layer.pool
    if p.global_pooling:
        return ((n, 1, 1, c),)
    oh = caffe_pool_output_size(h, p.kernel_size, p.stride, p.pad)
    ow = caffe_pool_output_size(w, p.kernel_size, p.stride, p.pad)
    return ((n, oh, ow, c),)


def apply_pooling(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    p = layer.pool
    (x,) = inputs
    if p.global_pooling:
        return (global_pool2d(x, p.pool),)
    return (pool2d(x, p.pool, p.kernel_size, p.stride, p.pad,
                   impl=ctx.ops.pool, interpret=ctx.ops.interpret),)


# ---------------------------------------------------------------------------
# LRN
# ---------------------------------------------------------------------------


def infer_lrn(layer: LayerSpec, in_shapes):
    return (in_shapes[0],)


def apply_lrn(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    p = layer.lrn
    (x,) = inputs
    return (lrn_op(x, p.local_size, alpha=p.alpha, beta=p.beta, k=p.k,
                   impl=ctx.ops.lrn, interpret=ctx.ops.interpret),)


# ---------------------------------------------------------------------------
# ReLU
# ---------------------------------------------------------------------------


def infer_relu(layer: LayerSpec, in_shapes):
    return (in_shapes[0],)


def apply_relu(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    (x,) = inputs
    return (jnp.maximum(x, 0),)


# ---------------------------------------------------------------------------
# InnerProduct
# ---------------------------------------------------------------------------


def _flat_dim(shape: Tuple[int, ...]) -> int:
    d = 1
    for s in shape[1:]:
        d *= s
    return d


def infer_innerproduct(layer: LayerSpec, in_shapes):
    n = in_shapes[0][0]
    return ((n, layer.inner_product.num_output),)


def init_innerproduct(key, layer: LayerSpec, in_shapes) -> Params:
    p = layer.inner_product
    fan_in = _flat_dim(in_shapes[0])
    wkey, bkey = jax.random.split(key)
    # Stored (in, out): feeds the MXU directly as x @ w.
    params = {"w": fill(wkey, p.weight_filler, (fan_in, p.num_output), fan_in)}
    if p.bias_term:
        params["b"] = fill(bkey, p.bias_filler, (p.num_output,), fan_in)
    return params


def apply_innerproduct(layer: LayerSpec, params: Params, inputs, ctx: ApplyCtx):
    (x,) = inputs
    if x.ndim == 4:
        # Caffe flattens NCHW-ordered; transpose so imported Caffe weights
        # (and exported ones) line up element-for-element.
        x = jnp.transpose(x, (0, 3, 1, 2))
    x, w, mm_precision, mm_out = resolve_weight(
        params, x.reshape(x.shape[0], -1), ctx)
    y = jnp.dot(x, w, precision=mm_precision,
                preferred_element_type=mm_out)
    if "b" in params:
        y = y + params["b"].astype(y.dtype)
    if ctx.tp_shards(layer):
        # column-parallel: this device computed features
        # [rank*out/m, (rank+1)*out/m); gather the full feature axis so
        # downstream layers see the logical blob. autodiff turns the gather
        # into the matching reduce-scatter of the cotangent.
        y = jax.lax.all_gather(y, ctx.tp_axis, axis=1, tiled=True)
    return (y,)


# ---------------------------------------------------------------------------
# Softmax / SoftmaxWithLoss / Accuracy
# ---------------------------------------------------------------------------


def infer_softmax(layer: LayerSpec, in_shapes):
    return (in_shapes[0],)


def apply_softmax(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    (x,) = inputs
    # Caffe softmax axis=1 == channel; channels are the last axis here.
    return (jax.nn.softmax(x, axis=-1),)


def _squeeze_label(label: jnp.ndarray) -> jnp.ndarray:
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    return label.astype(jnp.int32)


def infer_softmaxwithloss(layer: LayerSpec, in_shapes):
    return ((),)


def apply_softmaxwithloss(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    logits, label = inputs
    label = _squeeze_label(label)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, label[:, None], axis=-1)[:, 0]
    return (jnp.mean(nll),)


def infer_accuracy(layer: LayerSpec, in_shapes):
    return ((),)


def apply_accuracy(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    logits, label = inputs
    label = _squeeze_label(label)
    k = layer.accuracy.top_k if layer.accuracy else 1
    if k == 1:
        correct = jnp.argmax(logits, axis=-1).astype(jnp.int32) == label
    else:
        topk = lax.top_k(logits, k)[1].astype(jnp.int32)
        correct = jnp.any(topk == label[:, None], axis=-1)
    return (jnp.mean(correct.astype(jnp.float32)),)


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------


def infer_dropout(layer: LayerSpec, in_shapes):
    return (in_shapes[0],)


def apply_dropout(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    (x,) = inputs
    ratio = layer.dropout.dropout_ratio if layer.dropout else 0.5
    if not ctx.train or ratio == 0.0:
        return (x,)
    keep = 1.0 - ratio
    mask = jax.random.bernoulli(ctx.fold(layer.name), keep, x.shape)
    # Caffe scales at train time by 1/keep so eval needs no rescale.
    return (jnp.where(mask, x / keep, 0.0).astype(x.dtype),)


# ---------------------------------------------------------------------------
# Concat / Flatten (small extras used by common Caffe zoo nets)
# ---------------------------------------------------------------------------


def infer_concat(layer: LayerSpec, in_shapes):
    base = list(in_shapes[0])
    base[-1] = sum(s[-1] for s in in_shapes)
    return (tuple(base),)


def apply_concat(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    return (jnp.concatenate(inputs, axis=-1),)


def infer_flatten(layer: LayerSpec, in_shapes):
    return ((in_shapes[0][0], _flat_dim(in_shapes[0])),)


def apply_flatten(layer: LayerSpec, params, inputs, ctx: ApplyCtx):
    (x,) = inputs
    if x.ndim == 4:
        x = jnp.transpose(x, (0, 3, 1, 2))
    return (x.reshape(x.shape[0], -1),)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

LAYER_IMPLS = {
    "Convolution": (init_convolution, apply_convolution, infer_convolution),
    "Pooling": (None, apply_pooling, infer_pooling),
    "LRN": (None, apply_lrn, infer_lrn),
    "ReLU": (None, apply_relu, infer_relu),
    "InnerProduct": (init_innerproduct, apply_innerproduct, infer_innerproduct),
    "Softmax": (None, apply_softmax, infer_softmax),
    "SoftmaxWithLoss": (None, apply_softmaxwithloss, infer_softmaxwithloss),
    "Accuracy": (None, apply_accuracy, infer_accuracy),
    "Dropout": (None, apply_dropout, infer_dropout),
    "Concat": (None, apply_concat, infer_concat),
    "Flatten": (None, apply_flatten, infer_flatten),
}
