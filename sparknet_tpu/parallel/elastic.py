"""Elastic pod membership: survive losing and gaining workers mid-run.

The paper's claim is that τ-interval parameter averaging tolerates slow,
unreliable *communication* (SparkNet, arXiv:1511.06051 — stale averages
still converge); this module extends that tolerance to unreliable
*workers*: spot/preemptible TPU fleets where pod membership changes while
the run is live. Every ingredient already exists in-tree and this layer
only composes them:

  - liveness comes from the per-worker heartbeats the pod-observability
    PR already writes under `RunConfig.pod_dir` (utils/heartbeat.py —
    local/NFS dir or gs://|s3:// prefix, no new channel);
  - dead-vs-slow is `utils.health.liveness_classify` — the SAME rule the
    pod aggregator's straggler naming uses, so a merely-slow worker can
    be flagged a straggler but can never be evicted for slowness;
  - recovery goes through the SHA-256-verified checkpoint store (PR 1/2):
    a resize restores survivors AND joiners from the newest verified
    snapshot, with the momentum policy the r5 A/B validated
    (norm_rescale — scripts/elastic_momentum_ab.py, ELASTIC_AB_r05.json).

`MembershipController` is the host-side decision maker: it polls the
heartbeat prefix, classifies every known worker, and emits a
`MembershipEvent` when the pod's membership actually changes. Deadness is
NEVER declared on a single missed beat: a stale worker becomes SUSPECT
and is re-probed with FULL-JITTER backoff (uniform in
[0, reprobe_backoff_s * 2^k] — the same thundering-herd fix the store
clients got in PR 1); only `dead_probes` consecutive stale probes evict.
A fresh beat at any point clears the suspicion. A worker that said
status="done" left gracefully and is removed without probing.

The train loop (apps/train_loop.py) consumes events at the τ boundary —
the only point where every worker's params are synchronized — and drives
the actual resize: checkpoint, rebuild the compiled round over the new
worker set, restore through the verified snapshot, reshard the data
partitions, continue. Below `min_workers` it checkpoints and raises
`TrainingHealthError` — degrade loudly, never hang.

Multi-host reality: a live JAX pod cannot drop a process from an
initialized runtime, so on process_count > 1 the loop raises
`ElasticRelaunch` (a SystemExit with code 75, EX_TEMPFAIL) — the
launcher (`scripts/tpu_pod_launch.sh watch`) treats that exit as
"membership changed, relaunch at the new size", and the relaunched job
resumes elastically from the newest periodic checkpoint (see
ElasticRelaunch for why the boundary save is skipped there).
Single-process pods (one host owning all chips, and the virtual-mesh
test/bench world) resize live through a fresh boundary checkpoint.
"""
from __future__ import annotations

import random
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.config import ElasticConfig
from ..utils.health import _median, liveness_classify
from ..utils.heartbeat import worker_sort_key  # noqa: F401  (re-export)

#: EX_TEMPFAIL — the launcher's "relaunch me at the new pod size" code
ELASTIC_RELAUNCH_EXIT = 75


class ElasticRelaunch(SystemExit):
    """Raised by the train loop when a membership change cannot be
    applied in-process. Exits with code 75 (EX_TEMPFAIL), which
    `tpu_pod_launch.sh watch` treats as relaunch-don't-strike; the
    relaunched job resumes elastically from the checkpoint store.

    What the resumed state is depends on WHY the resize was impossible:
    a single-host loop that merely lacks a resizable trainer/source
    writes the τ-boundary checkpoint first, so nothing is lost; a
    MULTI-HOST loop raises without the boundary save — membership is
    observed per process (jittered re-probes), so entering the save's
    collective allgather on a decision the other processes may not have
    reached yet could hang the pod, the exact failure elasticity exists
    to prevent — and the relaunch resumes from the newest PERIODIC
    checkpoint instead (up to checkpoint_every rounds are re-trained)."""

    def __init__(self, reason: str):
        super().__init__(ELASTIC_RELAUNCH_EXIT)
        self.reason = reason

    def __str__(self) -> str:  # SystemExit.__str__ would print "75"
        return f"elastic relaunch requested: {self.reason}"


@dataclass(frozen=True)
class MembershipEvent:
    """One membership change, emitted at most once per poll."""

    epoch: int                 # monotonically increasing per change
    alive: Tuple[str, ...]     # the NEW membership (sorted worker ids)
    dead: Tuple[str, ...]      # evicted this event (stale/missing/done)
    joined: Tuple[str, ...]    # adopted this event
    reasons: Dict[str, str]    # worker id -> liveness verdict that did it

    @property
    def n_workers(self) -> int:
        return len(self.alive)


class MembershipController:
    """Declares workers dead or joined from their pod-dir heartbeats.

    `self_worker` is this process's own worker id: it is always a member
    and never probed (its heartbeat is written by the very loop running
    this controller — a self-eviction would be a deadlock with extra
    steps). Initial membership is the DECLARED launch size — worker ids
    0..expected_workers-1 (cfg.expected_workers, defaulting to the
    caller's process count) — plus any extra worker with a fresh beat at
    the first poll. A launched worker that never beats is candidate-dead
    and takes the normal suspect → re-probe → evict path. A leftover
    STALE (or done) heartbeat from a previous incarnation never seeds
    membership just to be evicted — in or out of the declared range:
    excluding an in-range leftover is what stops a relaunched pod from
    re-evicting a permanently-lost worker and exit-75-bouncing forever
    (the worker rejoins through adopt once it beats fresh).

    `now` / `rng` are injectable for deterministic tests; production uses
    the wall clock and the process-global PRNG.
    """

    def __init__(self, cfg: ElasticConfig, pod_dir: str,
                 self_worker: int = 0, expected_workers: Optional[int] = None,
                 registry=None,
                 now: Callable[[], float] = time.time,
                 rng: Optional[random.Random] = None):
        self.cfg = cfg
        self.pod_dir = pod_dir
        self.self_worker = str(int(self_worker))
        self.expected_workers = int(cfg.expected_workers
                                    or expected_workers or 1)
        self._now = now
        self._rng = rng or random.Random()
        self.epoch = 0
        self.members: set = set()
        #: worker id -> {"probes": stale probes so far,
        #:               "next_probe_t": monotonic-ish deadline}
        self._suspect: Dict[str, Dict[str, float]] = {}
        self._denied_logged: set = set()
        self._last_views: Optional[Dict[str, Any]] = None
        self._last_poll_t = 0.0
        self._started = False
        self.audit: deque = deque(maxlen=256)  # event dicts, newest last
        self._g_epoch = self._c_evict = self._c_rejoin = None
        self._g_members = None
        if registry is not None:
            self._g_epoch = registry.gauge(
                "sparknet_pod_membership_epoch",
                "membership epoch (bumped on every evict/join)")
            self._g_epoch.set(0)
            self._g_members = registry.gauge(
                "sparknet_pod_members",
                "workers currently in the elastic membership")
            self._c_evict = registry.counter(
                "sparknet_pod_worker_evictions_total",
                "workers declared dead (stale heartbeat survived the "
                "full-jitter re-probes) or departed (status done)",
                labels=("worker",))
            self._c_rejoin = registry.counter(
                "sparknet_pod_worker_rejoins_total",
                "workers adopted into a live membership",
                labels=("worker",))

    # -- heartbeat views -----------------------------------------------------

    def _read_views(self) -> Dict[str, Optional[Dict[str, Any]]]:
        from ..obs.pod import discover_worker_heartbeats
        from ..utils.heartbeat import read_heartbeat
        return {w: read_heartbeat(p)
                for w, p in discover_worker_heartbeats(self.pod_dir).items()}

    def _verdict(self, hb: Optional[Dict[str, Any]]) -> str:
        return liveness_classify(hb, self.cfg.stale_after_s)

    # -- the poll ------------------------------------------------------------

    def poll(self, rnd: Optional[int] = None,
             force: bool = False) -> Optional[MembershipEvent]:
        """One membership check; returns an event IFF membership changed.
        Rate-limited to `cfg.poll_interval_s` unless `force` (the loop
        calls this once per round; the listing+reads are cheap but a
        bucket prefix should not be listed at kHz)."""
        now = self._now()
        if not force and self._started and \
                now - self._last_poll_t < self.cfg.poll_interval_s:
            return None
        self._last_poll_t = now
        views = self._last_views = self._read_views()
        if not self._started:
            self._started = True
            declared = {str(i) for i in range(self.expected_workers)}
            # a declared worker whose prefix heartbeat already reads
            # stale (or done) is a LEFTOVER of a previous incarnation —
            # it died before this (re)launch. Seeding it anyway would
            # re-evict it and, on a relaunch-only pod, raise exit 75
            # again: an endless relaunch bounce after a permanent
            # preemption. It is NOT seeded; it rejoins through the adopt
            # path the moment it beats fresh. A declared worker with NO
            # heartbeat may merely not have started yet: seeded, and
            # probed as candidate-dead like any other silence.
            leftover = {w for w in declared
                        if self._verdict(views.get(w)) in ("stale", "done")}
            self.members = declared - leftover
            self.members |= {w for w, hb in views.items()
                             if self._verdict(hb) in ("ok", "sick")}
            self.members.add(self.self_worker)
            if leftover:
                self.audit.append({"ts": round(self._now(), 3),
                                   "round": rnd, "epoch": self.epoch,
                                   "seed_leftovers": sorted(leftover)})
            if self._g_members is not None:
                self._g_members.set(len(self.members))
            return None

        dead: List[str] = []
        joined: List[str] = []
        reasons: Dict[str, str] = {}

        for w in sorted(self.members - {self.self_worker}):
            verdict = self._verdict(views.get(w))
            if verdict in ("ok", "sick"):
                self._suspect.pop(w, None)  # fresh beat clears suspicion
                continue
            if verdict == "done":  # graceful goodbye: no probes needed
                self._suspect.pop(w, None)
                dead.append(w)
                reasons[w] = verdict
                continue
            # stale/missing -> suspect with full-jitter re-probe: the
            # first sighting only STARTS the clock; eviction needs
            # cfg.dead_probes consecutive stale re-probes
            s = self._suspect.get(w)
            if s is None:
                self._suspect[w] = {
                    "probes": 0,
                    "next_probe_t": now + self._rng.uniform(
                        0.0, self.cfg.reprobe_backoff_s)}
                continue
            if now < s["next_probe_t"]:
                continue
            s["probes"] += 1
            if s["probes"] >= max(1, self.cfg.dead_probes):
                self._suspect.pop(w, None)
                dead.append(w)
                reasons[w] = verdict
            else:
                s["next_probe_t"] = now + self._rng.uniform(
                    0.0, self.cfg.reprobe_backoff_s * (2 ** s["probes"]))

        for w in sorted(set(views) - self.members):
            if self._verdict(views[w]) not in ("ok", "sick"):
                continue
            if self.cfg.rejoin == "deny":
                if w not in self._denied_logged:
                    self._denied_logged.add(w)
                    import warnings
                    warnings.warn(
                        f"elastic: worker {w} offered a fresh heartbeat "
                        f"but rejoin policy is 'deny' — ignoring",
                        RuntimeWarning)
                continue
            joined.append(w)
            reasons[w] = "joined"

        if not dead and not joined:
            return None
        self.members = (self.members - set(dead)) | set(joined)
        self.epoch += 1
        for w in dead:
            if self._c_evict is not None:
                self._c_evict.inc(worker=w)
        for w in joined:
            self._denied_logged.discard(w)
            if self._c_rejoin is not None:
                self._c_rejoin.inc(worker=w)
        if self._g_epoch is not None:
            self._g_epoch.set(self.epoch)
            self._g_members.set(len(self.members))
        ev = MembershipEvent(epoch=self.epoch,
                             alive=tuple(sorted(self.members,
                                                key=worker_sort_key)),
                             dead=tuple(dead), joined=tuple(joined),
                             reasons=reasons)
        self.audit.append({"ts": round(self._now(), 3), "round": rnd,
                           "epoch": ev.epoch, "dead": list(ev.dead),
                           "joined": list(ev.joined),
                           "reasons": dict(reasons),
                           "n_workers": ev.n_workers})
        return ev

    # -- per-worker τ adaptation --------------------------------------------

    def tau_by_worker(self, tau: int) -> Optional[Dict[str, int]]:
        """Heterogeneous-pod τ budgets (cfg.tau_adapt): worker i gets
        clip(round(tau * median_round_s / round_s_i), tau_min, tau)
        local steps, so a chronically slow worker contributes a shorter
        (but still averaged-in) trajectory instead of stalling the τ
        barrier for everyone. Returns {worker id: tau_i} — the train
        loop expands it to the per-data-group vector the trainer takes
        (a worker may own several device groups). None when adaptation
        is off, the heartbeats carry no round times yet, or every budget
        comes out at the full τ. The median is `utils.health._median` —
        the same estimator the straggler attribution uses, so a 2-worker
        pod's midpoint sits BETWEEN the two times and the slow worker
        actually gets a shorter budget."""
        if not self.cfg.tau_adapt:
            return None
        # reuse the poll's cached views: τ adaptation rides the same
        # rate-limited heartbeat reads, it never adds listing traffic
        views = (self._last_views if self._last_views is not None
                 else self._read_views())
        times: Dict[str, float] = {}
        for w in self.members:
            hb = views.get(w)
            if hb and self._verdict(hb) in ("ok", "sick") and \
                    hb.get("round_s"):
                times[w] = float(hb["round_s"])
        if len(times) < 2:
            return None
        med = _median(sorted(times.values()))
        out: Dict[str, int] = {}
        for w in sorted(self.members, key=worker_sort_key):
            r = times.get(w)
            if not r or r <= 0 or med <= 0:
                out[w] = tau
                continue
            out[w] = int(min(tau, max(self.cfg.tau_min,
                                      round(tau * med / r))))
        return out if any(t != tau for t in out.values()) else None
