"""NamedSharding-founded trainer: logical state over the named (data, model)
mesh (ROADMAP item 2 / the r7 tentpole).

Where ParallelTrainer's TrainState tiles every leaf with a leading
[n_devices] replica axis (device i holds row i of a stacked array), this
trainer keeps the LOGICAL state and lets `NamedSharding` place it
(SNIPPETS.md [2]: Mesh + NamedSharding + shard_map-under-jit):

  params    full logical shapes, replicated across the data axis BY SPEC;
            tensor-parallel layers hold the full logical weight,
            column-sharded over the model axis by spec (`P(None, "model")`)
            instead of pre-split stacked rows — `averaged_params` becomes
            the identity and a checkpoint always stores full weights, which
            is what lets serve load tp>1 checkpoints without reassembly.
  momentum  `[n_data, ...]` rows sharded over the data axis — each data
            group holds exactly its own worker-local velocity (reference
            semantics preserved; same per-device bytes as the replica
            layout, none of its bookkeeping).
  it        one replicated scalar.

The whole round — τ local SGD steps, the weight-averaging pmean, and the
next round's bookkeeping (iteration counter, momentum/storage re-sharding)
— is ONE jitted executable: the τ boundary never round-trips the host.
The per-worker scan runs inside `shard_map` under that jit, and its math
is shared line for line with `ParallelTrainer._round_math`, which is what
lets tests/test_sharded.py pin the two trainers BITWISE on the f32
TINY_MLP round.

state_sharding — the ZeRO-1-style HBM lever (requires tp == 1):

  "replicated"  exact legacy semantics (worker-local momentum, replicated
                params). Per-device state bytes match the shard_map
                trainer's.
  "momentum"    ONE logical momentum, STORED sharded over the data axis
                (per-device momentum bytes / n_data — the ZeRO-1 split of
                optimizer state across data-parallel workers). Each round
                gathers it at the shard_map boundary, runs the τ
                worker-local steps, then averages the workers' velocities
                back into the shard (a pmean the storage constraint lets
                XLA lower as reduce-scatter). Momentum is therefore
                cross-worker AVERAGED once per round — a semantic opt-in:
                the r5 momentum-policy A/B (ELASTIC_AB_r05.json) measured
                plain averaging within sub-point noise of the best policy
                and far ahead of zeroing, and this mode exists exactly for
                nets whose optimizer state does not fit one chip's HBM
                (PR 5's HBM gauges are the decision input, BENCH_r07 the
                proof).
  "full"        "momentum" plus params stored sharded over the data axis
                at rest (gathered per round the same way): at-rest state
                HBM ~ (params + momentum) / n_data per device.

Multi-host: state placement uses `jax.make_array_from_callback`, so every
process must hold the full logical value when constructing/restoring state
(true for init and checkpoint restore). The τ-boundary round itself is
unchanged multi-host SPMD.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..model.layers import OpsImpl, tp_shards_layer
from ..model.net import CompiledNet, PyTree
from ..solver import SolverConfig
from .mesh import DATA_AXIS, MODEL_AXIS, shard_map_unchecked
from .trainer import (ParallelTrainer, TrainState, _find_accuracy_blob,
                      reduce_momentum_rows)

STATE_SHARDINGS = ("replicated", "momentum", "full")


def _put(x, sharding: NamedSharding):
    """Place one logical array. Single-process: device_put. Multi-host:
    every process holds the full logical value and contributes its own
    devices' shards via make_array_from_callback."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


class ShardedTrainer(ParallelTrainer):
    """Drop-in ParallelTrainer replacement with NamedSharding-placed
    logical state (module docstring). The public surface — train_round /
    place_batches / evaluate / resized / adapt_state / averaged_params /
    last_health / compiled_variants — is the ParallelTrainer contract;
    RunConfig.trainer_impl="named" selects it in the train loop."""

    state_layout = "logical"

    def __init__(self, net: CompiledNet, solver_cfg: SolverConfig,
                 mesh: Mesh, tau: int = 10, mode: str = "local_sgd",
                 loss_blob: str = "loss", acc_blob: Optional[str] = None,
                 compute_health: bool = True, elastic_tau: bool = False,
                 donate_batches: bool = False,
                 ops: Optional[OpsImpl] = None,
                 fused_boundary: bool = False,
                 state_sharding: str = "replicated"):
        if state_sharding not in STATE_SHARDINGS:
            raise ValueError(f"unknown state_sharding {state_sharding!r}: "
                             f"expected one of {STATE_SHARDINGS}")
        tp = (int(mesh.shape[MODEL_AXIS])
              if MODEL_AXIS in mesh.axis_names else 1)
        if state_sharding != "replicated" and tp != 1:
            raise NotImplementedError(
                "ZeRO-style state sharding splits over the DATA axis; "
                "combining it with tensor parallelism is future work — "
                "use state_sharding='replicated' with tp > 1")
        self.state_sharding = state_sharding
        super().__init__(net, solver_cfg, mesh, tau=tau, mode=mode,
                         loss_blob=loss_blob, acc_blob=acc_blob,
                         compute_health=compute_health,
                         elastic_tau=elastic_tau,
                         donate_batches=donate_batches, ops=ops,
                         fused_boundary=fused_boundary)

    def _ctor_extra(self) -> Dict[str, Any]:
        return {"state_sharding": self.state_sharding}

    # -- sharding specs ------------------------------------------------------

    def _model_dims(self, lname: str, pname: str, ndim: int) -> tuple:
        """Per-dim model-axis placement of one param leaf: TP layers hold
        the full logical weight column-sharded over the model axis (w on
        its output dim, b on dim 0); everything else replicated."""
        if lname in self._tp_layers:
            axis = 1 if pname == "w" else 0
            return tuple(MODEL_AXIS if i == axis else None
                         for i in range(ndim))
        return (None,) * ndim

    def _zero1_dims(self, dims: tuple, shape: tuple) -> tuple:
        """Insert the DATA axis on the first free dim divisible by n_data
        — the at-rest ZeRO split. An indivisible leaf stays whole (logged
        nowhere: tiny biases dominate that set; the BENCH_r07 measurement
        reports the realized per-device bytes, not the ideal)."""
        for i, (d, s) in enumerate(zip(dims, shape)):
            if d is None and s % self.n_data == 0 and s > 0:
                return dims[:i] + (DATA_AXIS,) + dims[i + 1:]
        return dims

    def _build_specs(self) -> None:
        self._tp_layers = self._tp_sharded_layers()
        shapes = jax.eval_shape(self.net.init_params, jax.random.PRNGKey(0))
        compute, p_store, m_store, m_in, m_out = {}, {}, {}, {}, {}
        for lname, lp in shapes.items():
            compute[lname], p_store[lname] = {}, {}
            m_store[lname], m_in[lname], m_out[lname] = {}, {}, {}
            for pname, leaf in lp.items():
                dims = self._model_dims(lname, pname, len(leaf.shape))
                compute[lname][pname] = P(*dims)
                p_store[lname][pname] = P(*(
                    self._zero1_dims(dims, leaf.shape)
                    if self.state_sharding == "full" else dims))
                if self.state_sharding == "replicated":
                    # [n_data, ...] worker rows, one per data group
                    m_store[lname][pname] = P(DATA_AXIS, *dims)
                    m_in[lname][pname] = P(DATA_AXIS, *dims)
                    m_out[lname][pname] = P(DATA_AXIS, *dims)
                else:
                    # ZeRO-1: logical momentum sharded at rest, gathered
                    # to the full value at the shard_map boundary and
                    # pmean'd (replicated) back out — the jit-level
                    # storage constraint re-shards it
                    m_store[lname][pname] = P(
                        *self._zero1_dims(dims, leaf.shape))
                    m_in[lname][pname] = P(*dims)
                    m_out[lname][pname] = P(*dims)
        self._pspec_compute = compute
        self._pspec_store = p_store
        self._mspec_store = m_store
        self._mspec_in = m_in
        self._mspec_out = m_out

    def _store_shardings(self) -> TrainState:
        """Per-leaf storage NamedShardings as a TrainState of trees."""
        sh = lambda spec: NamedSharding(self.mesh, spec)  # noqa: E731
        return TrainState(
            params=jax.tree.map(sh, self._pspec_store,
                                is_leaf=lambda x: isinstance(x, P)),
            momentum=jax.tree.map(sh, self._mspec_store,
                                  is_leaf=lambda x: isinstance(x, P)),
            it=sh(P()))

    # -- compiled round ------------------------------------------------------

    def _compile(self) -> None:
        self._build_specs()
        state_in = TrainState(params=self._pspec_compute,
                              momentum=self._mspec_in, it=P())
        state_out = TrainState(params=self._pspec_compute,
                               momentum=self._mspec_out, it=P())
        extra_specs = (P(),) if self.elastic_tau else ()
        # sync_sgd: every worker applies the same pmean'd gradient to the
        # same params, so the output params ARE replicated — but they mix
        # with the device-varying momentum rows, which shard_map's
        # replication tracker cannot see through. The values are equal by
        # construction (classic synchronous SGD); check off, like the
        # Pallas case.
        smap = (shard_map_unchecked if self.mode == "sync_sgd"
                else self._smap)
        smapped = smap(
            self._round_impl, mesh=self.mesh,
            in_specs=(state_in, P(None, DATA_AXIS), P(DATA_AXIS), P())
            + extra_specs,
            out_specs=(state_out, P(), self._health_specs()))
        if self.state_sharding == "replicated":
            # compute layout == storage layout: no constraint, and the
            # traced program stays the shared round math verbatim (the
            # bitwise-parity pin against ParallelTrainer depends on it)
            round_fn = smapped
        else:
            store = self._store_shardings()

            def round_fn(state, batches, rngs, lr_scale, *extra):
                new_state, loss, health = smapped(state, batches, rngs,
                                                  lr_scale, *extra)
                # re-shard to the at-rest ZeRO layout INSIDE the jit: the
                # boundary pmean + this constraint is the reduce-scatter;
                # state never materializes unsharded between rounds
                new_state = jax.tree.map(
                    lax.with_sharding_constraint, new_state, store)
                return new_state, loss, health

        self._round = jax.jit(
            round_fn, donate_argnums=(0, 1) if self.donate_batches
            else (0,))
        self._eval = jax.jit(
            self._smap(self._eval_impl, mesh=self.mesh,
                       in_specs=(self._pspec_compute, P(DATA_AXIS)),
                       out_specs=P()))

    def _round_impl(self, state: TrainState, batches, rng, lr_scale,
                    tau_vec=None):
        # per-device views: params are the logical value (TP: this rank's
        # column shard) with NO replica axis to squeeze; momentum is this
        # worker's [1, ...] row (replicated mode) or the gathered logical
        # momentum (ZeRO modes)
        params = state.params
        momentum = (jax.tree.map(lambda x: x[0], state.momentum)
                    if self.state_sharding == "replicated"
                    else state.momentum)
        it = state.it
        rng = rng[0]
        my_tau = (tau_vec[lax.axis_index(DATA_AXIS)]
                  if tau_vec is not None else None)
        params, sstate, mean_loss, health = self._round_math(
            params, momentum, it, batches, rng, lr_scale, my_tau)
        mom = sstate.momentum
        if self.state_sharding == "replicated":
            mom = jax.tree.map(lambda x: x[None], mom)
        else:
            # ZeRO-1 semantic: the workers' post-round velocities average
            # into the ONE logical momentum (replicated here; the jit-level
            # storage constraint shards it at rest)
            mom = lax.pmean(mom, DATA_AXIS)
        return (TrainState(params=params, momentum=mom, it=sstate.it),
                mean_loss, health)

    def _eval_impl(self, params, batch):
        blobs = self.net.apply(params, batch, train=False,
                               tp_axis=self._tp_axis, tp_size=self.tp,
                               ops=self.ops)
        acc_blob = self.acc_blob or _find_accuracy_blob(self.net)
        n = next(iter(batch.values())).shape[0]
        correct = blobs[acc_blob] * n
        total_correct = lax.psum(correct, DATA_AXIS)
        total_n = lax.psum(jnp.asarray(n, jnp.float32), DATA_AXIS)
        acc = total_correct / total_n
        if self._tp_axis is not None:
            acc = lax.pmean(acc, self._tp_axis)  # replicas agree
        return acc

    # -- state construction --------------------------------------------------

    def _momentum_rows(self, mom: PyTree, params: PyTree,
                       policy: str = "norm_rescale") -> PyTree:
        """Normalize an incoming momentum tree to THIS trainer's layout.
        A leaf with one more dim than its param is a per-worker row stack:
        kept exactly when it matches n_data (replicated mode), else
        policy-reduced (reduce_momentum_rows). A logical leaf broadcasts
        to rows (replicated) or passes through (ZeRO modes)."""

        def adapt(lname, pname, m):
            m = np.asarray(m)
            p_ndim = len(np.shape(params[lname][pname]))
            rows = m if m.ndim == p_ndim + 1 else None
            if self.state_sharding == "replicated":
                if rows is not None and rows.shape[0] == self.n_data:
                    return jnp.asarray(rows)
                if rows is not None:
                    m = reduce_momentum_rows(rows, policy)
                return jnp.broadcast_to(
                    jnp.asarray(m)[None], (self.n_data,) + m.shape)
            if rows is not None:
                m = reduce_momentum_rows(rows, policy)
            return jnp.asarray(m)

        return {l: {p: adapt(l, p, m) for p, m in lp.items()}
                for l, lp in mom.items()}

    def state_from_params(self, params: PyTree,
                          momentum: Optional[PyTree] = None,
                          it: int = 0) -> TrainState:
        """Build device state from ONE logical params copy. `momentum`
        may be a logical tree (broadcast per the layout), a [n_data]-row
        stack, or None (zeros)."""
        params = {l: {p: jnp.asarray(x) for p, x in lp.items()}
                  for l, lp in params.items()}
        vdt = jnp.dtype(self.solver.cfg.velocity_dtype)
        if momentum is None:
            zeros = jax.tree.map(lambda w: jnp.zeros(w.shape, vdt), params)
            momentum = (jax.tree.map(
                lambda z: jnp.broadcast_to(z[None],
                                           (self.n_data,) + z.shape), zeros)
                if self.state_sharding == "replicated" else zeros)
        else:
            momentum = self._momentum_rows(momentum, params)
        return self.place(TrainState(
            params=params, momentum=momentum,
            it=jnp.asarray(int(it), jnp.int32)))

    def place(self, state: TrainState) -> TrainState:
        """Place a (possibly host) logical-layout TrainState onto the
        mesh's storage shardings. Casts momentum to the configured
        velocity dtype (same rule as ParallelTrainer.place)."""
        vdt = jnp.dtype(self.solver.cfg.velocity_dtype)
        if any(x.dtype != vdt for x in jax.tree.leaves(state.momentum)):
            state = dataclasses.replace(
                state, momentum=jax.tree.map(
                    lambda x: jnp.asarray(x).astype(vdt)
                    if x.dtype != vdt else x, state.momentum))
        store = self._store_shardings()
        return jax.tree.map(_put, state, store)

    def averaged_params(self, state: TrainState) -> PyTree:
        """The logical params ARE the single synchronized copy — no
        replica row to select, and under TP the NamedSharding-placed
        leaves are logically full already (materializing one gathers its
        column shards)."""
        return state.params

    def adapt_state(self, flat: Dict[str, np.ndarray], old_tp: int = 1,
                    momentum_policy: str = "norm_rescale",
                    old_layout: str = "replica") -> TrainState:
        """Resume from a flat checkpoint taken on ANY topology/layout.

        `old_layout="replica"`: the shard_map trainer's [old_n_devices]
        leading-axis layout — params take data group 0's (reassembled
        across old TP column shards) copy, momentum rows collapse to one
        per old data group. `"logical"`: this trainer's own layout —
        params as stored; momentum rows or logical per the saved
        state_sharding. Either way `_momentum_rows` then maps the rows to
        THIS trainer's layout: exact when the data-group count is
        unchanged (replicated mode), policy-reconstructed otherwise
        (`momentum_policy`, the r5 A/B knob)."""
        old_tp_layers = {l.name for l in self.net.spec.layers
                         if tp_shards_layer(l, old_tp)}
        params: PyTree = {}
        momentum: PyTree = {}
        it = 0
        for key, arr in flat.items():
            parts = key.split("/")
            if parts[0] == "it":
                it = int(np.asarray(arr).reshape(-1)[0])
                continue
            kind, lname, pname = parts
            arr = np.asarray(arr)
            if old_layout == "replica":
                # [old_n_devices, ...] rows, device d = (data d//tp,
                # model d%tp): params take data group 0's copy (post-
                # round replicas are identical), reassembled across the
                # old model ranks' column shards; momentum collapses to
                # one logical row PER old data group
                axis = 1 if pname == "w" else 0
                if kind == "params":
                    if lname in old_tp_layers:
                        arr = np.concatenate(
                            [arr[j] for j in range(old_tp)], axis=axis)
                    else:
                        arr = arr[0]
                elif lname in old_tp_layers:
                    groups = arr.reshape((-1, old_tp) + arr.shape[1:])
                    arr = np.concatenate(
                        [groups[:, j] for j in range(old_tp)],
                        axis=axis + 1)  # +1: leading data-group dim
            (params if kind == "params"
             else momentum).setdefault(lname, {})[pname] = arr
        if not momentum:
            return self.state_from_params(params, it=it)
        return self.place(TrainState(
            params={l: {p: jnp.asarray(x) for p, x in lp.items()}
                    for l, lp in params.items()},
            momentum=self._momentum_rows(momentum, params,
                                         policy=momentum_policy),
            it=jnp.asarray(int(it), jnp.int32)))

    def adapt_live(self, state: TrainState,
                   momentum_policy: str = "norm_rescale") -> TrainState:
        """Elastic resize as RE-PLACEMENT: adopt the PREVIOUS logical-
        layout trainer's live state onto THIS trainer's mesh without the
        checkpoint round-trip the replica layout needs (its stacked rows
        are keyed to the old device count; logical params are topology-
        free). Params move exactly; momentum rows map through
        `_momentum_rows` (exact when the data-group count is unchanged,
        policy-reconstructed otherwise — same rule as adapt_state)."""
        params = jax.tree.map(np.asarray, state.params)
        momentum = jax.tree.map(np.asarray, state.momentum)
        it = int(np.asarray(state.it).reshape(-1)[0])
        return self.place(TrainState(
            params={l: {p: jnp.asarray(x) for p, x in lp.items()}
                    for l, lp in params.items()},
            momentum=self._momentum_rows(momentum, params,
                                         policy=momentum_policy),
            it=jnp.asarray(it, jnp.int32)))
