"""Distributed data-parallel trainer: τ-local-step parameter averaging on-mesh.

This is the TPU-native re-design of the reference's whole training loop
(reference `apps/CifarApp.scala:100-149`):

    reference (Spark)                        here (one XLA program)
    -----------------------------------     ---------------------------------
    sc.broadcast(netWeights)            →   nothing: params live per-device
    foreach{ setWeights(bcast.value) }  →   (already there after pmean)
    foreachPartition{ τ × solver.step } →   lax.scan of τ jitted SGD steps
    map(getWeights).reduce(add)         →   lax.pmean over the mesh axis
    netWeights.scalarDivide(n) (driver) →   (pmean is already the mean)

Semantics preserved exactly (SURVEY.md §7 "hard parts" #2):
  - τ local SGD steps between averagings, each worker on its own data shard;
  - only the *net weights* are averaged; solver momentum stays worker-local
    and stale across syncs (reference `libs/CaffeNet.scala:123-137` — only
    net blobs cross the wire);
  - τ=1 `sync_sgd` mode averages gradients instead: classic synchronous SGD.

State layout: every leaf of params/momentum carries a leading device axis of
size mesh.n_devices, sharded over the data axis — i.e. each device holds
exactly its own (possibly diverged) replica. After a round the replicas are
numerically identical, but keeping the axis makes divergence-during-τ a
first-class, inspectable thing instead of hidden executor state.

The whole round (τ steps + averaging) is ONE compiled executable: no host
round-trips, weights never leave the devices, the driver only gets scalars.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..model.net import CompiledNet, PyTree
from ..solver import SgdSolver, SolverConfig, SolverState
from .mesh import (DATA_AXIS, local_device_rows, place_global_state,
                   put_device_axis)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """Replicated-per-device training state. Leaves have a leading
    [n_devices] axis sharded over the data mesh axis."""

    params: PyTree
    momentum: PyTree
    it: jnp.ndarray  # [n_devices] int32 (same value everywhere)


class ParallelTrainer:
    """Data-parallel trainer over a 1-D (data,) mesh.

    mode: "local_sgd" (τ steps then weight pmean — the reference's scheme) or
          "sync_sgd" (per-step gradient pmean, τ must be 1).
    """

    def __init__(self, net: CompiledNet, solver_cfg: SolverConfig, mesh: Mesh,
                 tau: int = 10, mode: str = "local_sgd",
                 loss_blob: str = "loss", acc_blob: Optional[str] = None):
        assert mode in ("local_sgd", "sync_sgd")
        if mode == "sync_sgd":
            assert tau == 1, "sync_sgd averages every step; tau must be 1"
        if solver_cfg.iter_size != 1:
            raise ValueError(
                "iter_size > 1 is a single-net accumulation feature "
                "(SgdSolver.step); in the distributed trainer scale "
                "local_batch or tau instead — failing loudly rather than "
                "silently ignoring it")
        self.net = net
        self.solver = SgdSolver(net, solver_cfg, loss_blob=loss_blob)
        self.mesh = mesh
        self.tau = tau
        self.mode = mode
        self.loss_blob = loss_blob
        self.acc_blob = acc_blob
        self.n_devices = int(np.prod(mesh.devices.shape))
        self.n_local_devices = len(local_device_rows(mesh))

        dev = P(DATA_AXIS)  # leading device axis
        batch_spec = P(None, DATA_AXIS)  # [tau, global_batch, ...] -> shard batch
        state_specs = TrainState(params=dev, momentum=dev, it=dev)

        self._round = jax.jit(
            shard_map(self._round_impl, mesh=mesh,
                      in_specs=(state_specs, batch_spec, P(DATA_AXIS)),
                      out_specs=(state_specs, P())),
            donate_argnums=(0,))
        self._eval = jax.jit(
            shard_map(self._eval_impl, mesh=mesh,
                      in_specs=(dev, P(DATA_AXIS)),
                      out_specs=P()))

    # -- state construction --------------------------------------------------

    def init_state(self, key: jax.Array) -> TrainState:
        """Identical initial params on every device (the reference seeds all
        workers from worker-0's weights, `apps/CifarApp.scala:98`)."""
        return self.state_from_params(self.net.init_params(key))

    def state_from_params(self, params: PyTree) -> TrainState:
        def tile(x):
            return jnp.broadcast_to(x[None], (self.n_devices,) + x.shape)
        zeros = jax.tree.map(jnp.zeros_like, params)
        state = TrainState(params=jax.tree.map(tile, params),
                           momentum=jax.tree.map(tile, zeros),
                           it=jnp.zeros((self.n_devices,), jnp.int32))
        return self.place(state)

    def place(self, state: TrainState) -> TrainState:
        """Re-place a (possibly host/numpy) TrainState onto the mesh sharding
        the jitted round expects — required after checkpoint restore, else
        every subsequent round recompiles for the foreign layout. Leaves
        carry the GLOBAL device axis; under multi-host each process
        contributes its own devices' rows."""
        return place_global_state(state, self.mesh, P(DATA_AXIS))

    def averaged_params(self, state: TrainState) -> PyTree:
        """Single copy of the (already synchronized) params: device 0's."""
        return jax.tree.map(lambda x: x[0], state.params)

    # -- one training round (runs INSIDE shard_map; axis = DATA_AXIS) --------

    def _round_impl(self, state: TrainState, batches, rng):
        # shapes here are per-device: params [1, ...]; batches [tau, local_b, ...]
        params = jax.tree.map(lambda x: x[0], state.params)
        momentum = jax.tree.map(lambda x: x[0], state.momentum)
        it = state.it[0]
        rng = rng[0]

        def local_step(carry, inputs):
            params, sstate = carry
            batch, step_rng = inputs
            if self.mode == "sync_sgd":
                (loss, _), grads = jax.value_and_grad(
                    lambda p: self.net.loss_fn(self.loss_blob)(
                        p, batch, step_rng), has_aux=True)(params)
                grads = lax.pmean(grads, DATA_AXIS)
                loss = lax.pmean(loss, DATA_AXIS)
                params, sstate = self.solver.update(params, sstate, grads)
            else:
                (loss, _), grads = jax.value_and_grad(
                    lambda p: self.net.loss_fn(self.loss_blob)(
                        p, batch, step_rng), has_aux=True)(params)
                params, sstate = self.solver.update(params, sstate, grads)
            return (params, sstate), loss

        step_rngs = jax.random.split(rng, self.tau)
        (params, sstate), losses = lax.scan(
            local_step, (params, SolverState(momentum=momentum, it=it)),
            (batches, step_rngs))

        if self.mode == "local_sgd":
            # THE sync: weight averaging as an in-pod allreduce. Momentum is
            # deliberately NOT averaged (reference parity, SURVEY §7).
            params = lax.pmean(params, DATA_AXIS)
        mean_loss = lax.pmean(jnp.mean(losses), DATA_AXIS)

        new_state = TrainState(
            params=jax.tree.map(lambda x: x[None], params),
            momentum=jax.tree.map(lambda x: x[None], sstate.momentum),
            it=sstate.it[None],
        )
        return new_state, mean_loss

    # -- distributed eval ----------------------------------------------------

    def _eval_impl(self, params, batch):
        params = jax.tree.map(lambda x: x[0], params)
        blobs = self.net.apply(params, batch, train=False)
        acc_blob = self.acc_blob or _find_accuracy_blob(self.net)
        n = next(iter(batch.values())).shape[0]
        correct = blobs[acc_blob] * n
        total_correct = lax.psum(correct, DATA_AXIS)
        total_n = lax.psum(jnp.asarray(n, jnp.float32), DATA_AXIS)
        return total_correct / total_n

    # -- public API ----------------------------------------------------------

    def train_round(self, state: TrainState, batches: Dict[str, np.ndarray],
                    rng: jax.Array) -> Tuple[TrainState, float]:
        """One outer round: τ local steps per device + averaging.

        `batches[input]` has shape [tau, host_batch, ...] with host_batch =
        (locally-addressable devices) × per-device batch; sharded over
        devices along axis 1. Single-process, host_batch == the global
        batch; multi-host, each process passes only its own hosts' examples
        (disjoint data — the reference's per-executor partitions).
        """
        rngs = jax.random.split(rng, self.n_devices)  # same on every host
        rngs = place_global_state(rngs, self.mesh, P(DATA_AXIS))
        new_state, loss = self._round(state, self._shard_batches(batches), rngs)
        return new_state, loss

    def evaluate(self, state: TrainState, batch: Dict[str, np.ndarray]) -> float:
        """Distributed accuracy over one global batch (psum of correct/count —
        reference's eval reduce, `apps/CifarApp.scala:107-124`)."""
        sharded = {
            k: put_device_axis(np.asarray(v), self.mesh, P(DATA_AXIS))
            for k, v in batch.items()}
        return float(self._eval(state.params, sharded))

    def _shard_batches(self, batches):
        from .. import precision

        dt = precision.compute_dtype()
        out = {}
        for k, v in batches.items():
            if hasattr(v, "devices"):  # already device-resident (bench path)
                arr = v
            else:
                arr = np.asarray(v)
                # cast float inputs to the compute dtype on the HOST: the
                # first in-net op would cast anyway (cast_in), so this is
                # value-identical — and it halves the H2D bytes and drops an
                # in-round [tau, B, H, W, C] convert under bfloat16 policy
                if arr.dtype == np.float32 and dt != jnp.float32:
                    arr = arr.astype(dt)
            assert arr.shape[0] == self.tau, (
                f"{k}: leading dim {arr.shape[0]} != tau {self.tau}")
            assert arr.shape[1] % self.n_local_devices == 0, (
                f"{k}: host batch {arr.shape[1]} not divisible by "
                f"{self.n_local_devices} local devices")
            out[k] = put_device_axis(arr, self.mesh, P(None, DATA_AXIS))
        return out


def _find_accuracy_blob(net: CompiledNet) -> str:
    for layer in net.spec.layers:
        if layer.type == "Accuracy":
            return layer.tops[0]
    raise ValueError("net has no Accuracy layer; pass acc_blob=")
