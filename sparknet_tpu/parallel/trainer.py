"""Distributed data-parallel trainer: τ-local-step parameter averaging on-mesh.

This is the TPU-native re-design of the reference's whole training loop
(reference `apps/CifarApp.scala:100-149`):

    reference (Spark)                        here (one XLA program)
    -----------------------------------     ---------------------------------
    sc.broadcast(netWeights)            →   nothing: params live per-device
    foreach{ setWeights(bcast.value) }  →   (already there after pmean)
    foreachPartition{ τ × solver.step } →   lax.scan of τ jitted SGD steps
    map(getWeights).reduce(add)         →   lax.pmean over the mesh axis
    netWeights.scalarDivide(n) (driver) →   (pmean is already the mean)

Semantics preserved exactly (SURVEY.md §7 "hard parts" #2):
  - τ local SGD steps between averagings, each worker on its own data shard;
  - only the *net weights* are averaged; solver momentum stays worker-local
    and stale across syncs (reference `libs/CaffeNet.scala:123-137` — only
    net blobs cross the wire);
  - τ=1 `sync_sgd` mode averages gradients instead: classic synchronous SGD.

State layout: every leaf of params/momentum carries a leading device axis of
size mesh.n_devices, sharded over the data axis — i.e. each device holds
exactly its own (possibly diverged) replica. After a round the replicas are
numerically identical, but keeping the axis makes divergence-during-τ a
first-class, inspectable thing instead of hidden executor state.

The whole round (τ steps + averaging) is ONE compiled executable: no host
round-trips, weights never leave the devices, the driver only gets scalars.
"""
from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..model.layers import OpsImpl, tp_shards_layer
from ..model.net import CompiledNet, PyTree
from ..solver import SgdSolver, SolverConfig, SolverState
from .mesh import (DATA_AXIS, MODEL_AXIS, local_device_rows, make_mesh,
                   place_global_state, put_device_axis, scan_unroll,
                   shard_map, shard_map_unchecked)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """Replicated-per-device training state. Leaves have a leading
    [n_devices] axis sharded over the data mesh axis."""

    params: PyTree
    momentum: PyTree
    it: jnp.ndarray  # [n_devices] int32 (same value everywhere)


class ParallelTrainer:
    """Data-parallel (optionally DPxTP hybrid) trainer.

    mode: "local_sgd" (τ steps then weight pmean — the reference's scheme) or
          "sync_sgd" (per-step gradient pmean, τ must be 1).

    Tensor parallelism (beyond reference parity): pass a 2-D
    ("data", "model") mesh. InnerProduct layers whose num_output is
    divisible by the model-axis size hold column shards of their weights
    (Megatron-style column-parallel + feature all_gather over ICI); conv
    layers are replicated across the model axis. Within a model group every device
    sees the same batch and rng, so replicated params evolve identically;
    weight averaging stays a pmean over the DATA axis only — shard
    identity is preserved. TP is numerically exact: the (data=N, model=M)
    trajectory equals the (data=N) one (oracle-tested).
    """

    def __init__(self, net: CompiledNet, solver_cfg: SolverConfig, mesh: Mesh,
                 tau: int = 10, mode: str = "local_sgd",
                 loss_blob: str = "loss", acc_blob: Optional[str] = None,
                 compute_health: bool = True, elastic_tau: bool = False,
                 donate_batches: bool = False,
                 ops: Optional[OpsImpl] = None,
                 fused_boundary: bool = False):
        assert mode in ("local_sgd", "sync_sgd")
        if mode == "sync_sgd":
            assert tau == 1, "sync_sgd averages every step; tau must be 1"
        if elastic_tau and mode != "local_sgd":
            raise ValueError("elastic_tau (per-worker local steps) only "
                             "makes sense in local_sgd mode")
        if solver_cfg.iter_size != 1:
            raise ValueError(
                "iter_size > 1 is a single-net accumulation feature "
                "(SgdSolver.step); in the distributed trainer scale "
                "local_batch or tau instead — failing loudly rather than "
                "silently ignoring it")
        assert set(mesh.axis_names) <= {DATA_AXIS, MODEL_AXIS}, (
            f"ParallelTrainer meshes use ('{DATA_AXIS}',) or "
            f"('{DATA_AXIS}', '{MODEL_AXIS}'), got {mesh.axis_names}")
        assert DATA_AXIS in mesh.axis_names, mesh.axis_names
        self.net = net
        self.solver = SgdSolver(net, solver_cfg, loss_blob=loss_blob)
        self.mesh = mesh
        self.tau = tau
        self.mode = mode
        self.loss_blob = loss_blob
        self.acc_blob = acc_blob
        self.n_devices = int(np.prod(mesh.devices.shape))
        self.n_local_devices = len(local_device_rows(mesh))
        self.tp = (int(mesh.shape[MODEL_AXIS])
                   if MODEL_AXIS in mesh.axis_names else 1)
        self.n_data = self.n_devices // self.tp
        self._tp_axis = MODEL_AXIS if self.tp > 1 else None
        if self.tp > 1 and jax.process_count() > 1:
            raise NotImplementedError(
                "multi-host TP: per-host rng/data row slicing assumes a "
                "1-D data mesh — keep the model axis within one host")

        # leading device axis covers the WHOLE mesh (data-major, model-minor
        # — matches mesh.devices.flat for a ("data","model") mesh)
        dev = (P((DATA_AXIS, MODEL_AXIS)) if self.tp > 1 else P(DATA_AXIS))
        self._dev_spec = dev

        # compute_health=False compiles the ORIGINAL round — no isfinite
        # passes over the state, no per-step grad-norm reduction, no extra
        # scalar collectives (for runs that disable the supervisor, e.g.
        # deliberate-divergence fixtures or wire-byte-pinned benchmarks)
        self.compute_health = bool(compute_health)
        # elastic_tau compiles the round with ONE extra traced input: a
        # replicated [n_data] int32 vector of per-worker local-step
        # budgets (heterogeneous pods — the elastic layer shortens a
        # chronically slow worker's τ instead of stalling the barrier).
        # Steps at index >= tau_i are masked no-ops for that worker, so
        # changing the vector NEVER recompiles; a full-τ vector computes
        # the legacy round (the selects pick the updated operand — any
        # residual difference is XLA fusion reassociation at the last
        # ulp, pinned by tests/test_elastic.py). Trainers built without
        # the flag compile the byte-identical legacy round.
        self.elastic_tau = bool(elastic_tau)
        self._tau_vec_dev: Optional[Tuple[Tuple[int, ...], jax.Array]] = None
        #: kernel-implementation selection for LRN/pooling, threaded into
        #: every loss/eval apply (the Pallas-vs-XLA config lever)
        self.ops = ops or OpsImpl()
        # donate_batches additionally donates the [tau, global_batch, ...]
        # input buffers to the compiled round: XLA reuses their HBM for
        # round intermediates instead of holding batch + intermediates
        # live simultaneously (lower peak HBM, less allocator churn). The
        # CONTRACT: the caller hands each round a FRESH batch pytree and
        # never touches it again after train_round — device placement
        # (put_device_axis) always allocates new buffers, so the two-slot
        # rotation the train loop runs (round R donated to the executable
        # while the prefetch thread places round R+1) can never write
        # into a buffer the device still owns. Bench/test callers that
        # re-feed one batches dict across rounds must leave this off.
        self.donate_batches = bool(donate_batches)
        # fused_boundary (r8): peel the FINAL τ step out of the scan so
        # the boundary weight-averaging pmean (and the ZeRO momentum
        # average + at-rest re-shard under ShardedTrainer) traces in the
        # SAME region as the last optimizer update. On TPU the rolled
        # scan's while-loop boundary otherwise forces the full-params
        # all-reduce to start strictly after every local step retired;
        # peeled, XLA's latency-hiding scheduler can overlap the early
        # layers' boundary collective with the tail of the final update.
        # The peeled round runs the SAME ops on the same values in the
        # same order — pinned bitwise against the unfused two-step
        # (scan-then-average) on the TINY_MLP multi-round trajectory
        # under BOTH trainer impls, health scalars included
        # (tests/test_round_pipeline.py), so the shard_map trainer's
        # semantics are preserved. On conv nets the changed program
        # SHAPE can shift XLA's fusion tiling at the last ulp (the same
        # caveat elastic_tau documents) — the loop-level pin holds at
        # ulp tolerance there. Default OFF for direct-API callers (the
        # donate_batches rule); RunConfig.fused_boundary (default ON)
        # flips it for the train loop.
        self.fused_boundary = bool(fused_boundary)
        # a pallas_call traced inside shard_map has no replication rule,
        # so replication checking goes off exactly when the ops config can
        # route LRN/pool to a Pallas kernel on this backend (explicit
        # "pallas", or "auto" where it resolves to the kernel: TPU, or any
        # backend under the interpreter)
        may_pallas = any(
            impl == "pallas"
            or (impl == "auto" and (self.ops.interpret
                                    or jax.default_backend() == "tpu"))
            for impl in (self.ops.lrn, self.ops.pool))
        self._smap = shard_map_unchecked if may_pallas else shard_map
        #: first-call-validated batch signatures: `_check_batch` asserts
        #: the tau/divisibility invariants once per (input, shape, dtype,
        #: placement) and steady-state rounds skip straight past them
        self._batch_sigs: set = set()
        self._local_data_groups = max(1, self.n_local_devices // self.tp)
        #: device scalars from the LAST train_round (fetch with float()):
        #: {"grad_norm": sqrt of the psum over workers of each worker's
        #: WORST-step squared grad norm (max-over-τ runs before the psum,
        #: so the wire cost is one scalar; can exceed the true per-step
        #: global norm by up to sqrt(n_data) when workers peak on
        #: different steps), "nonfinite": count of data groups whose
        #: PRE-AVERAGE local round state (τ losses, pre-pmean params,
        #: momentum) went NaN/Inf — floored at 1.0 when only the
        #: post-average params are poisoned (unattributable), "nonfinite_
        #: by_worker": the [n_data] per-worker breakdown (the same psum
        #: carries a one-hot vector instead of a scalar, so the wire cost
        #: is n_data f32 — attribution of a consistently bad host/feed is
        #: argmax of this vector, logged by the train loop on nonfinite
        #: rounds; all-zero when the anomaly has no owner)}. None when
        #: compute_health=False. Kept OFF the train_round return so
        #: existing (state, loss) callers are untouched; the train loop
        #: reads them at its log_every flush — no extra per-round host
        #: sync.
        self.last_health: Optional[Dict[str, jax.Array]] = None
        self._lr_scale_dev: Optional[Tuple[float, jax.Array]] = None
        #: optional PhaseTimers (utils/metrics.py): when the train loop
        #: installs one, train_round splits its wall time into "h2d" (the
        #: host->device batch placement in _shard_batches) and "dispatch"
        #: (the compiled round's enqueue) — the per-round step-time
        #: breakdown's two finest columns. None costs nothing.
        self.phase_timers = None
        self._compile()

    #: checkpoint/state-layout tag ("replica": every leaf carries the
    #: leading [n_devices] axis; the NamedSharding trainer overrides with
    #: "logical") — stamped into checkpoint `extra` so restore can route
    #: between the layouts
    state_layout = "replica"

    def _health_specs(self):
        return ({"grad_norm": P(), "nonfinite": P(),
                 "nonfinite_by_worker": P()}
                if self.compute_health else {})

    def _compile(self) -> None:
        """Build the jitted round + eval executables. The state lives on
        the mesh as [n_devices]-leading-axis leaves sharded over the whole
        device axis; batches are [tau, global_batch, ...] sharded over
        data only (TP replicas consume identical examples). Subclasses
        with a different state layout override this (and only this plus
        the state-construction methods) — the round MATH is shared via
        `_round_math`."""
        dev = self._dev_spec
        state_specs = TrainState(params=dev, momentum=dev, it=dev)
        extra_specs = (P(),) if self.elastic_tau else ()
        self._round = jax.jit(
            self._smap(self._round_impl, mesh=self.mesh,
                       in_specs=(state_specs, P(None, DATA_AXIS),
                                 P(DATA_AXIS), P()) + extra_specs,
                       out_specs=(state_specs, P(), self._health_specs())),
            donate_argnums=(0, 1) if self.donate_batches else (0,))
        self._eval = jax.jit(
            self._smap(self._eval_impl, mesh=self.mesh,
                       in_specs=(dev, P(DATA_AXIS)),
                       out_specs=P()))

    def compiled_variants(self) -> int:
        """Entries in the jitted round's executable cache — 1 in steady
        state; growth means something keeps retriggering XLA compilation
        (a drifting batch shape/dtype, a layout change). The train loop
        exports this as the `sparknet_train_round_compiled_variants`
        gauge so jit-cache churn shows up on a scrape instead of as an
        unexplained slow round. 0 when this jax version does not expose
        the cache size."""
        try:
            return int(self._round._cache_size())
        except Exception:
            return 0

    # -- state construction --------------------------------------------------

    def _tp_sharded_layers(self) -> set:
        """Layer names whose params are column-sharded across the model
        axis (the shared `tp_shards_layer` convention)."""
        return {l.name for l in self.net.spec.layers
                if tp_shards_layer(l, self.tp)}

    def init_state(self, key: jax.Array) -> TrainState:
        """Identical initial params on every device (the reference seeds all
        workers from worker-0's weights, `apps/CifarApp.scala:98`)."""
        return self.state_from_params(self.net.init_params(key))

    def state_from_params(self, params: PyTree,
                          momentum: Optional[PyTree] = None,
                          it: int = 0) -> TrainState:
        """Build a device TrainState from ONE logical (full, unsharded)
        copy of the params — tiled across data groups and column-sharded
        per the TP convention. `momentum`/`it` seed the optimizer state
        (zeros / 0 for a fresh run; a reassembled average for elastic
        resume)."""
        tp_layers = self._tp_sharded_layers()

        def expand(lname: str, pname: str, x: jnp.ndarray) -> jnp.ndarray:
            x = jnp.asarray(x)
            if lname in tp_layers:
                # device row d = (data d//tp, model d%tp): model rank takes
                # its column shard, repeated across the data groups
                axis = 1 if pname == "w" else 0
                shards = jnp.split(x, self.tp, axis=axis)
                return jnp.stack([shards[d % self.tp]
                                  for d in range(self.n_devices)])
            return jnp.broadcast_to(x[None], (self.n_devices,) + x.shape)

        def expand_tree(tree):
            return {l: {p: expand(l, p, x) for p, x in lp.items()}
                    for l, lp in tree.items()}

        params_dev = expand_tree(params)
        vdt = jnp.dtype(self.solver.cfg.velocity_dtype)
        state = TrainState(
            params=params_dev,
            momentum=(expand_tree(momentum) if momentum is not None
                      else jax.tree.map(
                          lambda w: jnp.zeros(w.shape, vdt), params_dev)),
            it=jnp.full((self.n_devices,), int(it), jnp.int32))
        return self.place(state)

    def adapt_state(self, flat: Dict[str, np.ndarray],
                    old_tp: int = 1,
                    momentum_policy: str = "norm_rescale",
                    old_layout: str = "replica") -> TrainState:
        """ELASTIC resume: rebuild a TrainState for THIS topology from a
        checkpoint taken on a different one (`checkpoint.restore_flat`
        output; keys 'params/<layer>/<blob>', 'momentum/...', 'it').

        `old_layout="logical"` accepts a ShardedTrainer checkpoint
        (logical full params, momentum as [n_data] worker rows or one
        ZeRO-averaged tree): params re-tile exactly; worker momentum rows
        map 1:1 onto devices when the data-group count matches (tp == 1),
        else reconstruct per `momentum_policy`.

        Params are exact — post-round replicas are identical, so data
        group 0's (reassembled) copy IS the model. Momentum is worker-
        local state with no continuity across a topology change (the
        reference had no resume at all, and momentum is stale-by-design
        across rounds anyway, SURVEY §7 hard-part #2); `momentum_policy`
        picks the reconstruction:

          norm_rescale (default)  mean over the old data groups, rescaled
                                  back to the average per-worker norm
                                  (averaging k decorrelated velocities
                                  shrinks the norm ~1/sqrt(k))
          average                 plain mean (the r4 default)
          zero                    fresh zeros

        A/B'd (r5, `scripts/elastic_momentum_ab.py`, ELASTIC_AB_r05.json:
        3 seeds x {8->4, 8->2} x 8 post-resume rounds, TINY_MLP scale):
        norm_rescale edged out averaging in all 6 cells, but the margins
        are sub-point (8->4 max 9.9% vs 10.5%; 8->2 30.8% vs 31.2%) and
        the evidence is small-model-only — treat the two as roughly
        equivalent until the A/B is rerun at CaffeNet shapes
        (scripts/parity_caffenet.py infra exists; ADVICE r5 #5).
        Zero-reset was uniformly WORST (8->4 max 31%, 8->2 38% —
        restarting momentum costs more than averaging's blur), which is
        the one solid conclusion. Measured band for the default:
        <=10% loss inflation at 8->4, <=31% at 8->2, asserted at 15%/40%
        by tests/test_apps.py::test_elastic_resume_momentum_trajectory_band.
        A same-topology pass bypasses the policy entirely: every worker's
        own momentum row is restored as written, so a non-elastic resume
        through this path is exact."""
        assert momentum_policy in ("average", "zero", "norm_rescale"), (
            momentum_policy)
        if old_layout == "logical":
            return self._adapt_logical(flat, momentum_policy)
        old_tp_layers = {l.name for l in self.net.spec.layers
                         if tp_shards_layer(l, old_tp)}

        def reduce_momentum(rows: np.ndarray) -> np.ndarray:
            return reduce_momentum_rows(rows, momentum_policy)

        def reassemble(kind: str, lname: str, pname: str,
                       x: np.ndarray) -> np.ndarray:
            reduce = ((lambda rows: rows[0]) if kind == "params"
                      else reduce_momentum)
            if lname in old_tp_layers:
                axis = 1 if pname == "w" else 0
                return np.concatenate(
                    [reduce(x[j::old_tp]) for j in range(old_tp)],
                    axis=axis)
            return reduce(x)

        old_n_dev = next((np.asarray(a).shape[0] for k, a in flat.items()
                          if not k.startswith("it")), None)
        same_topology = (old_n_dev == self.n_devices and old_tp == self.tp)
        trees: Dict[str, PyTree] = {"params": {}, "momentum": {}}
        it = 0
        for key, arr in flat.items():
            parts = key.split("/")
            if parts[0] == "it":
                it = int(np.asarray(arr).reshape(-1)[0])
                continue
            kind, lname, pname = parts
            # SAME topology: every worker's own momentum row survives as
            # written — no reconstruction policy applies, the resume is
            # exact (the r5 A/B made the elastic policy norm-rescaling,
            # which must never perturb a non-elastic resume) and the
            # reassembly (f32 means + norms over every row) is skipped
            trees[kind].setdefault(lname, {})[pname] = (
                jnp.asarray(arr) if same_topology
                else reassemble(kind, lname, pname, arr))
        if same_topology:
            return self.place(TrainState(
                params=trees["params"], momentum=trees["momentum"],
                it=jnp.full((self.n_devices,), it, jnp.int32)))
        return self.state_from_params(trees["params"],
                                      momentum=trees["momentum"], it=it)

    def _adapt_logical(self, flat: Dict[str, np.ndarray],
                       momentum_policy: str) -> TrainState:
        """adapt_state's logical-layout branch (see its docstring)."""
        params: PyTree = {}
        mom_rows: PyTree = {}
        it = 0
        for key, arr in flat.items():
            parts = key.split("/")
            if parts[0] == "it":
                it = int(np.asarray(arr).reshape(-1)[0])
                continue
            kind, lname, pname = parts
            (params if kind == "params"
             else mom_rows).setdefault(lname, {})[pname] = np.asarray(arr)
        rows_exact = self.tp == 1 and mom_rows and all(
            m.ndim == np.asarray(params[l][p]).ndim + 1
            and m.shape[0] == self.n_devices
            for l, lp in mom_rows.items() for p, m in ((p, lp[p])
                                                       for p in lp))
        if rows_exact:
            # each logical worker row IS that device's momentum (tp == 1:
            # data groups == devices) — the exact, policy-free mapping
            return self.place(TrainState(
                params={l: {p: jnp.broadcast_to(
                    jnp.asarray(x)[None], (self.n_devices,) + x.shape)
                    for p, x in lp.items()} for l, lp in params.items()},
                momentum={l: {p: jnp.asarray(m) for p, m in lp.items()}
                          for l, lp in mom_rows.items()},
                it=jnp.full((self.n_devices,), it, jnp.int32)))
        momentum = {l: {p: (reduce_momentum_rows(m, momentum_policy)
                            if m.ndim == np.asarray(params[l][p]).ndim + 1
                            else m)
                        for p, m in lp.items()}
                    for l, lp in mom_rows.items()} or None
        return self.state_from_params(params, momentum=momentum, it=it)

    def place(self, state: TrainState) -> TrainState:
        """Re-place a (possibly host/numpy) TrainState onto the mesh sharding
        the jitted round expects — required after checkpoint restore, else
        every subsequent round recompiles for the foreign layout. Leaves
        carry the GLOBAL device axis; under multi-host each process
        contributes its own devices' rows.

        The momentum dtype is part of that layout: a checkpoint taken under
        a different SolverConfig.velocity_dtype would otherwise ride along
        uncast and silently override the configured knob for the rest of
        the run, so it is cast here (both the same-topology and the
        elastic-resume path funnel through place)."""
        vdt = jnp.dtype(self.solver.cfg.velocity_dtype)
        if any(x.dtype != vdt for x in jax.tree.leaves(state.momentum)):
            state = dataclasses.replace(
                state, momentum=jax.tree.map(
                    lambda x: jnp.asarray(x).astype(vdt)
                    if x.dtype != vdt else x, state.momentum))
        return place_global_state(state, self.mesh, self._dev_spec)

    def averaged_params(self, state: TrainState) -> PyTree:
        """Single logical copy of the (already synchronized) params. Under
        TP, the column shards of data group 0 are concatenated back into
        full weights (export/checkpoint-compat view)."""
        if self.tp == 1:
            return jax.tree.map(lambda x: x[0], state.params)
        tp_layers = self._tp_sharded_layers()
        out: PyTree = {}
        for lname, lp in state.params.items():
            out[lname] = {}
            for pname, x in lp.items():
                if lname in tp_layers:
                    axis = 1 if pname == "w" else 0
                    out[lname][pname] = jnp.concatenate(
                        [x[j] for j in range(self.tp)], axis=axis)
                else:
                    out[lname][pname] = x[0]
        return out

    # -- one training round (runs INSIDE shard_map; axis = DATA_AXIS) --------

    def _round_impl(self, state: TrainState, batches, rng, lr_scale,
                    tau_vec=None):
        # shapes here are per-device: params [1, ...]; batches [tau, local_b, ...]
        params = jax.tree.map(lambda x: x[0], state.params)
        momentum = jax.tree.map(lambda x: x[0], state.momentum)
        it = state.it[0]
        rng = rng[0]
        # heterogeneous τ: THIS worker's local-step budget out of the
        # replicated per-worker vector (elastic_tau trainers only)
        my_tau = (tau_vec[lax.axis_index(DATA_AXIS)]
                  if tau_vec is not None else None)
        params, sstate, mean_loss, health = self._round_math(
            params, momentum, it, batches, rng, lr_scale, my_tau)
        new_state = TrainState(
            params=jax.tree.map(lambda x: x[None], params),
            momentum=jax.tree.map(lambda x: x[None], sstate.momentum),
            it=sstate.it[None],
        )
        return new_state, mean_loss, health

    def _round_math(self, params, momentum, it, batches, rng, lr_scale,
                    my_tau):
        """The round's MATH on per-device logical views (params/momentum
        without any device axis): τ local SGD steps, weight averaging over
        the data axis, health scalars. Runs INSIDE shard_map; shared
        verbatim by both state layouts (ParallelTrainer's [n_devices]
        replica rows and ShardedTrainer's NamedSharding-placed logical
        state) so the parity suite can pin them bitwise. Returns (params,
        SolverState, mean_loss, health)."""
        loss_fn = self.net.loss_fn(self.loss_blob, tp_axis=self._tp_axis,
                                   tp_size=self.tp, ops=self.ops)
        tp_layers = self._tp_sharded_layers()

        def fix_tp_grads(grads):
            """SPMD autodiff of the replicated-downstream TP program sums
            every replica's (identical) loss: column-shard grads come back
            x tp (the gather's psum-scatter transpose), and each replica's
            backbone grad carries ONLY its own shard's term (x tp). The
            exact logical gradient is shards / tp and backbone pmean'd over
            the model axis (= sum of per-shard terms / tp)."""
            if self._tp_axis is None:
                return grads
            return {l: (jax.tree.map(lambda g: g / self.tp, lp)
                        if l in tp_layers
                        else lax.pmean(lp, self._tp_axis))
                    for l, lp in grads.items()}

        def local_step(carry, inputs):
            params, sstate = carry
            if my_tau is None:
                batch, step_rng = inputs
            else:
                batch, step_rng, step_idx = inputs
            (loss, _), grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch, step_rng),
                has_aux=True)(params)
            grads = fix_tp_grads(grads)
            # health signal: this step's LOCAL squared gradient norm (a
            # per-leaf reduction fused into the compiled step, no host
            # sync). Taken BEFORE the sync_sgd pmean so the later psum
            # yields the true concatenated-across-workers norm in both
            # modes — post-pmean it would inflate by sqrt(n_data).
            grad_sq = (sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                           for g in jax.tree.leaves(grads))
                       if self.compute_health else jnp.zeros((), jnp.float32))
            if self.mode == "sync_sgd":
                grads = lax.pmean(grads, DATA_AXIS)
                loss = lax.pmean(loss, DATA_AXIS)
            new_params, new_sstate = self.solver.update(
                params, sstate, grads, lr_scale=lr_scale)
            if my_tau is not None:
                # heterogeneous τ: steps past THIS worker's budget are
                # no-ops — params/momentum carry through unchanged and
                # the step's loss/grad_sq leave the health statistics
                # (a full-τ vector selects the updated operand on every
                # step, reproducing the unmasked round to the last ulp
                # of XLA's fusion choices). The `it` schedule clock
                # still advances by the nominal τ on every worker: the
                # LR policy must not diverge across the pod.
                active = step_idx < my_tau

                def keep(n, o):
                    return jnp.where(active, n, o)

                new_params = jax.tree.map(keep, new_params, params)
                new_sstate = SolverState(
                    momentum=jax.tree.map(keep, new_sstate.momentum,
                                          sstate.momentum),
                    it=new_sstate.it)
                loss = jnp.where(active, loss, 0.0)
                grad_sq = jnp.where(active, grad_sq, 0.0)
            return (new_params, new_sstate), (loss, grad_sq)

        step_rngs = jax.random.split(rng, self.tau)
        xs = ((batches, step_rngs) if my_tau is None
              else (batches, step_rngs, jnp.arange(self.tau)))
        init = (params, SolverState(momentum=momentum, it=it))
        if self.fused_boundary:
            # fused τ-boundary (ctor comment): τ-1 scanned steps, then
            # the final step PEELED inline so the boundary average below
            # shares its trace region — same math, same order, bitwise
            carry = init
            if self.tau > 1:
                carry, (losses, grad_sqs) = lax.scan(
                    local_step, carry,
                    jax.tree.map(lambda x: x[:-1], xs),
                    unroll=scan_unroll(self.tau - 1))
                carry, (loss_t, gs_t) = local_step(
                    carry, jax.tree.map(lambda x: x[-1], xs))
                losses = jnp.concatenate([losses, loss_t[None]])
                grad_sqs = jnp.concatenate([grad_sqs, gs_t[None]])
            else:  # τ=1: the whole round is scan-free
                carry, (loss_t, gs_t) = local_step(
                    carry, jax.tree.map(lambda x: x[-1], xs))
                losses, grad_sqs = loss_t[None], gs_t[None]
            params, sstate = carry
        else:
            (params, sstate), (losses, grad_sqs) = lax.scan(
                local_step, init, xs, unroll=scan_unroll(self.tau))

        # pre-average view: after the pmean one poisoned worker's NaN is
        # every worker's NaN, so ATTRIBUTION must read the worker-local
        # state (τ-step losses, pre-average params, momentum) first
        local_params = params
        if self.mode == "local_sgd":
            # THE sync: weight averaging as an in-pod allreduce OVER THE
            # DATA AXIS ONLY — under TP each model rank averages its own
            # column shard with its peers. Momentum is deliberately NOT
            # averaged (reference parity, SURVEY §7).
            params = lax.pmean(params, DATA_AXIS)
        if my_tau is None:
            mean_loss = lax.pmean(jnp.mean(losses), DATA_AXIS)
        else:
            # masked steps contributed zero loss: average over the steps
            # THIS worker actually ran, then equal-weight across workers
            # (each worker's own-trajectory mean, the τ-averaging view)
            mean_loss = lax.pmean(
                jnp.sum(losses)
                / jnp.maximum(my_tau.astype(jnp.float32), 1.0),
                DATA_AXIS)

        # -- on-device health scalars (utils/health.py is the host half) --
        # global gradient norm: each worker's WORST-step squared norm,
        # summed across workers (the max-over-τ runs BEFORE the psum so
        # the wire cost is one f32 scalar, tau-invariant — the collective
        # pins in tests/test_collectives.py hold). NaN/Inf detection runs
        # on the round's OUTPUTS (losses + post-averaging params/momentum):
        # a nonfinite gradient necessarily poisons the updated params, so
        # one reduction per leaf per ROUND suffices — no per-step isfinite.
        health = {}
        if self.compute_health:
            grad_norm = jnp.sqrt(lax.psum(jnp.max(grad_sqs), DATA_AXIS))
            # per-worker attribution rides the SAME psum: each data group
            # contributes a one-hot [n_data] row instead of a scalar, so
            # one all-reduce yields both the breakdown (which worker's
            # shard went nonfinite — a consistently bad host/feed shows
            # up as a hot index) and, by summing, the scalar count. The
            # flag is computed over the PRE-average local state (losses,
            # pre-pmean params, worker-local momentum): post-average
            # params are replica-identical, so they can flag a round but
            # never localize it. Wire cost grows 4 B -> 4*n_data B,
            # still noise next to the param all-reduce.
            finite_local = jnp.all(jnp.isfinite(losses))
            for leaf in (jax.tree.leaves(local_params)
                         + jax.tree.leaves(sstate.momentum)):
                finite_local &= jnp.all(
                    jnp.isfinite(leaf.astype(jnp.float32)))
            my_row = (jnp.arange(self.n_data)
                      == lax.axis_index(DATA_AXIS)).astype(jnp.float32)
            # post-average params stay the AUTHORITY for the scalar: a
            # poisoned average over clean local state (an overflow born
            # in the pmean itself) must still trip the supervisor, just
            # without a worker index to blame. The flag rides the SAME
            # psum as slot [n_data] (a separate scalar collective would
            # both add an op to the pinned wire profile and — in
            # sync_sgd, where no pmean touches the params — leave
            # shard_map unable to infer its replication).
            finite_avg = jnp.asarray(True)
            for leaf in jax.tree.leaves(params):
                finite_avg &= jnp.all(
                    jnp.isfinite(leaf.astype(jnp.float32)))
            summed = lax.psum(jnp.concatenate([
                my_row * (~finite_local).astype(jnp.float32),
                (~finite_avg).astype(jnp.float32)[None]]), DATA_AXIS)
            by_worker = summed[:-1]
            nonfinite = jnp.maximum(jnp.sum(by_worker),
                                    jnp.minimum(summed[-1], 1.0))
            if self._tp_axis is not None:
                # numerically (near-)no-ops — TP replicas compute identical
                # flags; clears the model-axis vma so P() typechecks
                grad_norm = lax.pmean(grad_norm, self._tp_axis)
                nonfinite = lax.pmean(nonfinite, self._tp_axis)
                by_worker = lax.pmean(by_worker, self._tp_axis)
            health = {"grad_norm": grad_norm, "nonfinite": nonfinite,
                      "nonfinite_by_worker": by_worker}
        if self._tp_axis is not None:
            # numerically a no-op (TP replicas compute identical losses);
            # clears the model-axis vma so the P() out_spec typechecks
            mean_loss = lax.pmean(mean_loss, self._tp_axis)
        return params, sstate, mean_loss, health

    # -- distributed eval ----------------------------------------------------

    def _eval_impl(self, params, batch):
        params = jax.tree.map(lambda x: x[0], params)
        blobs = self.net.apply(params, batch, train=False,
                               tp_axis=self._tp_axis, tp_size=self.tp,
                               ops=self.ops)
        acc_blob = self.acc_blob or _find_accuracy_blob(self.net)
        n = next(iter(batch.values())).shape[0]
        correct = blobs[acc_blob] * n
        total_correct = lax.psum(correct, DATA_AXIS)
        total_n = lax.psum(jnp.asarray(n, jnp.float32), DATA_AXIS)
        acc = total_correct / total_n
        if self._tp_axis is not None:
            acc = lax.pmean(acc, self._tp_axis)  # replicas agree; clears vma
        return acc

    # -- public API ----------------------------------------------------------

    #: run_loop keys LR backoff on this: the layer-IR solver takes a
    #: runtime lr_scale; the graph backend's in-graph optimizer does not
    supports_lr_scale = True

    def train_round(self, state: TrainState, batches: Dict[str, np.ndarray],
                    rng: jax.Array, lr_scale: float = 1.0,
                    tau_by_worker=None) -> Tuple[TrainState, float]:
        """One outer round: τ local steps per device + averaging.

        `batches[input]` has shape [tau, host_batch, ...] with host_batch =
        (locally-addressable devices) × per-device batch; sharded over
        devices along axis 1. Single-process, host_batch == the global
        batch; multi-host, each process passes only its own hosts' examples
        (disjoint data — the reference's per-executor partitions). Values
        may instead be PRE-PLACED device arrays from `place_batches` (the
        explicit contract documented there): the `h2d` phase then costs
        nothing at dispatch. With `donate_batches`, this call CONSUMES the
        batch buffers — feed fresh ones each round.

        `lr_scale` multiplies the lr-policy rate for this round (health
        supervisor backoff; a traced input, so changing it does not
        recompile). Health scalars from the round land in `last_health`
        as device scalars — see its comment.

        `tau_by_worker` (elastic_tau trainers only): per-data-group
        local-step budgets, clipped to [1, tau] — worker i executes its
        first tau_i scan steps and carries its state unchanged through
        the rest (heterogeneous pods; a traced input like lr_scale, so
        adapting never recompiles). None = full τ everywhere, which is
        numerically identical to a non-elastic trainer's round.
        """
        # one rng row per DATA group, same on every host; TP replicas in a
        # model group share the row (dropout masks must agree on the
        # gathered activations)
        rngs = jax.random.split(rng, self.n_data)
        rngs = place_global_state(rngs, self.mesh, P(DATA_AXIS))
        if self._lr_scale_dev is None or \
                self._lr_scale_dev[0] != float(lr_scale):
            self._lr_scale_dev = (float(lr_scale),
                                  jnp.asarray(lr_scale, jnp.float32))
        if self.elastic_tau:
            vec = (tuple(int(min(self.tau, max(1, t)))
                         for t in tau_by_worker)
                   if tau_by_worker is not None
                   else (self.tau,) * self.n_data)
            assert len(vec) == self.n_data, (
                f"tau_by_worker has {len(vec)} entries for "
                f"{self.n_data} data groups")
            if self._tau_vec_dev is None or self._tau_vec_dev[0] != vec:
                self._tau_vec_dev = (vec, jnp.asarray(vec, jnp.int32))
            extra = (self._tau_vec_dev[1],)
        else:
            if tau_by_worker is not None:
                raise ValueError("tau_by_worker requires a trainer built "
                                 "with elastic_tau=True")
            extra = ()
        timers = self.phase_timers
        if timers is not None:
            with timers.phase("h2d"):
                sharded = self._shard_batches(batches)
            with timers.phase("dispatch"):
                new_state, loss, health = self._round(
                    state, sharded, rngs, self._lr_scale_dev[1], *extra)
        else:
            new_state, loss, health = self._round(
                state, self._shard_batches(batches), rngs,
                self._lr_scale_dev[1], *extra)
        self.last_health = health or None  # {} when compute_health=False
        return new_state, loss

    def resized(self, n_devices: int) -> "ParallelTrainer":
        """A NEW trainer over the first `n_devices` visible devices — the
        elastic resize: same net, solver, τ, mode, and health layout,
        fresh mesh and compiled round. The health psum's
        `[n_data+1]`-vector layout follows the new worker count because
        the round is rebuilt, so attribution indexes always match the
        live membership. The old trainer's executables are dropped with
        the old object. TP pods cannot resize live (the column-shard
        assignment itself would change — relaunch instead)."""
        if self.tp != 1:
            raise NotImplementedError(
                "elastic resize with tensor parallelism: the shard "
                "assignment changes with the mesh — checkpoint and "
                "relaunch at the new size instead")
        return type(self)(
            self.net, self.solver.cfg, make_mesh(n_devices), tau=self.tau,
            mode=self.mode, loss_blob=self.loss_blob, acc_blob=self.acc_blob,
            compute_health=self.compute_health, elastic_tau=self.elastic_tau,
            donate_batches=self.donate_batches, ops=self.ops,
            fused_boundary=self.fused_boundary,
            **self._ctor_extra())

    def _ctor_extra(self) -> Dict[str, Any]:
        """Subclass-specific constructor kwargs `resized()` must carry to
        the replacement trainer (e.g. ShardedTrainer.state_sharding)."""
        return {}

    def evaluate(self, state: TrainState, batch: Dict[str, np.ndarray]) -> float:
        """Distributed accuracy over one global batch (psum of correct/count —
        reference's eval reduce, `apps/CifarApp.scala:107-124`)."""
        from .. import precision

        sharded = {
            k: put_device_axis(np.asarray(v), self.mesh, P(DATA_AXIS))
            for k, v in precision.cast_host_inputs(batch).items()}
        return float(self._eval(state.params, sharded))

    def place_batches(self, batches, compute_dt=None):
        """Pre-place one round's batches on device — the H2D half of the
        round, runnable OFF the dispatch path (the train loop's prefetch
        thread calls this for round R+1 while round R computes, driving
        train_round's `h2d` phase to ~0).

        THE PLACEMENT CONTRACT (train_round / _shard_batches): a batch
        value that is a `jax.Array` is treated as ALREADY PLACED — cast to
        the compute dtype and sharded P(None, data) exactly as this method
        produces — and passes through untouched; anything else is a host
        array [tau, host_batch, ...] that gets cast + placed at dispatch.
        Mixing is allowed per input. `compute_dt` must be passed when
        calling from a worker thread: the precision policy is thread-local
        (same rule as `precision.cast_host_inputs`).

        With `donate_batches`, the returned arrays are CONSUMED by the
        next train_round — place fresh ones each round (placement always
        allocates new device buffers, so a pre-placed round R+1 can never
        alias the donated round-R buffers the device still owns)."""
        from .. import precision

        dt = (compute_dt if compute_dt is not None
              else precision.compute_dtype())
        out = {}
        for k, v in precision.cast_host_inputs(batches, dt).items():
            if isinstance(v, jax.Array) and not isinstance(v, np.ndarray):
                self._check_batch(k, v, placed=True, dt=dt)
                out[k] = v
            else:
                arr = np.asarray(v)
                self._check_batch(k, arr, placed=False)
                # the batch shards over the DATA axis only (TP replicas
                # share rows)
                out[k] = put_device_axis(arr, self.mesh, P(None, DATA_AXIS))
        return out

    def _check_batch(self, k: str, arr, placed: bool, dt=None) -> None:
        """Batch invariants, hoisted to first sight of each (input, shape,
        dtype, placement[, sharding]) signature — steady-state rounds take
        one set lookup instead of re-asserting shapes and re-deriving the
        local-group split every round."""
        sig = (k, tuple(arr.shape), str(arr.dtype), placed,
               str(dt) if placed else None,
               arr.sharding if placed else None)
        if sig in self._batch_sigs:
            return
        assert arr.shape[0] == self.tau, (
            f"{k}: leading dim {arr.shape[0]} != tau {self.tau}")
        if placed:
            # pre-placed arrays carry the GLOBAL batch; they must split
            # over every data group (their sharding was fixed at placement)
            assert arr.shape[1] % max(1, self.n_data) == 0, (
                f"{k}: global batch {arr.shape[1]} not divisible by "
                f"{self.n_data} data-parallel groups")
            # the dtype half of the placement contract, enforced: a float
            # batch a caller placed WITHOUT the compute-dtype cast
            # (cast_host_inputs skips device arrays) would otherwise
            # silently diverge from the host-array path — a second jit
            # executable and non-pinned numerics (same f32/bf16 rule as
            # precision.cast_in)
            if arr.dtype in (jnp.float32, jnp.bfloat16):
                assert arr.dtype == dt, (
                    f"{k}: pre-placed array has dtype {arr.dtype}, but the "
                    f"compute dtype is {dt} — place via place_batches (it "
                    f"casts), or cast before placing")
            # the sharding half of the contract: a caller-placed array must
            # already be P(None, data) over THIS mesh — a plain device_put'd
            # array would pass the shape/dtype checks and then be silently
            # resharded inside every dispatch, a real per-round copy hidden
            # behind the t_h2d_ms ~ 0 the passthrough reports
            want = NamedSharding(self.mesh, P(None, DATA_AXIS))
            assert arr.sharding.is_equivalent_to(want, arr.ndim), (
                f"{k}: pre-placed array sharding {arr.sharding} is not "
                f"P(None, '{DATA_AXIS}') over the trainer mesh — place via "
                f"place_batches")
        else:
            assert arr.shape[1] % self._local_data_groups == 0, (
                f"{k}: host batch {arr.shape[1]} not divisible by "
                f"{self._local_data_groups} local data-parallel groups")
        self._batch_sigs.add(sig)

    def _shard_batches(self, batches):
        return self.place_batches(batches)


def reduce_momentum_rows(rows: np.ndarray, policy: str) -> np.ndarray:
    """Reconstruct ONE momentum from k per-worker velocity rows — the
    elastic-resume reconstruction (see ParallelTrainer.adapt_state for the
    r5 A/B evidence behind the policies). f32 accumulator: a bf16 velocity
    (SolverConfig.velocity_dtype) must not be averaged in bf16."""
    avg = rows.mean(axis=0, dtype=np.float32)
    if policy == "zero":
        return np.zeros_like(avg).astype(rows.dtype)
    if policy == "norm_rescale":
        # averaging k partially-decorrelated velocities shrinks the norm
        # ~1/sqrt(k); rescale the mean back to the average per-worker norm
        # so the first post-resume steps keep their step size
        target = float(np.mean([np.linalg.norm(
            r.astype(np.float32)) for r in rows]))
        cur = float(np.linalg.norm(avg))
        if cur > 0:
            avg = avg * (target / cur)
    return avg.astype(rows.dtype)


def _find_accuracy_blob(net: CompiledNet) -> str:
    for layer in net.spec.layers:
        if layer.type == "Accuracy":
            return layer.tops[0]
    raise ValueError("net has no Accuracy layer; pass acc_blob=")
