"""Device mesh construction + multi-host initialization.

Replaces the reference's entire cluster control plane (Spark driver +
executors + `spark-submit`, reference `apps/CifarApp.scala:31-49`,
`ec2/spark_ec2.py`) with the JAX single-controller model: every host runs the
same program, `jax.distributed.initialize` forms the global runtime, and a
`jax.sharding.Mesh` over all devices is the communication fabric — collectives
ride ICI (and DCN across slices) instead of driver TCP.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: the experimental location
    from jax.experimental.shard_map import shard_map  # noqa: F401

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map_unchecked(f, **kw):
    """`shard_map` with replication checking OFF — required whenever the
    body may trace a `pallas_call`, which has no shard_map replication
    rule (jax's own error message names `check_rep=False` as the
    workaround). Kwarg name varies by jax version: `check_rep`
    (<= 0.5-ish) vs `check_vma` (newer)."""
    try:
        return shard_map(f, check_rep=False, **kw)
    except TypeError:
        return shard_map(f, check_vma=False, **kw)


def axis_size(axis_name: str) -> int:
    """STATIC size of a mesh axis from inside shard_map (usable in
    `range()` / `jnp.arange()`): `lax.axis_size` where it exists (jax >=
    0.4.38-ish), else the axis-env frame, which older jax returns as the
    bare int."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis_name))
    import jax.core as jcore
    frame = jcore.axis_frame(axis_name)
    return int(getattr(frame, "size", frame))


def pvary(tree, axis_names):
    """Mark replicated values device-varying over `axis_names` (shard_map
    vma typing). jax >= 0.9 spells it `lax.pcast`, 0.5-0.8 `lax.pvary`;
    older jax has no vma tracking at all — identity."""
    from jax import lax
    if hasattr(lax, "pcast"):
        return lax.pcast(tree, axis_names, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(tree, axis_names)
    return tree


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = (DATA_AXIS,),
              shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Build a mesh over the first `n_devices` devices (default: all).

    1-D (data,) meshes cover the reference's pure-DP world; pass
    axis_names=("data","model") + shape for DP×TP hybrid layouts.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),) if len(axis_names) == 1 else None
        assert shape is not None, "multi-axis mesh needs an explicit shape"
    arr = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(arr, axis_names=tuple(axis_names))


_COORDINATOR_ENV_HINTS = ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                          "MEGASCALE_COORDINATOR_ADDRESS")


def _multihost_configured() -> bool:
    """True only when the environment describes a >1-host world: an explicit
    coordinator address, or a TPU hostname list with MULTIPLE entries
    (single-host TPU VMs set TPU_WORKER_HOSTNAMES=localhost — that is a
    1-host world and must not trigger distributed init)."""
    if any(os.environ.get(k) for k in _COORDINATOR_ENV_HINTS):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Form the multi-host runtime. Must be called BEFORE any other JAX use
    (backend init pins the process world — do not touch jax.devices() or
    jax.process_count() first).

    Returns True if a multi-host world was formed, False for a deliberate
    single-process run (no coordinator configured). Real initialization
    failures PROPAGATE — a pod run silently degrading to per-host training
    would be wrong results with no error.
    """
    if os.environ.get("SPARKNET_TPU_DIST_INIT"):
        return True
    explicit = coordinator is not None
    configured = explicit or _multihost_configured()
    if not configured:
        return False  # single-process (tests, single TPU VM)
    kwargs = {}
    if explicit:
        kwargs = dict(coordinator_address=coordinator,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
    os.environ["SPARKNET_TPU_DIST_INIT"] = "1"
    return True


def host_id_count() -> Tuple[int, int]:
    """(process_index, process_count): the host-sharding key. The reference's
    analogue was the Spark partition id per executor; here every host runs
    the same program and takes its slice by process index."""
    return jax.process_index(), jax.process_count()


def scan_unroll(length: int) -> int:
    """`unroll=` for a τ/worker scan whose body contains convolutions.

    XLA:CPU executes convolution ops inside a while-loop body on a
    pathologically slow path — measured 26x (r5): a 3-step cifar10_quick
    train scan runs 24.8 s rolled vs 0.95 s fully unrolled on one core,
    while the identical body as a bare jitted step takes 0.51 s. On the
    CPU backend (the virtual-mesh test/CI configuration) fully unroll;
    on TPU the rolled scan compiles faster and runs at the same speed,
    so keep it (partial unrolls don't help: any residual while-loop puts
    every conv back on the slow path)."""
    return length if jax.default_backend() == "cpu" else 1


def local_device_rows(mesh: Mesh) -> list:
    """Positions along the flattened mesh device axis owned by THIS process
    (not assumed contiguous — TPU mesh construction may reorder devices for
    ICI topology)."""
    pi = jax.process_index()
    return [i for i, d in enumerate(mesh.devices.flat)
            if d.process_index == pi]


def put_device_axis(arr, mesh: Mesh, spec: P):
    """Place a host array onto the mesh with `spec`.

    Single-process: plain device_put. Multi-host: `arr` is this process's
    LOCAL slice along the sharded axis and the global array is assembled via
    jax.make_array_from_process_local_data — each host contributes only the
    rows its devices own (disjoint host data, the multi-host data path)."""
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    return jax.make_array_from_process_local_data(sh, np.asarray(arr))


def place_global_state(tree, mesh: Mesh, spec: P):
    """Place a pytree whose leaves carry a leading GLOBAL device axis (shape
    [n_global_devices, ...], identical on every host — e.g. a freshly tiled
    or checkpoint-restored TrainState). Multi-host: each host slices out its
    own devices' rows and contributes only those."""
    if jax.process_count() == 1:
        return jax.device_put(tree, NamedSharding(mesh, spec))
    rows = local_device_rows(mesh)

    def put(x):
        return put_device_axis(np.asarray(x)[rows], mesh, spec)

    return jax.tree.map(put, tree)


def fetch_global(tree):
    """Materialize (possibly multi-host-sharded) arrays as host numpy on
    EVERY process — the collective the checkpoint writer needs (momentum is
    worker-local state, so this is a real allgather, not a replica read).

    Single-process, the device->host copies for ALL leaves are started
    asynchronously FIRST (`copy_to_host_async`), then materialized: the
    transfers overlap each other (and whatever the device is still
    computing) instead of serializing one blocking `np.asarray` per leaf —
    the checkpoint stage-1 fetch is the main beneficiary (BENCH_r07
    non-blocking-collect arm)."""
    if jax.process_count() == 1:
        for leaf in jax.tree.leaves(tree):
            if isinstance(leaf, jax.Array):
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass  # fetch still correct via the blocking asarray
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(tree, tiled=True)


def per_device_state_bytes(state) -> dict:
    """At-rest bytes ONE device holds for this TrainState's params and
    momentum — the HBM ledger the ZeRO state_sharding modes exist to
    shrink (`sharding.shard_shape` is the allocator's view, exact on any
    backend). One definition shared by the BENCH_r07 acceptance ledger
    (bench.py --sharding) and the tier-1 byte pin (tests/test_sharded.py)
    so the two cannot drift."""
    out = {}
    for name, tree in (("params", state.params),
                       ("momentum", state.momentum)):
        out[name] = sum(
            int(np.prod(leaf.sharding.shard_shape(leaf.shape)))
            * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree))
    return out


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int, axis: int = 0) -> NamedSharding:
    spec = [None] * ndim
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))
