"""Device mesh construction + multi-host initialization.

Replaces the reference's entire cluster control plane (Spark driver +
executors + `spark-submit`, reference `apps/CifarApp.scala:31-49`,
`ec2/spark_ec2.py`) with the JAX single-controller model: every host runs the
same program, `jax.distributed.initialize` forms the global runtime, and a
`jax.sharding.Mesh` over all devices is the communication fabric — collectives
ride ICI (and DCN across slices) instead of driver TCP.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: the experimental location
    from jax.experimental.shard_map import shard_map  # noqa: F401

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map_unchecked(f, **kw):
    """`shard_map` with replication checking OFF — required whenever the
    body may trace a `pallas_call`, which has no shard_map replication
    rule (jax's own error message names `check_rep=False` as the
    workaround). Kwarg name varies by jax version: `check_rep`
    (<= 0.5-ish) vs `check_vma` (newer)."""
    try:
        return shard_map(f, check_rep=False, **kw)
    except TypeError:
        return shard_map(f, check_vma=False, **kw)


def axis_size(axis_name: str) -> int:
    """STATIC size of a mesh axis from inside shard_map (usable in
    `range()` / `jnp.arange()`): `lax.axis_size` where it exists (jax >=
    0.4.38-ish), else the axis-env frame, which older jax returns as the
    bare int."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis_name))
    import jax.core as jcore
    frame = jcore.axis_frame(axis_name)
    return int(getattr(frame, "size", frame))


def pvary(tree, axis_names):
    """Mark replicated values device-varying over `axis_names` (shard_map
    vma typing). jax >= 0.9 spells it `lax.pcast`, 0.5-0.8 `lax.pvary`;
    older jax has no vma tracking at all — identity."""
    from jax import lax
    if hasattr(lax, "pcast"):
        return lax.pcast(tree, axis_names, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(tree, axis_names)
    return tree


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = (DATA_AXIS,),
              shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Build a mesh over the first `n_devices` devices (default: all).

    1-D (data,) meshes cover the reference's pure-DP world; pass
    axis_names=("data","model") + shape for DP×TP hybrid layouts.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),) if len(axis_names) == 1 else None
        assert shape is not None, "multi-axis mesh needs an explicit shape"
    arr = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(arr, axis_names=tuple(axis_names))


_COORDINATOR_ENV_HINTS = ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                          "MEGASCALE_COORDINATOR_ADDRESS")


def _multihost_configured() -> bool:
    """True only when the environment describes a >1-host world: an explicit
    coordinator address, or a TPU hostname list with MULTIPLE entries
    (single-host TPU VMs set TPU_WORKER_HOSTNAMES=localhost — that is a
    1-host world and must not trigger distributed init)."""
    if any(os.environ.get(k) for k in _COORDINATOR_ENV_HINTS):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Form the multi-host runtime. Must be called BEFORE any other JAX use
    (backend init pins the process world — do not touch jax.devices() or
    jax.process_count() first).

    Returns True if a multi-host world was formed, False for a deliberate
    single-process run (no coordinator configured). Real initialization
    failures PROPAGATE — a pod run silently degrading to per-host training
    would be wrong results with no error.
    """
    if os.environ.get("SPARKNET_TPU_DIST_INIT"):
        return True
    explicit = coordinator is not None
    configured = explicit or _multihost_configured()
    if not configured:
        return False  # single-process (tests, single TPU VM)
    kwargs = {}
    if explicit:
        kwargs = dict(coordinator_address=coordinator,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
    os.environ["SPARKNET_TPU_DIST_INIT"] = "1"
    return True


def host_id_count() -> Tuple[int, int]:
    """(process_index, process_count): the host-sharding key. The reference's
    analogue was the Spark partition id per executor; here every host runs
    the same program and takes its slice by process index."""
    return jax.process_index(), jax.process_count()


def scan_unroll(length: int) -> int:
    """`unroll=` for a τ/worker scan whose body contains convolutions.

    XLA:CPU executes convolution ops inside a while-loop body on a
    pathologically slow path — measured 26x (r5): a 3-step cifar10_quick
    train scan runs 24.8 s rolled vs 0.95 s fully unrolled on one core,
    while the identical body as a bare jitted step takes 0.51 s. On the
    CPU backend (the virtual-mesh test/CI configuration) fully unroll;
    on TPU the rolled scan compiles faster and runs at the same speed,
    so keep it (partial unrolls don't help: any residual while-loop puts
    every conv back on the slow path)."""
    return length if jax.default_backend() == "cpu" else 1


def local_device_rows(mesh: Mesh) -> list:
    """Positions along the flattened mesh device axis owned by THIS process
    (not assumed contiguous — TPU mesh construction may reorder devices for
    ICI topology)."""
    pi = jax.process_index()
    return [i for i, d in enumerate(mesh.devices.flat)
            if d.process_index == pi]


def put_device_axis(arr, mesh: Mesh, spec: P):
    """Place a host array onto the mesh with `spec`.

    Single-process: plain device_put. Multi-host: `arr` is this process's
    LOCAL slice along the sharded axis and the global array is assembled via
    jax.make_array_from_process_local_data — each host contributes only the
    rows its devices own (disjoint host data, the multi-host data path)."""
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    return jax.make_array_from_process_local_data(sh, np.asarray(arr))


def place_global_state(tree, mesh: Mesh, spec: P):
    """Place a pytree whose leaves carry a leading GLOBAL device axis (shape
    [n_global_devices, ...], identical on every host — e.g. a freshly tiled
    or checkpoint-restored TrainState). Multi-host: each host slices out its
    own devices' rows and contributes only those."""
    if jax.process_count() == 1:
        return jax.device_put(tree, NamedSharding(mesh, spec))
    rows = local_device_rows(mesh)

    def put(x):
        return put_device_axis(np.asarray(x)[rows], mesh, spec)

    return jax.tree.map(put, tree)


def fetch_global(tree):
    """Materialize (possibly multi-host-sharded) arrays as host numpy on
    EVERY process — the collective the checkpoint writer needs (momentum is
    worker-local state, so this is a real allgather, not a replica read).

    Since r8 this is the MONOLITHIC FALLBACK: the default checkpoint
    path is `fetch_state_shards` below, which never materializes the
    full state on any host — each worker fetches only the distinct
    pieces its own devices hold and writes its own shard file. This
    full gather remains for the graph backend, single-device runs, and
    `checkpoint_sharded="off"`.

    Single-process, the device->host copies for ALL leaves are started
    asynchronously FIRST (`copy_to_host_async`), then materialized: the
    transfers overlap each other (and whatever the device is still
    computing) instead of serializing one blocking `np.asarray` per leaf —
    the checkpoint stage-1 fetch is the main beneficiary (BENCH_r07
    non-blocking-collect arm)."""
    if jax.process_count() == 1:
        for leaf in jax.tree.leaves(tree):
            if isinstance(leaf, jax.Array):
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass  # fetch still correct via the blocking asarray
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(tree, tiled=True)


def fetch_state_shards(tree, mesh: Mesh, own_data: bool = True) -> dict:
    """Stage 1 of a SHARDED checkpoint save — the gather-free replacement
    for `fetch_global`: instead of allgathering the full state to every
    host, fetch only the DISTINCT pieces of each leaf (one representative
    per replica group, owner = the lowest-ranked device holding it in
    mesh order) and tag each with the shard FILE it belongs to (file id =
    owning device's rank). Fully-replicated leaves are chunked along
    their leading dim across the files so no byte is written twice and
    the files stay balanced — total bytes across shard files equal the
    monolithic layout's exactly.

    Device→host copies for every piece are started asynchronously first
    (`copy_to_host_async`, the r7 stage-1 overlap), then materialized.
    `own_data=True` (the default) deep-copies any leaf whose host view
    still aliases a device buffer — the async stage-2 writer overlaps
    later rounds, and the round's donation may reuse that buffer (same
    OWNDATA rule as the monolithic writer path).

    Multi-host: each process materializes only the pieces its own devices
    own (`pieces` carry arr=None for foreign ones — `checkpoint.
    save_sharded` writes my files, process 0 commits the manifest), so
    per-host stage-1 bytes are O(state/n_processes) for sharded leaves.
    Returns the snapshot dict `checkpoint.save_sharded` consumes:
    {"n_shards", "owners": {file: process}, "process_index",
    "process_count", "leaves": {key: {"shape", "dtype", "pieces":
    [(file_id, offsets, shape, arr|None), ...]}}}."""
    from ..utils.checkpoint import _path_str  # no cycle: checkpoint is leaf

    devices = list(mesh.devices.flat)
    n = len(devices)
    rank = {d: i for i, d in enumerate(devices)}
    my_pi = jax.process_index()
    owners = {i: int(d.process_index) for i, d in enumerate(devices)}

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat["/".join(_path_str(p) for p in path)] = leaf

    def owned(a: np.ndarray) -> np.ndarray:
        if own_data and not a.flags["OWNDATA"]:
            return np.array(a)
        return a

    # pass 1: plan every leaf's pieces + start the async D2H copies
    plans = {}
    for key, leaf in flat.items():
        if isinstance(leaf, jax.Array) and not isinstance(leaf, np.ndarray):
            local = {s.device: s for s in leaf.addressable_shards}
            idx_map = leaf.sharding.devices_indices_map(leaf.shape)
            groups: dict = {}  # normalized index -> owner device
            for d, idx in idx_map.items():
                if d not in rank:
                    continue  # a sharding over a sub-mesh never happens,
                    # but never mis-file a foreign device's piece
                norm = tuple(
                    (int(s.start or 0),
                     int(s.stop if s.stop is not None else dim))
                    for s, dim in zip(idx, leaf.shape))
                cur = groups.get(norm)
                if cur is None or rank[d] < rank[cur]:
                    groups[norm] = d
            replicated = (len(groups) == 1 and all(
                lo == 0 and hi == dim for (lo, hi), dim in
                zip(next(iter(groups)), leaf.shape)))
            if replicated:
                src = local.get(next(iter(groups.values())),
                                next(iter(local.values()), None))
                if src is not None:
                    try:
                        src.data.copy_to_host_async()
                    except Exception:
                        pass
                plans[key] = ("replicated", leaf, src)
            else:
                mine = []
                for norm, d in sorted(groups.items(),
                                      key=lambda kv: rank[kv[1]]):
                    sh = local.get(d)
                    if sh is not None:
                        try:
                            sh.data.copy_to_host_async()
                        except Exception:
                            pass
                    mine.append((norm, d, sh))
                plans[key] = ("sharded", leaf, mine)
        else:
            plans[key] = ("replicated", np.asarray(leaf), None)

    # pass 2: materialize + assemble the piece lists
    leaves = {}
    for key, plan in plans.items():
        kind, leaf, info = plan
        shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype)
        pieces = []
        if kind == "sharded":
            for norm, d, sh in info:
                offsets = tuple(lo for lo, _ in norm)
                pshape = tuple(hi - lo for lo, hi in norm)
                arr = (owned(np.asarray(sh.data))
                       if sh is not None and owners[rank[d]] == my_pi
                       else None)
                pieces.append((rank[d], offsets, pshape, arr))
        else:
            full = None
            if info is not None:  # jax leaf: one local replica
                full = owned(np.asarray(info.data))
            elif isinstance(leaf, np.ndarray):
                full = leaf
            if shape == () or (shape and shape[0] == 0) or n == 1:
                arr = full if owners[0] == my_pi else None
                pieces.append((0, (0,) * len(shape), shape, arr))
            else:
                # chunk the replicated value across the shard files:
                # contiguous leading-dim blocks, sizes differing by <= 1
                lo = 0
                for j, chunk in enumerate(
                        np.array_split(np.arange(shape[0]),
                                       min(n, shape[0]))):
                    size = len(chunk)
                    if not size:
                        continue
                    arr = (full[lo:lo + size]
                           if full is not None and owners[j] == my_pi
                           else None)
                    pieces.append((j, (lo,) + (0,) * (len(shape) - 1),
                                   (size,) + shape[1:], arr))
                    lo += size
        leaves[key] = {"shape": shape, "dtype": dtype, "pieces": pieces}
    return {"n_shards": n, "owners": owners,
            "process_index": int(my_pi),
            "process_count": int(jax.process_count()),
            "leaves": leaves}


def per_device_state_bytes(state) -> dict:
    """At-rest bytes ONE device holds for this TrainState's params and
    momentum — the HBM ledger the ZeRO state_sharding modes exist to
    shrink (`sharding.shard_shape` is the allocator's view, exact on any
    backend). One definition shared by the BENCH_r07 acceptance ledger
    (bench.py --sharding) and the tier-1 byte pin (tests/test_sharded.py)
    so the two cannot drift."""
    out = {}
    for name, tree in (("params", state.params),
                       ("momentum", state.momentum)):
        out[name] = sum(
            int(np.prod(leaf.sharding.shard_shape(leaf.shape)))
            * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree))
    return out


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int, axis: int = 0) -> NamedSharding:
    spec = [None] * ndim
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))
