"""Distributed τ-averaging for the serialized-graph backend.

This closes the loop the reference proved with its SECOND backend: TF nets
trained *inside* the distributed averaging loop (`apps/MnistApp.scala:98-138`
— per-worker `TensorFlowNet.step` τ times, then `TensorFlowWeightCollection`
averaging). Here the same thing is one XLA program per round, built from
`GraphNet.make_train_step` (the pure in-graph-optimizer step) scanned τ times
inside shard_map, with the averaging as an on-mesh collective.

Averaging semantics — exactly what the reference's weight exchange did:
  - FLOAT variables are pmean'd across workers. For an imported TF graph
    that includes the `<var>/Momentum` slot variables (reference getWeights
    fetched every DT_FLOAT Variable, `TensorFlowNet.scala:95-108`, and
    MnistApp averaged all of them, `MnistApp.scala:135-136`).
  - INT variables (the global-step counter) stay local — the reference's
    DT_FLOAT filter excluded them from the wire. They are replica-identical
    anyway (same τ increments everywhere).
  - `slots` (native-graph velocity) stays worker-local and is NEVER reset —
    only variables cross the "wire", Caffe-style (SURVEY §7 hard-part #2).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..backend.graph_net import GraphNet
from .mesh import (DATA_AXIS, local_device_rows, place_global_state,
                   put_device_axis, scan_unroll, shard_map)

PyTree = Any


class GraphTrainer:
    """τ-local-step parameter-averaging trainer over a 1-D (data,) mesh for
    a GraphNet (serialized/imported graph with in-graph optimizer).

    State layout matches ParallelTrainer: every leaf carries a leading
    [n_devices] axis sharded over the data axis — each device holds its own
    (possibly diverged-during-τ) replica; after a round the float variables
    are numerically identical again.
    """

    def __init__(self, net: GraphNet, mesh: Mesh, tau: int = 10,
                 loss_name: Optional[str] = None,
                 acc_name: Optional[str] = "accuracy",
                 compute_health: bool = True):
        self.net = net
        self.mesh = mesh
        self.tau = tau
        self.loss_name = net.resolve_loss(loss_name)
        self.acc_name = acc_name
        self.n_devices = int(np.prod(mesh.devices.shape))
        self.n_local_devices = len(local_device_rows(mesh))
        self._step = net.make_train_step(self.loss_name)
        # False compiles the original round: no isfinite/delta reductions,
        # no extra scalar collectives (ParallelTrainer contract)
        self.compute_health = bool(compute_health)

        dev = P(DATA_AXIS)
        batch_spec = P(None, DATA_AXIS)  # [tau, global_batch, ...]
        health_specs = ({"grad_norm": P(), "nonfinite": P()}
                        if self.compute_health else {})
        self._round = jax.jit(
            shard_map(self._round_impl, mesh=mesh,
                      in_specs=(dev, batch_spec),
                      out_specs=(dev, P(), health_specs)),
            donate_argnums=(0,))
        #: device health scalars from the LAST train_round (the layer-IR
        #: trainer's contract): "grad_norm" here is the applied-update norm
        #: of the float variables (grads live inside the imported graph's
        #: optimizer, so the per-round weight delta is the observable
        #: equivalent), "nonfinite" the count of workers whose round
        #: produced a NaN/Inf loss or variable.
        self.last_health = None
        #: in-graph optimizer owns the LR — no runtime backoff knob
        self.supports_lr_scale = False
        self._eval = jax.jit(
            shard_map(self._eval_impl, mesh=mesh,
                      in_specs=(dev, P(DATA_AXIS)),
                      out_specs=P()))

    # -- state ---------------------------------------------------------------

    def init_state(self, key=None) -> PyTree:
        """Tile the net's current train state across devices (the reference
        seeds all workers identically from worker-0, MnistApp.scala:88).
        `key` is accepted for trainer-interface parity and ignored: graph
        variable initializers are seeded at GraphNet construction."""
        state = self.net.init_train_state(self.loss_name)
        return self._tile_and_place(state)

    def _tile_and_place(self, state: PyTree) -> PyTree:
        """Broadcast single-copy leaves to the [n_devices, ...] layout the
        jitted round expects, and place them on the mesh."""

        def tile(x):
            x = jnp.asarray(x)
            return jnp.broadcast_to(x[None], (self.n_devices,) + x.shape)

        return self.place(jax.tree.map(tile, state))

    def place(self, state: PyTree) -> PyTree:
        """Leaves carry the GLOBAL device axis; under multi-host each
        process contributes its own devices' rows."""
        return place_global_state(state, self.mesh, P(DATA_AXIS))

    def adapt_state(self, flat: Dict[str, np.ndarray],
                    old_tp: int = 1) -> PyTree:
        """ELASTIC resume from a checkpoint taken on a different device
        count (`checkpoint.restore_flat` output; keys 'variables/<name>',
        'slots/<name>', 'it'). Variables are replica-identical after a
        round (float ones pmean'd, int counters advance in lockstep) so
        row 0 is THE value; worker-local slots are plain-averaged over
        the old workers (ParallelTrainer's pre-r5 policy — its r5 A/B
        winner, norm-rescaling, was validated on the layer-IR backend's
        Caffe-style velocity, not on in-graph slot variables, so the
        graph backend keeps the plain mean). A
        checkpoint that does not cover this graph's variables (wrong
        backend / wrong graph) fails loudly, like the same-topology path."""
        if old_tp != 1:
            raise ValueError(
                f"checkpoint has tp={old_tp}; the graph backend has no "
                f"tensor parallelism — resume on the original topology")
        out: PyTree = {"variables": {}, "slots": {}, "it": None}
        for key, arr in flat.items():
            parts = key.split("/", 1)
            if parts[0] == "it":
                out["it"] = jnp.asarray(int(np.asarray(arr).reshape(-1)[0]),
                                        jnp.int32)
            elif parts[0] == "variables":
                out["variables"][parts[1]] = jnp.asarray(arr[0])
            elif parts[0] == "slots":
                a = np.asarray(arr)
                if np.issubdtype(a.dtype, np.floating):
                    # accumulate in float64 so a float64 slot loses nothing
                    out["slots"][parts[1]] = jnp.asarray(
                        a.mean(axis=0, dtype=np.float64).astype(a.dtype))
                else:
                    # integer slots (counters) are replica-identical;
                    # averaging would silently truncate — take row 0
                    out["slots"][parts[1]] = jnp.asarray(a[0])
        want = set(self.net.variable_names)
        missing = want - set(out["variables"])
        extra = set(out["variables"]) - want
        if missing or extra or out["it"] is None:
            raise ValueError(
                f"checkpoint does not match this graph's train state "
                f"(missing variables {sorted(missing)[:5]}, unknown "
                f"variables {sorted(extra)[:5]}"
                f"{', no it counter' if out['it'] is None else ''}) — a "
                f"layer-backend or different-graph checkpoint cannot be "
                f"adapted")
        out["slots"] = {k: v for k, v in out["slots"].items() if k in want}
        return self._tile_and_place(out)

    def averaged_state(self, state: PyTree) -> PyTree:
        """Single-replica view (device 0's copy) for checkpoint/export."""
        return jax.tree.map(lambda x: x[0], state)

    def load_into_net(self, state: PyTree) -> None:
        self.net.load_train_state(self.averaged_state(state))

    # -- round (runs INSIDE shard_map) ---------------------------------------

    def _round_impl(self, state, batches):
        local = jax.tree.map(lambda x: x[0], state)
        float_vars = [k for k, v in local["variables"].items()
                      if jnp.issubdtype(v.dtype, jnp.floating)]
        old_float_vars = {k: local["variables"][k] for k in float_vars}

        def local_step(carry, batch):
            carry, loss = self._step(carry, batch)
            return carry, loss

        local, losses = lax.scan(local_step, local, batches,
                                 unroll=scan_unroll(self.tau))

        # health signal: each worker's OWN float-variable delta over the τ
        # steps, squared — measured BEFORE the averaging collective (after
        # it every worker holds the same mean and the psum would inflate
        # by the worker count)
        delta_sq = (sum(
            jnp.sum(jnp.square(local["variables"][k].astype(jnp.float32)
                               - old_float_vars[k].astype(jnp.float32)))
            for k in float_vars) if self.compute_health else None)

        # THE sync: float variables pmean'd, ints + slots stay local.
        def avg(x):
            if jnp.issubdtype(x.dtype, jnp.floating):
                return lax.pmean(x, DATA_AXIS)
            return x

        local["variables"] = {k: avg(v)
                              for k, v in local["variables"].items()}
        mean_loss = lax.pmean(jnp.mean(losses), DATA_AXIS)

        # on-device health scalars (ParallelTrainer contract): the graph's
        # gradients are internal to the imported optimizer, so the round's
        # applied-update norm stands in for the gradient norm; nonfinite
        # checks the round outputs (a NaN/Inf gradient poisons them)
        health = {}
        if self.compute_health:
            grad_norm = jnp.sqrt(lax.psum(delta_sq, DATA_AXIS))
            finite = jnp.all(jnp.isfinite(losses))
            for k in float_vars:
                finite &= jnp.all(jnp.isfinite(local["variables"][k]))
            nonfinite = lax.psum((~finite).astype(jnp.float32), DATA_AXIS)
            health = {"grad_norm": grad_norm, "nonfinite": nonfinite}
        return jax.tree.map(lambda x: x[None], local), mean_loss, health

    def _eval_impl(self, state, batch):
        variables = jax.tree.map(lambda x: x[0], state["variables"])
        (acc,) = self.net.fetch(variables, batch, (self.acc_name,))
        n = jnp.asarray(next(iter(batch.values())).shape[0], jnp.float32)
        return lax.psum(acc * n, DATA_AXIS) / lax.psum(n, DATA_AXIS)

    # -- public API ----------------------------------------------------------

    def train_round(self, state: PyTree, batches: Dict[str, np.ndarray],
                    rng=None) -> Tuple[PyTree, Any]:
        """One outer round: τ in-graph-optimizer steps per device, then the
        averaging collective. batches[input]: [tau, global_batch, ...].
        Returns (state, loss) with loss a DEVICE scalar — callers fetch it
        (`float(loss)`) when they need the synchronization, letting the
        train loop pipeline the fetch one round behind the dispatch.
        `rng` is accepted for trainer-interface parity and ignored (graph
        execution is deterministic; dropout-free eval semantics)."""
        new_state, loss, health = self._round(state,
                                              self._shard_batches(batches))
        self.last_health = health or None  # {} when compute_health=False
        return new_state, loss

    def evaluate(self, state: PyTree, batch: Dict[str, np.ndarray]) -> float:
        sharded = {
            k: put_device_axis(v, self.mesh, P(DATA_AXIS))
            for k, v in self._cast(batch).items()}
        return float(self._eval(state, sharded))

    def _cast(self, batch):
        """Host-side dtype casts per the graph's placeholder attrs (the
        layout/NCHW handling of GraphNet._prep is for single batches; the
        trainer requires device layout (NHWC) already)."""
        out = {}
        dtypes = self.net.input_dtypes()
        for iname in self.net.input_names:
            if iname not in batch:
                raise ValueError(f"batch missing graph input {iname!r}")
            out[iname] = np.asarray(batch[iname]).astype(dtypes[iname],
                                                         copy=False)
        return out

    def _shard_batches(self, batches):
        out = {}
        for k, v in self._cast(batches).items():
            assert v.shape[0] == self.tau, (
                f"{k}: leading dim {v.shape[0]} != tau {self.tau}")
            assert v.shape[1] % self.n_local_devices == 0, (
                f"{k}: host batch {v.shape[1]} not divisible by "
                f"{self.n_local_devices} local devices")
            out[k] = put_device_axis(v, self.mesh, P(None, DATA_AXIS))
        return out
