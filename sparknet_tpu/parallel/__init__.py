from .mesh import (DATA_AXIS, MODEL_AXIS, make_mesh,  # noqa: F401
                   initialize_multihost)
from .trainer import ParallelTrainer, TrainState  # noqa: F401
from .sharded import ShardedTrainer  # noqa: F401
from .graph_trainer import GraphTrainer  # noqa: F401
from .elastic import (ElasticRelaunch, MembershipController,  # noqa: F401
                      MembershipEvent)
