from .mesh import (DATA_AXIS, MODEL_AXIS, make_mesh,  # noqa: F401
                   initialize_multihost)
from .trainer import ParallelTrainer, TrainState  # noqa: F401
