"""Sequence/context parallelism: ring attention + all-to-all (Ulysses) forms.

Long sequences are sharded across a mesh axis ("seq"); two standard TPU
strategies are provided (absent from the reference — SURVEY §5.7 — but
first-class here):

  - **ring attention** (`ring_attention`): KV shards rotate around the ring
    via `lax.ppermute` while each device's Q shard accumulates attention
    with a stable online softmax (flash-style running max/denominator).
    Communication rides the ICI ring; memory per device is O(L/n), enabling
    contexts n× longer than a single chip could hold.

  - **Ulysses / all-to-all** (`ulysses_attention`): `lax.all_to_all` swaps
    sequence sharding for head sharding, runs exact local attention over the
    full sequence per head group, and swaps back. Cheaper at moderate L when
    heads ≥ mesh axis size.

Both are written against a mesh axis name and run inside `shard_map`;
`make_ring_attention(mesh)` wraps one for host-level convenience.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import axis_size, pvary, shard_map

from ..ops.attention import (block_accumulate, finalize_accumulator,
                             init_accumulator)

SEQ_AXIS = "seq"


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   axis_name: str = SEQ_AXIS,
                   causal: bool = False) -> jnp.ndarray:
    """Runs INSIDE shard_map. Per-device shapes [B, L/n, H, D] (seq-sharded).

    Device i initially holds KV shard i; after step t it holds shard
    (i - t) mod n — offsets for causal masking are derived from that.
    """
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    lq = q.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(carry, t):
        o, m, l, k_cur, v_cur = carry
        src = (me - t) % n  # whose shard we hold at step t
        o, m, l = block_accumulate(o, m, l, q, k_cur, v_cur,
                                   k_offset=src * lq, q_offset=me * lq,
                                   causal=causal)
        # rotate AFTER use; skipping the final rotate would save one hop but
        # make the carry shape conditional — XLA overlaps this with compute.
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o, m, l = init_accumulator(q.shape)
    # zeros/full constants are replicated; mark them device-varying so the
    # scan carry type matches the per-device accumulation results (vma
    # compat shim: pcast in jax >= 0.9, pvary in 0.5-0.8, no-op before)
    o, m, l = pvary((o, m, l), (axis_name,))
    (o, m, l, _, _), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(n))
    return finalize_accumulator(o, m, l, q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                      axis_name: str = SEQ_AXIS,
                      causal: bool = False) -> jnp.ndarray:
    """Runs INSIDE shard_map. Per-device [B, L/n, H, D] with H % n == 0.

    all_to_all: seq-sharded -> head-sharded (full L per device, H/n heads),
    exact attention locally, then back.
    """
    from ..ops.attention import attention
    n = axis_size(axis_name)
    assert q.shape[2] % n == 0, (
        f"heads {q.shape[2]} not divisible by seq-axis size {n}")
    # [B, L/n, H, D] -> gather seq, scatter heads -> [B, L, H/n, D]
    def a2a(x, concat, split):
        return lax.all_to_all(x, axis_name, split_axis=split,
                              concat_axis=concat, tiled=True)
    qh = a2a(q, 1, 2)
    kh = a2a(k, 1, 2)
    vh = a2a(v, 1, 2)
    oh = attention(qh, kh, vh, causal=causal)
    return a2a(oh, 2, 1)


def make_ring_attention(mesh: Mesh, *, axis_name: str = SEQ_AXIS,
                        causal: bool = False, impl: str = "ring"):
    """Host-level wrapper: takes GLOBAL [B, L, H, D] arrays sharded (or
    shardable) over `axis_name` on the length dim; returns global output."""
    fn = ring_attention if impl == "ring" else ulysses_attention
    inner = functools.partial(fn, axis_name=axis_name, causal=causal)
    spec = P(None, axis_name, None, None)
    kw = {}
    import inspect
    if "check_rep" in inspect.signature(shard_map).parameters:
        # old-jax (<= 0.4.x) replication checking miscounts the scan carry
        # under grad (jax advises check_rep=False as the workaround); newer
        # jax's vma tracking handles it via the pvary marking above
        kw["check_rep"] = False
    mapped = jax.jit(shard_map(
        lambda q, k, v: inner(q, k, v),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **kw))

    def apply(q, k, v):
        sharding = NamedSharding(mesh, spec)
        return mapped(jax.device_put(q, sharding),
                      jax.device_put(k, sharding),
                      jax.device_put(v, sharding))

    return apply
