"""The framework's Net/Solver API surface — NetInterface parity, TPU-native.

Reference API being matched (`libs/CaffeNet.scala:14-20`):

    trait NetInterface {
      def forward(rowIt): Array[Row]
      def forwardBackward(rowIt): Unit
      def getWeights(): WeightCollection
      def setWeights(weights): Unit
      def outputSchema(): StructType
    }
    trait Solver { def step(rowIt): Unit }            // CaffeSolver.scala:7-9

`JaxNet` is the stateful convenience wrapper over the pure `CompiledNet` +
`SgdSolver` core: it owns the current params/optimizer-state (device-resident,
replicated or sharded), exposes forward / forward_backward / step /
get_weights / set_weights / output_schema, and save/load. All compute methods
are jit-compiled once and reused.

Unlike the reference there is no JVM<->C++ copy per call: batches go host->
device once, weights stay device-resident, and `get_weights` is the only
deliberate device->host transfer (for checkpoint/export).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .model.caffe_compat import collection_to_params, params_to_collection
from .model.net import CompiledNet, PyTree
from .model.spec import NetSpec
from .model.weights import WeightCollection
from .schema import Field, Schema
from .solver import SgdSolver, SolverConfig, SolverState


def _maybe_nhwc(name: str, arr: np.ndarray, want_shape: Tuple[int, ...],
                layout: str) -> np.ndarray:
    """Accept NCHW host batches and transpose to device NHWC.

    layout="auto" (default) disambiguates by matching the expected NHWC
    element shape, so both reference-style NCHW batches and native NHWC
    batches just work; pass "NHWC"/"NCHW" to force.
    """
    if arr.ndim != 4:
        return arr
    if layout == "NCHW":
        return np.transpose(arr, (0, 2, 3, 1))
    if layout == "auto":
        want = tuple(want_shape[1:])
        if tuple(arr.shape[1:]) != want and \
                (arr.shape[2], arr.shape[3], arr.shape[1]) == want:
            return np.transpose(arr, (0, 2, 3, 1))
    return arr


class JaxNet:
    """Stateful net: CompiledNet + device params (+ optional solver)."""

    def __init__(self, spec: NetSpec, *, seed: int = 0,
                 solver: Optional[SolverConfig] = None,
                 input_layout: str = "auto",
                 loss_blob: str = "loss"):
        self.net = CompiledNet.compile(spec)
        self.input_layout = input_layout
        self.params: PyTree = self.net.init_params(jax.random.PRNGKey(seed))
        self.solver: Optional[SgdSolver] = None
        self.solver_state: Optional[SolverState] = None
        # serving-side weight-only quantization (model/quant.py): set via
        # set_quant() alongside a quantized params pytree. The config is
        # a STATIC jit argument (QuantConfig is frozen/hashable), so a
        # config change is part of the cache key — switching e.g. the
        # act dtype retraces instead of silently reusing the old
        # executable — and the f32 path (quant=None) keeps its own entry.
        self.quant = None
        if solver is not None:
            self.solver = SgdSolver(self.net, solver, loss_blob=loss_blob)
            self.solver_state = self.solver.init_state(self.params)
        self._fwd_test = jax.jit(
            lambda p, b, q: self.net.apply(p, b, train=False, quant=q),
            static_argnums=2)
        self._fwd_train = jax.jit(
            lambda p, b, r: self.net.apply(p, b, train=True, rng=r))
        _loss_blob = loss_blob
        self._grad = jax.jit(jax.grad(
            lambda p, b, r: self.net.apply(p, b, train=True, rng=r)[_loss_blob]))
        self._rng = jax.random.PRNGKey(seed ^ 0x5EED)

    def set_quant(self, quant) -> None:
        """Install/clear the quant config for test-phase forwards. Call
        alongside swapping `self.params` to/from a quantized pytree
        (model/quant.py quantize_params); the config rides the jit cache
        key, so mismatched combinations merely compile their own
        executables — they never reuse a stale one."""
        self.quant = quant

    # -- data plumbing ------------------------------------------------------

    def _prep(self, batch: Dict[str, np.ndarray]) -> Dict[str, jnp.ndarray]:
        out = {}
        for name, want in self.net.input_shapes.items():
            if name not in batch:
                raise ValueError(f"batch missing net input {name!r}")
            arr = np.asarray(batch[name])
            arr = _maybe_nhwc(name, arr, want, self.input_layout)
            if tuple(arr.shape[1:]) != tuple(want[1:]):
                raise ValueError(
                    f"input {name!r}: got {arr.shape}, net expects "
                    f"(N,)+{tuple(want[1:])} (device layout NHWC)")
            out[name] = jnp.asarray(arr)
        return out

    # -- NetInterface parity -------------------------------------------------

    def forward(self, batch: Dict[str, np.ndarray],
                blob_names: Optional[List[str]] = None
                ) -> Dict[str, np.ndarray]:
        """Test-phase forward. Returns output blobs (+ any requested hidden
        blobs, parity with `forward(rowIt, dataBlobNames)`,
        `libs/CaffeNet.scala:88-109`)."""
        blobs = self._fwd_test(self.params, self._prep(batch), self.quant)
        want = set(self.net.output_names) | set(blob_names or [])
        return {k: np.asarray(v) for k, v in blobs.items() if k in want}

    def forward_backward(self, batch: Dict[str, np.ndarray]) -> PyTree:
        """Forward + backward; returns grads, does NOT update weights
        (parity with `forwardBackward`, `libs/CaffeNet.scala:111-121`)."""
        self._rng, sub = jax.random.split(self._rng)
        return self._grad(self.params, self._prep(batch), sub)

    def step(self, batch: Dict[str, np.ndarray]) -> float:
        """One SGD step (parity with `CaffeSolver.step`). Returns loss."""
        assert self.solver is not None, "construct JaxNet with solver= to train"
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.solver_state, loss = self.solver.step(
            self.params, self.solver_state, self._prep(batch), sub)
        return float(loss)

    def get_weights(self) -> WeightCollection:
        return params_to_collection(self.net, self.params)

    def set_weights(self, weights: WeightCollection) -> None:
        new = collection_to_params(self.net, weights)
        for lname, lp in self.params.items():
            assert lname in new, f"weights missing layer {lname!r}"
            for pname, w in lp.items():
                assert new[lname][pname].shape == w.shape, (
                    f"{lname}/{pname}: {new[lname][pname].shape} != {w.shape}")
        self.params = new

    def output_schema(self) -> Schema:
        """Schema of output blobs (parity `outputSchema`,
        `libs/CaffeNet.scala:167-173`)."""
        fields = []
        for name in self.net.output_names:
            shape = self.net.blob_shapes[name]
            fields.append(Field(name=name, dtype="float32",
                                shape=tuple(shape[1:]) if shape else ()))
        return Schema(*fields)

    # -- checkpoint ---------------------------------------------------------

    def save_weights(self, path: str) -> None:
        """Weight-only export (parity `saveWeightsToFile`,
        `libs/CaffeNet.scala:159-165`). `.caffemodel` suffix writes binary
        Caffe NetParameter; anything else our npz format."""
        if path.endswith(".caffemodel"):
            from .model.caffemodel import save_caffemodel
            save_caffemodel(self.get_weights(), path,
                            net_name=self.net.spec.name)
        else:
            self.get_weights().save(path)

    def load_weights(self, path: str) -> None:
        """Weight-only import (parity `copyTrainedLayersFrom`,
        `libs/CaffeNet.scala:152-157`). Reads binary `.caffemodel`
        (trained Caffe nets import directly) or our npz format."""
        if path.endswith(".caffemodel"):
            from .model.caffemodel import load_caffemodel_file
            self.set_weights(load_caffemodel_file(path))
        else:
            self.set_weights(WeightCollection.load(path))
