"""Replica providers: where new serve capacity comes FROM.

The FleetController decides *when* the fleet grows or shrinks; a
`ReplicaProvider` knows *how* — it turns "grow model m" into a running
`sparknet-serve` replica reachable over a URL, and "retire" into a
clean teardown. Providers are pluggable (SparkNet shipped its EC2
provisioning layer inside the framework; this is our analog over the
serve stack):

  - `SubprocessReplicaProvider`: spawns real `sparknet-serve` child
    processes on THIS host, each with its own binary frame port
    (spkn://) and heartbeat file — the CPU-truth provider the fleet
    tests and `bench.py --fleet` run end to end. Children share the
    persistent compile cache, so a grow on a warm host skips every
    bucket compile (the r9 cold-start lever is what makes autoscaling
    cheap enough to be worth doing).
  - `PodReplicaProvider`: a STUB riding the `tpu_pod_launch.sh`
    protocol — grow assembles the launcher's create/setup/run command
    sequence for a fresh single-host TPU VM serving the model, retire
    assembles the delete. The command runner is injectable (tests
    record; real deployments pass subprocess). Structural on this box:
    a CPU CI machine cannot create TPU VMs, but the protocol — what
    would run, in what order, with which flags — is pinned here.

A `ReplicaHandle` is the provider's receipt: the URL the router should
route to, the heartbeat path health probes should watch, and whatever
the provider needs to retire it later.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence


@dataclass
class ReplicaHandle:
    """One grown replica: routing address + health + teardown state."""

    model: str
    url: str                            # spkn://host:port or http://...
    heartbeat_path: Optional[str] = None
    meta: Dict[str, Any] = field(default_factory=dict)


class ReplicaProvider:
    """The grow/retire/alive interface the controller drives."""

    def grow(self, model: str) -> ReplicaHandle:
        raise NotImplementedError

    def retire(self, handle: ReplicaHandle) -> None:
        raise NotImplementedError

    def alive(self, handle: ReplicaHandle) -> bool:
        """Is the replica's PROCESS still there? (Routability is the
        router's heartbeat-health call; this is the cheaper, blunter
        probe a kill -9 flips instantly.)"""
        return True

    def stop(self) -> None:
        """Tear down everything this provider still owns."""


def _free_port() -> int:
    """An OS-assigned free TCP port (bind-0, read, close). Racy in
    principle; in practice the child binds it immediately and a grow
    that loses the race fails loudly inside spawn_timeout_s."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class SubprocessReplicaProvider(ReplicaProvider):
    """Real `sparknet-serve` children over spkn:// on this host.

    `sources[model]` is the model source the child builds (zoo name or
    .prototxt path — exactly the `sparknet-serve --model` argument).
    Children write fast heartbeats (`heartbeat_every_s`) so the
    router's staleness rule sees a kill -9 promptly, and serve prob-only
    outputs at `max_batch` unless overridden via `extra_args`.

    Continuous learning: with `checkpoint_dir` set (a path/URL, `{model}`
    substituted), children watch the training store and hot-swap; each
    gets its provider tag as `--replica-name` — the identity the rollout
    gate (`rollout_gate`, when set) approves steps under — plus the
    fleet-shared `poll_interval_s`/`poll_jitter` cadence."""

    def __init__(self, sources: Dict[str, str],
                 workdir: Optional[str] = None,
                 max_batch: int = 8,
                 outputs: Sequence[str] = ("prob",),
                 compile_cache_dir: Optional[str] = None,
                 heartbeat_every_s: float = 0.5,
                 spawn_timeout_s: float = 120.0,
                 extra_args: Sequence[str] = (),
                 python: str = sys.executable,
                 checkpoint_dir: Optional[str] = None,
                 poll_interval_s: Optional[float] = None,
                 poll_jitter: Optional[float] = None,
                 rollout_gate: Optional[str] = None):
        self.sources = dict(sources)
        self.workdir = workdir or tempfile.mkdtemp(
            prefix="sparknet-fleet-")
        os.makedirs(self.workdir, exist_ok=True)
        self.max_batch = int(max_batch)
        self.outputs = tuple(outputs or ())
        self.compile_cache_dir = compile_cache_dir
        self.heartbeat_every_s = float(heartbeat_every_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.extra_args = tuple(extra_args)
        self.python = python
        self.checkpoint_dir = checkpoint_dir
        self.poll_interval_s = poll_interval_s
        self.poll_jitter = poll_jitter
        self.rollout_gate = rollout_gate
        self._n = 0
        self._procs: List[subprocess.Popen] = []

    def grow(self, model: str) -> ReplicaHandle:
        src = self.sources.get(model)
        if src is None:
            raise KeyError(f"no model source registered for {model!r} "
                           f"(have {sorted(self.sources)})")
        self._n += 1
        tag = f"{model.replace('/', '_')}-{self._n}"
        port = _free_port()
        hb = os.path.join(self.workdir, f"replica-{tag}.heartbeat.json")
        log_path = os.path.join(self.workdir, f"replica-{tag}.log")
        cmd = [self.python, "-m", "sparknet_tpu.serve.app",
               "--model", src, "--model-name", model,
               "--binary-port", str(port),
               "--max-batch", str(self.max_batch),
               "--heartbeat", hb,
               "--heartbeat-every", str(self.heartbeat_every_s)]
        if self.outputs:
            cmd += ["--outputs", ",".join(self.outputs)]
        if self.compile_cache_dir:
            cmd += ["--compile-cache", self.compile_cache_dir]
        if self.checkpoint_dir:
            cmd += ["--checkpoint-dir",
                    self.checkpoint_dir.replace("{model}", model),
                    "--replica-name", tag]
            if self.poll_interval_s is not None:
                cmd += ["--poll-interval", str(self.poll_interval_s)]
            if self.poll_jitter is not None:
                cmd += ["--poll-jitter", str(self.poll_jitter)]
            if self.rollout_gate:
                cmd += ["--rollout-gate",
                        self.rollout_gate.replace("{model}", model)]
        cmd += list(self.extra_args)
        # the child must resolve sparknet_tpu however THIS process did
        # (editable install, or a bare checkout run from the repo root)
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=log_f, stderr=log_f,
                                    cwd=self.workdir, env=env)
        finally:
            log_f.close()  # the child holds its own fd now
        # shm_eligible: the child is a colocated loopback process — the
        # binary client's spkn-shm handshake will succeed against it
        # (the nonce proof still decides at connect time; this flag is
        # advisory, for status/placement readers)
        handle = ReplicaHandle(model, f"spkn://127.0.0.1:{port}",
                               heartbeat_path=hb,
                               meta={"proc": proc, "port": port,
                                     "log": log_path, "tag": tag,
                                     "shm_eligible": True})
        self._procs.append(proc)
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break  # died during bring-up: fail with the log tail
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=1.0).close()
                return handle
            except OSError:
                time.sleep(0.1)
        self.retire(handle)
        tail = ""
        try:
            with open(log_path, "rb") as f:
                tail = f.read()[-2000:].decode("utf-8", "replace")
        except OSError:
            pass
        raise RuntimeError(
            f"replica {tag} did not come up on port {port} within "
            f"{self.spawn_timeout_s:.0f}s (exit={proc.poll()}); "
            f"log tail:\n{tail}")

    def retire(self, handle: ReplicaHandle) -> None:
        proc = handle.meta.get("proc")
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5.0)

    def alive(self, handle: ReplicaHandle) -> bool:
        proc = handle.meta.get("proc")
        return proc is not None and proc.poll() is None

    def stop(self) -> None:
        for proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 5.0
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=max(0.1,
                                          deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
        self._procs = []


class PodReplicaProvider(ReplicaProvider):
    """The `tpu_pod_launch.sh` protocol stub: one fresh single-host TPU
    VM per grow, serving the model over the binary plane on `port`.

    `runner(argv)` executes one launcher invocation (tests inject a
    recorder; production passes e.g.
    `lambda argv: subprocess.run(argv, check=True)`). The VM's DNS name
    doubles as the spkn:// host — the launcher's network setup resolves
    it inside the pod's VPC. `alive` defers to the launcher's own
    `watch` supervision (this provider cannot cheaply probe a remote
    VM's process table)."""

    def __init__(self, sources: Dict[str, str], zone: str,
                 accel_type: str, name_prefix: str = "sparknet-fleet",
                 port: int = 8470,
                 launcher: str = "scripts/tpu_pod_launch.sh",
                 runner: Optional[Callable[[List[str]], Any]] = None):
        self.sources = dict(sources)
        self.zone = zone
        self.accel_type = accel_type
        self.name_prefix = name_prefix
        self.port = int(port)
        self.launcher = launcher
        self.runner = runner or (lambda argv: subprocess.run(
            argv, check=True))
        self._n = 0
        self._live: List[str] = []

    def grow(self, model: str) -> ReplicaHandle:
        src = self.sources.get(model)
        if src is None:
            raise KeyError(f"no model source registered for {model!r}")
        self._n += 1
        name = f"{self.name_prefix}-{model.replace('/', '-')}-{self._n}"
        serve_cmd = (f"sparknet-serve --model {src} "
                     f"--model-name {model} "
                     f"--binary-port {self.port} "
                     f"--binary-host 0.0.0.0 --outputs prob")
        commands = [
            [self.launcher, "create", name, self.zone, self.accel_type],
            [self.launcher, "setup", name, self.zone],
            [self.launcher, "run", name, self.zone, serve_cmd],
        ]
        for argv in commands:
            self.runner(argv)
        self._live.append(name)
        return ReplicaHandle(model, f"spkn://{name}:{self.port}",
                             meta={"name": name, "commands": commands})

    def retire(self, handle: ReplicaHandle) -> None:
        name = handle.meta.get("name")
        if name is None:
            return
        self.runner([self.launcher, "delete", name, self.zone])
        if name in self._live:
            self._live.remove(name)

    def stop(self) -> None:
        for name in list(self._live):
            self.runner([self.launcher, "delete", name, self.zone])
        self._live = []
