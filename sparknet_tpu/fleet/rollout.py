"""Staggered checkpoint adoption: the fleet rollout duty + its gate file.

Without this module every ModelManager polls the checkpoint store
independently, so a freshly committed step goes live on EVERY replica
within one poll interval — a checkpoint that passes digests but fails
the canary/parity gates would be rejected fleet-wide *simultaneously*,
putting every replica into swap-cooldown shedding at once. The rollout
duty turns adoption into a sequenced wave plan:

    canary (one replica, the local lane when present)
      -> wave 1 (<= wave_size replicas)  [health gate]
      -> wave 2 ...                      [health gate]
      -> done (gate opens the step to everyone, future replicas too)

and on a canary rejection or a wave health-gate breach it HALTS: the
step is denied fleet-wide, approvals revert to the pre-rollout step
(replicas that already adopted swap back DOWN), and the audit trail
records why. A bad step therefore reaches at most the canary — the
existing parity/nonfinite canary + swap-cooldown shedding contain the
blast radius to one replica, never the fleet.

Coordination is a single atomically-replaced JSON file (`ROLLOUT.json`,
local path or gs://|s3:// — the same stores checkpoints live in, so a
fleet of subprocess replicas needs no extra RPC surface):

    {"v": 1, "target": 12, "all": 8, "state": "wave", "wave": 1,
     "approved": {"lenet-1": 12}, "denied": [11]}

ModelManager reads it during poll (`serve/model_manager.py`): a replica
adopts `approved[replica]` when present, else `all`, and never a step in
`denied`; no entry at all means HOLD. A missing/unreadable gate degrades
to ungated independent polling — the pre-rollout behavior — so the gate
can be introduced (or lost to a store blip) without stranding a fleet.

`RolloutManager` runs as a FleetController duty (one instance per model
whose lane watches a checkpoint dir with a gate configured); the
controller feeds it replica adoption views each tick and it rewrites the
gate. Everything is tick-driven and clock-injected: tests step the whole
state machine deterministically.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

IDLE = "idle"
CANARY = "canary"
WAVE = "wave"


def read_gate(path: str) -> Optional[Dict[str, Any]]:
    """The rollout gate dict, or None when missing/unreadable/torn (the
    caller degrades to ungated polling). Accepts gs://|s3:// like the
    checkpoint store."""
    try:
        if isinstance(path, str) and path.startswith(("gs://", "s3://")):
            from ..utils.checkpoint import _bucket_ops
            gate = json.loads(_bucket_ops(path).read(path))
        else:
            with open(path) as f:
                gate = json.load(f)
    except Exception:
        return None
    return gate if isinstance(gate, dict) else None


def write_gate(path: str, gate: Dict[str, Any]) -> None:
    """Atomic replace (tmp + os.replace locally, one-object PUT on a
    bucket — both atomic) so a polling replica never reads a torn
    plan."""
    if isinstance(path, str) and path.startswith(("gs://", "s3://")):
        from ..utils.checkpoint import _bucket_ops
        _bucket_ops(path).write(path, json.dumps(gate).encode())
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".rollout-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(gate, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class ReplicaView:
    """One replica's adoption state as the controller sees it this tick:
    `key` is the gate identity (ModelManager.replica — "local" for the
    in-process lane, the provider tag for children), `step` the
    checkpoint it currently serves (None = unknown, e.g. a heartbeat not
    yet landed), `rollbacks` its rejected/rolled-back swap count (a
    rising count during a rollout = the step was refused)."""

    __slots__ = ("key", "step", "rollbacks")

    def __init__(self, key: str, step: Optional[int],
                 rollbacks: int = 0) -> None:
        self.key = str(key)
        self.step = None if step is None else int(step)
        self.rollbacks = int(rollbacks)


class RolloutManager:
    """The wave sequencer for ONE model's checkpoint adoption (module
    doc). `tick(views, newest_step, burn, now)` advances the state
    machine one step and rewrites the gate when the plan changed;
    `event` (the controller's audit hook) receives every transition."""

    def __init__(self, gate_path: str, wave_size: int = 2,
                 halt_burn: float = 1.5, timeout_s: float = 30.0,
                 event: Optional[Callable[..., None]] = None,
                 logger=None):
        if wave_size < 1:
            raise ValueError(f"wave_size must be >= 1 (got {wave_size})")
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0 (got {timeout_s})")
        self.gate_path = gate_path
        self.wave_size = int(wave_size)
        self.halt_burn = float(halt_burn)
        self.timeout_s = float(timeout_s)
        self.event = event
        self.log = logger
        self.state = IDLE
        self.target: Optional[int] = None
        self.fallback: Optional[int] = None   # the pre-rollout "all"
        self.canary: Optional[str] = None
        self.wave = 0                          # 0 = canary phase
        self.waves_done = 0                    # completed rollouts' waves
        self.rollouts = 0                      # completed rollouts
        self.halts = 0
        self.denied: List[int] = []
        self._approved: Dict[str, int] = {}
        self._wave_keys: List[str] = []
        self._rollbacks0: Dict[str, int] = {}
        self._phase_t0 = 0.0
        self._all: Optional[int] = None

    # -- gate ----------------------------------------------------------------

    def _write(self) -> None:
        gate: Dict[str, Any] = {"v": 1, "state": self.state,
                                "wave": self.wave,
                                "approved": dict(self._approved),
                                "denied": list(self.denied)}
        if self.target is not None:
            gate["target"] = self.target
        if self._all is not None:
            gate["all"] = self._all
        write_gate(self.gate_path, gate)

    def _emit(self, reason: str, **extra: Any) -> None:
        if self.event is not None:
            self.event("rollout", reason, **extra)
        if self.log is not None:
            kv = " ".join(f"{k}={v}" for k, v in extra.items())
            self.log.log(f"rollout: {reason} {kv}")

    # -- the duty ------------------------------------------------------------

    def tick(self, views: List[ReplicaView], newest_step: Optional[int],
             burn: float, now: Optional[float] = None) -> str:
        """One sequencing step; returns the (possibly new) state. The
        controller passes every replica's adoption view (canary
        preference = list order: put the local lane first), the newest
        COMMITTED step in the store, and the model's current SLO burn
        (the wave health gate)."""
        now = time.monotonic() if now is None else now
        if self.state == IDLE:
            self._tick_idle(views, newest_step, now)
        elif self.state == CANARY:
            self._tick_canary(views, burn, now)
        elif self.state == WAVE:
            self._tick_wave(views, burn, now)
        return self.state

    def _tick_idle(self, views: List[ReplicaView],
                   newest_step: Optional[int], now: float) -> None:
        if newest_step is None or not views:
            return
        if newest_step in self.denied:
            return
        if self._all is not None and newest_step <= self._all:
            return
        # a new committed step: open a rollout with the first view as
        # canary, everyone else held at the current "all"
        self.target = int(newest_step)
        self.canary = views[0].key
        self.fallback = self._all if self._all is not None \
            else views[0].step
        self.wave = 0
        self._approved = {self.canary: self.target}
        self._wave_keys = [self.canary]
        self._rollbacks0 = {v.key: v.rollbacks for v in views}
        self._phase_t0 = now
        self.state = CANARY
        self._write()
        self._emit("canary", step=self.target, replica=self.canary,
                   fallback=self.fallback)

    def _rejected(self, views: List[ReplicaView]) -> Optional[str]:
        """The wave member whose rollback count rose since the phase
        opened (= it refused the target step), or None."""
        for v in views:
            if v.key in self._wave_keys and \
                    v.rollbacks > self._rollbacks0.get(v.key, v.rollbacks):
                return v.key
        return None

    def _adopted(self, views: List[ReplicaView]) -> bool:
        got = {v.key: v.step for v in views}
        return all(got.get(k) == self.target for k in self._wave_keys)

    def _tick_canary(self, views: List[ReplicaView], burn: float,
                     now: float) -> None:
        bad = self._rejected(views)
        if bad is not None:
            self._halt(f"canary {bad} rejected step")
            return
        if not self._adopted(views):
            if now - self._phase_t0 > self.timeout_s:
                self._halt(f"canary {self.canary} never adopted within "
                           f"{self.timeout_s}s")
            return
        if burn >= self.halt_burn:
            self._halt(f"burn {burn:.2f} >= {self.halt_burn} on the "
                       f"canary")
            return
        self._next_wave(views, now)

    def _tick_wave(self, views: List[ReplicaView], burn: float,
                   now: float) -> None:
        bad = self._rejected(views)
        if bad is not None:
            self._halt(f"replica {bad} rejected step in wave "
                       f"{self.wave}")
            return
        if burn >= self.halt_burn:
            self._halt(f"burn {burn:.2f} >= {self.halt_burn} in wave "
                       f"{self.wave}")
            return
        if not self._adopted(views):
            if now - self._phase_t0 > self.timeout_s:
                self._halt(f"wave {self.wave} never adopted within "
                           f"{self.timeout_s}s")
            return
        self._next_wave(views, now)

    def _next_wave(self, views: List[ReplicaView], now: float) -> None:
        pending = [v.key for v in views
                   if self._approved.get(v.key) != self.target]
        if not pending:
            self._finish()
            return
        self.wave += 1
        batch = pending[:self.wave_size]
        for k in batch:
            self._approved[k] = self.target
        self._wave_keys = batch
        self._rollbacks0 = {v.key: v.rollbacks for v in views}
        self._phase_t0 = now
        self.state = WAVE
        self._write()
        self._emit("wave", step=self.target, wave=self.wave,
                   replicas=batch)

    def _finish(self) -> None:
        self.waves_done += self.wave
        self.rollouts += 1
        self._all = self.target
        self._approved = {}
        self._wave_keys = []
        done_step = self.target
        self.target = None
        self.canary = None
        self.state = IDLE
        self._write()
        self._emit("done", step=done_step, waves=self.wave)
        self.wave = 0

    def _halt(self, why: str) -> None:
        """Deny the step fleet-wide and revert every approval: replicas
        that already adopted it (at most the current wave + earlier
        waves — one canary in the worst and common case) swap back DOWN
        to the pre-rollout step; nobody else ever sees it."""
        self.halts += 1
        if self.target is not None and self.target not in self.denied:
            self.denied.append(self.target)
        self._all = self.fallback
        self._approved = {}
        self._wave_keys = []
        halted_step = self.target
        self.target = None
        self.canary = None
        self.state = IDLE
        self._write()
        self._emit("halt", step=halted_step, wave=self.wave, why=why)
        self.wave = 0

    def status(self) -> Dict[str, Any]:
        return {"state": self.state, "target": self.target,
                "all": self._all, "wave": self.wave,
                "canary": self.canary,
                "approved": dict(self._approved),
                "denied": list(self.denied),
                "rollouts": self.rollouts,
                "waves_done": self.waves_done,
                "halts": self.halts}
