"""`sparknet_tpu.fleet` — the serve control plane: signal-driven replica
autoscaling, priority-aware admission pressure, SLO-burn shedding.

SparkNet shipped cluster provisioning inside the framework (the L7 EC2
launcher); this package is our analog over the serve stack. It closes
the loop from the signals the obs stack already exports (windowed p99
vs SLO, queue depth, shed rate, replica heartbeat health) to actions on
a `ModelRouter` fleet:

  - `FleetController` (controller.py): the fixed-cadence loop — SLO
    burn per model, admission pressure (the fast lever, into
    `serve.admission.PriorityAdmission`), replica grow/retire and
    shared-pool resize (the slow levers) under hysteresis, cooldowns,
    and per-model min/max bounds; dead-replica replacement; the
    scale-event audit trail behind `/fleet/status`.
  - `FleetPolicy` / `ModelSignals` (policy.py): the pure decision
    logic — thresholds, hysteresis shape, burn→pressure curve.
  - `ReplicaProvider` (provider.py): where capacity comes from —
    `SubprocessReplicaProvider` spawns real `sparknet-serve` children
    over spkn:// (CPU truth: tests + `bench.py --fleet`);
    `PodReplicaProvider` is the `tpu_pod_launch.sh`-protocol stub for
    TPU VMs.
  - `RolloutManager` (rollout.py): the continuous-learning rollout
    duty — staggered checkpoint adoption (canary -> health-gated waves
    -> fleet-wide) coordinated through an atomically-replaced
    ROLLOUT.json gate the serving ModelManagers obey, with
    halt-and-rollback on a rejected canary step.

Enable from the CLI with `sparknet-serve --models ... --autoscale`
(+ `--rollout-gate` for staggered adoption).
"""
from .controller import FleetConfig, FleetController
from .policy import FleetPolicy, ModelSignals, slo_burn
from .provider import (PodReplicaProvider, ReplicaHandle,
                       ReplicaProvider, SubprocessReplicaProvider)
from .rollout import ReplicaView, RolloutManager, read_gate, write_gate

__all__ = [
    "FleetController", "FleetConfig",
    "FleetPolicy", "ModelSignals", "slo_burn",
    "ReplicaProvider", "ReplicaHandle",
    "SubprocessReplicaProvider", "PodReplicaProvider",
    "RolloutManager", "ReplicaView", "read_gate", "write_gate",
]
