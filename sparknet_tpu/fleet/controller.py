"""The fleet control plane: close the loop from serve signals to serve
actions.

Every input already exists — per-model latency windows, queue depth,
shed counters, replica heartbeat health (the obs stack's exports) — but
until this module nothing ACTED on them: a flood meant an operator
watching /metrics. `FleetController` is the missing loop (SparkNet
shipped cluster provisioning as part of the framework — L7 in PAPER.md;
this is our replica-controller analog over the serve stack):

  every `interval_s`, on its own thread:
    1. gather per-model signals (fleet/policy.ModelSignals) from the
       ModelRouter's meters;
    2. compute **SLO burn** (windowed p99 / objective) per model and
       push admission pressure into `PriorityAdmission` — the FAST
       lever: low-priority traffic sheds first, tenant refill tightens,
       within one tick of the burn appearing;
    3. drive the SLOW levers under hysteresis + cooldowns:
         - grow/retire remote replicas through a pluggable
           `ReplicaProvider` (subprocess children over spkn:// for CPU
           truth; the pod-launcher stub for TPU VMs), bounded by
           [min_replicas, max_replicas] per model. Scale-DOWN always
           drains first (router.drain — new routing gated, in-flight
           completes) and retires only after `drain_grace_s`: a shrink
           drops zero responses, pinned.
         - resize the router's shared worker pool within
           [pool_min, pool_max] (the in-process lane lever).
    4. replace dead replicas: a provider-owned replica whose process is
       gone (kill -9) or whose heartbeat probe stays false `dead_ticks`
       ticks is evicted from the router, retired, named in the audit
       trail, and regrown (reason="replace") — death is an incident,
       not a scale-down decision.

Observability: `sparknet_fleet_replicas{model}`,
`sparknet_fleet_slo_burn{model}`,
`sparknet_fleet_scale_events_total{model,direction,reason}`,
`sparknet_fleet_admission_pressure`, a bounded audit deque served at
`/fleet/status` (the router's StatusServer), and `event="fleet_scale"`
JSONL rows + periodic `fleet_replicas` rows the `sparknet-metrics`
fleet view renders.

`tick()` is public and thread-free: tests drive the whole control law
deterministically by feeding the router's meters and calling it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..utils.heartbeat import read_heartbeat
from ..utils.logger import Logger
from .policy import FleetPolicy, ModelSignals
from .provider import ReplicaHandle, ReplicaProvider
from .rollout import ReplicaView, RolloutManager


@dataclass
class FleetConfig:
    """Controller knobs (`sparknet-serve --autoscale` mirrors these)."""

    interval_s: float = 1.0         # control cadence
    window_s: float = 30.0          # the sliding p99 window (SLO burn)
    min_replicas: int = 1           # per model, local lane included
    max_replicas: int = 4
    # shared-pool bounds; None pool_min = the router's configured
    # workers, None pool_max = pool_min (pool lever off)
    pool_min: Optional[int] = None
    pool_max: Optional[int] = None
    drain_grace_s: float = 5.0      # drain -> retire gap on scale-down
    dead_ticks: int = 2             # consecutive failed health probes
    up_cooldown_s: float = 5.0      # min gap between grows (per model)
    down_cooldown_s: float = 20.0   # min gap between shrinks (per model)
    # fallback objective for lanes without ServeConfig.slo_p99_ms
    slo_p99_ms: Optional[float] = None
    # admission-pressure floor while a burn-rate PAGE is firing (the
    # SLO alerter, attach_alerter): the fast lever jumps ahead of the
    # slow replica lever the moment the ledger pages — never below what
    # the burn signal already asks for, and still subject to the batch-
    # starvation relief clamp
    page_pressure: float = 0.9
    replace_dead: bool = True
    status_row_every: int = 10      # fleet_replicas JSONL cadence, ticks
    policy: FleetPolicy = field(default_factory=FleetPolicy)

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError(f"interval_s must be > 0 "
                             f"(got {self.interval_s})")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas (got "
                f"{self.min_replicas}, {self.max_replicas})")
        if self.pool_min is not None and self.pool_min < 1:
            raise ValueError(f"pool_min must be >= 1 (got "
                             f"{self.pool_min})")
        if (self.pool_min is not None and self.pool_max is not None
                and self.pool_max < self.pool_min):
            raise ValueError(
                f"pool_max ({self.pool_max}) < pool_min "
                f"({self.pool_min})")
        if self.dead_ticks < 1:
            raise ValueError("dead_ticks must be >= 1")
        if not 0.0 <= self.page_pressure <= 1.0:
            raise ValueError(f"page_pressure must be in [0, 1] "
                             f"(got {self.page_pressure})")
        if isinstance(self.policy, dict):
            self.policy = FleetPolicy(**self.policy)


class _ModelState:
    __slots__ = ("hot", "cold", "last_up", "last_down", "burn")

    def __init__(self) -> None:
        self.hot = 0
        self.cold = 0
        self.last_up = -1e18
        self.last_down = -1e18
        self.burn = 0.0


class FleetController:
    """The control loop over one ModelRouter (module doc)."""

    def __init__(self, router, provider: Optional[ReplicaProvider] = None,
                 cfg: Optional[FleetConfig] = None,
                 admission=None, logger: Optional[Logger] = None):
        self.router = router
        self.provider = provider
        self.cfg = cfg = cfg if cfg is not None else FleetConfig()
        self.policy = cfg.policy
        self.admission = admission
        self.log = logger
        router.attach_fleet(self)
        self.registry = router.registry
        self._g_replicas = self.registry.gauge(
            "sparknet_fleet_replicas",
            "registered replicas per model (local lane included)",
            labels=("model",))
        self._g_burn = self.registry.gauge(
            "sparknet_fleet_slo_burn",
            "windowed p99 / slo_p99_ms per model (1.0 = at objective)",
            labels=("model",))
        self._c_events = self.registry.counter(
            "sparknet_fleet_scale_events_total",
            "fleet actions by model, direction (up/down/error) and "
            "reason (slo_burn/queue/shed/quiet/dead/replace/...)",
            labels=("model", "direction", "reason"))
        self._g_pressure = self.registry.gauge(
            "sparknet_fleet_admission_pressure",
            "the fast lever: [0,1] overload level pushed into "
            "priority admission each tick")
        self._g_pressure.set(0.0)
        self._g_starvation = self.registry.gauge(
            "sparknet_fleet_batch_starvation_s",
            "seconds the low (scavenger/batch) class has been "
            "continuously admission-shed with nothing admitted")
        self._g_starvation.set(0.0)
        self._batch_relieving = False  # audit edge detector
        # SLO burn-rate alerter (attach_alerter): firing pages escalate
        # the fast lever; edge-detected for the audit trail
        self.alerter = None
        self._page_escalating = False
        self._state: Dict[str, _ModelState] = {}
        # provider-grown replicas: model -> [(router Replica, handle)]
        self._owned: Dict[str, List[Tuple[Any, ReplicaHandle]]] = {}
        # draining replicas awaiting retire: (retire_at, model, rep,
        # handle)
        self._retiring: List[Tuple[float, str, Any,
                                   Optional[ReplicaHandle]]] = []
        self._unhealthy: Dict[Tuple[str, str], int] = {}
        self._prev_shed: Dict[str, float] = {}
        self._prev_tick_t: Optional[float] = None
        self._pool_hot = 0
        self._pool_cold = 0
        self._last_pool_t = -1e18
        self.pressure = 0.0
        self.ticks = 0
        self.scale_events = 0
        # rollout duty: one wave sequencer per model whose local lane
        # watches a checkpoint dir through a rollout gate (lazily built
        # on the first tick that sees such a lane)
        self._rollouts: Dict[str, RolloutManager] = {}
        self.audit: deque = deque(maxlen=200)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._tick_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetController":
        assert self._thread is None, "already started"
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-controller",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, retire_owned: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 3 * self.cfg.interval_s))
            self._thread = None
        if retire_owned and self.provider is not None:
            # a tick may be mid-grow (a subprocess spawn blocks up to
            # spawn_timeout_s): wait a bounded moment for the graceful
            # drain-then-retire path, then fall back to provider.stop()
            # — terminating every child it owns needs no lock
            if self._tick_lock.acquire(timeout=10.0):
                try:
                    for _, model, rep, handle in self._retiring:
                        self._finish_retire(model, rep, handle)
                    self._retiring = []
                    for model, pairs in list(self._owned.items()):
                        for rep, handle in list(pairs):
                            try:
                                self.router.drain(model, rep.name)
                            except Exception:
                                pass
                            self._finish_retire(model, rep, handle)
                    self._owned = {}
                finally:
                    self._tick_lock.release()
            else:
                self._log("fleet: stop() could not take the tick lock "
                          "(grow in flight?); force-stopping the "
                          "provider")
            try:
                self.provider.stop()
            except Exception as e:
                self._log(f"fleet: provider stop failed: {e}")

    def attach_alerter(self, alerter) -> "FleetController":
        """Wire a `BurnRateAlerter`: its `firing_pages()` becomes a fast
        admission-pressure input each tick (cfg.page_pressure)."""
        self.alerter = alerter
        return self

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception as e:
                # the control loop must outlive any one bad tick — a
                # probe hiccup must not leave the fleet pilotless
                self._log(f"fleet: tick failed ({type(e).__name__}: "
                          f"{e}); continuing")

    # -- signals -------------------------------------------------------------

    def _models(self) -> List[str]:
        return sorted(set(self.router.lanes) | set(self.router.replicas))

    def _slo_for(self, model: str) -> Optional[float]:
        lane = self.router.lanes.get(model)
        if lane is not None and lane.cfg.slo_p99_ms is not None:
            return lane.cfg.slo_p99_ms
        return self.cfg.slo_p99_ms

    def _signals(self, model: str, dt_s: float) -> ModelSignals:
        lat = self.router.latency.get(model)
        win = (lat.windowed(self.cfg.window_s) if lat is not None
               else {"n": 0, "p99_ms": None})
        lane = self.router.lanes.get(model)
        queue_frac = 0.0
        low_queue_frac = 0.0
        shed_total = 0.0
        if lane is not None:
            queue_frac = lane.batcher.depth() / max(
                lane.cfg.max_queue, 1)
            low_queue_frac = lane.batcher.low_depth() / max(
                lane.cfg.max_queue, 1)
            shed_total = float(lane.batcher.shed)
            rej = self.registry.counter(
                "sparknet_serve_queue_rejected_total",
                labels=("model",)).value(model=model)
            shed_total += float(rej or 0.0)
        prev = self._prev_shed.get(model, shed_total)
        self._prev_shed[model] = shed_total
        # divided by ACTUAL elapsed time, not the configured cadence: a
        # tick delayed by a blocking grow accumulates a whole spawn's
        # worth of sheds, and interval_s in the denominator would read
        # that as a rate spike and cascade further grows
        shed_per_s = max(0.0, shed_total - prev) / max(dt_s, 1e-3)
        reps = self.router.replicas.get(model, [])
        routable = sum(1 for r in reps
                       if self.router._replica_routable(r))
        return ModelSignals(model=model, p99_ms=win["p99_ms"],
                            slo_p99_ms=self._slo_for(model),
                            n_window=int(win["n"]),
                            queue_frac=queue_frac,
                            shed_per_s=shed_per_s,
                            replicas=len(reps), routable=routable,
                            low_queue_frac=low_queue_frac,
                            batch_starvation_s=self._starvation_s())

    def _starvation_s(self) -> float:
        """How long the low class has been continuously pressure-shed
        at the attached admission door (0 without one)."""
        if self.admission is not None and \
                hasattr(self.admission, "starvation_s"):
            return float(self.admission.starvation_s())
        return 0.0

    # -- the control step ----------------------------------------------------

    def tick(self) -> Dict[str, ModelSignals]:
        """One control step (the loop calls this every interval; tests
        call it directly). Returns the signals it acted on."""
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self) -> Dict[str, ModelSignals]:
        now = time.monotonic()
        dt_s = (now - self._prev_tick_t
                if self._prev_tick_t is not None else self.cfg.interval_s)
        self._prev_tick_t = now
        self.ticks += 1
        sigs: Dict[str, ModelSignals] = {}
        burn_max = 0.0
        for model in self._models():
            sig = self._signals(model, dt_s)
            sigs[model] = sig
            st = self._state.setdefault(model, _ModelState())
            st.burn = self.policy.burn(sig)
            burn_max = max(burn_max, st.burn)
            self._g_burn.set(round(st.burn, 4), model=model)
        # fast lever: admission pressure, every tick, no hysteresis —
        # shedding low-priority load is cheap and instantly reversible
        self.pressure = self.policy.pressure_from_burn(burn_max)
        # SLO page escalation: a firing burn-rate page floors the fast
        # lever at page_pressure IMMEDIATELY, ahead of the replica
        # lever's cooldowns — only admission, never the hysteresis-
        # guarded levers, and the batch-relief clamp below still wins
        pages = self.alerter.firing_pages() if self.alerter is not None \
            else []
        if pages and self.cfg.page_pressure > self.pressure:
            if not self._page_escalating:
                self._page_escalating = True
                self._event("_slo", "pressure", "slo_page",
                            models=",".join(pages),
                            pressure=round(self.pressure, 4),
                            escalated=self.cfg.page_pressure)
            self.pressure = self.cfg.page_pressure
        elif not pages:
            self._page_escalating = False
        # scavenger relief: sustained pressure must not weld the door
        # shut on the low class forever. Past the policy's starvation
        # bound the pressure is clamped just under low's shed threshold
        # for the tick — online traffic still outranks batch at every
        # queue, the door just stops being airtight.
        starvation = self._starvation_s()
        self._g_starvation.set(round(starvation, 3))
        if self.policy.batch_relief(starvation, self.pressure):
            if not self._batch_relieving:
                self._batch_relieving = True
                self._event("_batch", "relief", "batch_starvation",
                            starvation_s=round(starvation, 3),
                            pressure=round(self.pressure, 4),
                            clamped=self.policy.batch_relief_pressure)
            self.pressure = self.policy.batch_relief_pressure
        else:
            self._batch_relieving = False
        self._g_pressure.set(round(self.pressure, 4))
        if self.admission is not None and \
                hasattr(self.admission, "set_pressure"):
            self.admission.set_pressure(self.pressure)
        # slow levers
        self._process_retiring(now)
        if self.provider is not None:
            for model, sig in sigs.items():
                self._replace_dead(model, sig, now)
            for model, sig in sigs.items():
                self._scale_model(model, sigs[model], now)
        self._scale_pool(sigs, now)
        self._rollout_duty(sigs, now)
        # POST-action counts: the gauge a grow lands in shows the grown
        # fleet, not the pre-grow signal snapshot
        for model in sigs:
            self._g_replicas.set(
                len(self.router.replicas.get(model, [])), model=model)
        if self.log is not None and self.cfg.status_row_every and \
                self.ticks % self.cfg.status_row_every == 0:
            # post-action counts: the row a grow lands in shows the
            # grown fleet, not the pre-grow signal snapshot
            self.log.metrics(self.ticks, fleet_replicas={
                m: len(self.router.replicas.get(m, []))
                for m in sigs},
                fleet_pressure=round(self.pressure, 4))
        return sigs

    def _scale_model(self, model: str, sig: ModelSignals,
                     now: float) -> None:
        st = self._state[model]
        pending = sum(1 for _, m, _, _ in self._retiring if m == model)
        if (sig.replicas - pending < self.cfg.min_replicas
                and now - st.last_up >= self.cfg.up_cooldown_s):
            # the floor is not a load decision: below min_replicas the
            # fleet grows regardless of temperature (paced by the up
            # cooldown so a failing grow cannot hot-loop spawns)
            st.last_up = now
            self._grow(model, "min_bound")
            return
        reason = self.policy.hot_reason(sig)
        if reason is not None:
            st.hot += 1
            st.cold = 0
        else:
            st.hot = 0
            st.cold = st.cold + 1 if self.policy.is_cold(sig) else 0
        if (reason is not None and st.hot >= self.policy.up_ticks
                and sig.replicas < self.cfg.max_replicas
                and now - st.last_up >= self.cfg.up_cooldown_s):
            st.last_up = now
            st.hot = 0
            self._grow(model, reason)
        elif (st.cold >= self.policy.down_ticks and pending == 0
                and sig.replicas - pending > self.cfg.min_replicas
                and self._owned.get(model)
                and now - st.last_down >= self.cfg.down_cooldown_s):
            st.last_down = now
            st.cold = 0
            self._shrink(model, now)

    def _grow(self, model: str, reason: str) -> None:
        try:
            handle = self.provider.grow(model)
        except Exception as e:
            self._event(model, "error", "grow_failed", error=str(e))
            self._log(f"fleet: grow {model} failed: {e}")
            return
        rep = self.router.add_remote_replica(
            model, handle.url, heartbeat_path=handle.heartbeat_path)
        self._owned.setdefault(model, []).append((rep, handle))
        self._event(model, "up", reason, replica=rep.name,
                    replicas=len(self.router.replicas.get(model, [])))
        self._log(f"fleet: scaled {model} UP ({reason}) -> "
                  f"{handle.url}")

    def _shrink(self, model: str, now: float) -> None:
        pairs = self._owned.get(model, [])
        for rep, handle in reversed(pairs):  # LIFO: newest goes first
            if not rep.draining:
                self.router.drain(model, rep.name)
                self._retiring.append(
                    (now + self.cfg.drain_grace_s, model, rep, handle))
                self._event(model, "down", "quiet", replica=rep.name,
                            replicas=len(self.router.replicas.get(
                                model, [])) - 1)
                self._log(f"fleet: scaling {model} DOWN (quiet): "
                          f"draining {rep.name}")
                return

    def _process_retiring(self, now: float) -> None:
        due = [e for e in self._retiring if e[0] <= now]
        self._retiring = [e for e in self._retiring if e[0] > now]
        for _, model, rep, handle in due:
            self._finish_retire(model, rep, handle)

    def _finish_retire(self, model: str, rep, handle) -> None:
        try:
            self.router.remove_replica(model, rep.name)
        except Exception:
            pass  # already evicted (e.g. by the dead-replica path)
        pairs = self._owned.get(model, [])
        self._owned[model] = [p for p in pairs if p[0] is not rep]
        if handle is not None and self.provider is not None:
            try:
                self.provider.retire(handle)
            except Exception as e:
                self._log(f"fleet: retire of {rep.name} failed: {e}")

    def _replace_dead(self, model: str, sig: ModelSignals,
                      now: float) -> None:
        for rep, handle in list(self._owned.get(model, [])):
            if rep.draining:
                continue  # already on its way out
            key = (model, rep.name)
            proc_dead = not self.provider.alive(handle)
            probe_dead = False
            if rep.health_fn is not None:
                try:
                    probe_dead = not rep.health_fn()
                except Exception:
                    probe_dead = True
            if probe_dead or proc_dead:
                self._unhealthy[key] = self._unhealthy.get(key, 0) + 1
            else:
                self._unhealthy.pop(key, None)
                continue
            if not proc_dead and \
                    self._unhealthy[key] < self.cfg.dead_ticks:
                continue  # stale beat: give it dead_ticks to recover
            self._unhealthy.pop(key, None)
            cause = "process gone" if proc_dead else "stale heartbeat"
            self._event(model, "down", "dead", replica=rep.name,
                        proc_dead=proc_dead)
            self._log(f"fleet: replica {model}/{rep.name} is dead "
                      f"({cause}); evicting")
            self._finish_retire(model, rep, handle)
            if self.cfg.replace_dead and \
                    len(self.router.replicas.get(model, [])) < \
                    self.cfg.max_replicas:
                self._grow(model, "replace")

    def _scale_pool(self, sigs: Dict[str, ModelSignals],
                    now: float) -> None:
        pool_min = (self.cfg.pool_min
                    if self.cfg.pool_min is not None
                    else self.router.cfg.workers)
        pool_max = (self.cfg.pool_max
                    if self.cfg.pool_max is not None else pool_min)
        if pool_max <= pool_min:
            return  # lever off
        lanes = [s for m, s in sigs.items()
                 if m in self.router.lanes]
        hot = any(s.queue_frac >= self.policy.queue_high for s in lanes)
        quiet = all(s.queue_frac < self.policy.queue_low for s in lanes)
        target = self.router._pool_target
        if hot:
            self._pool_hot += 1
            self._pool_cold = 0
        elif quiet:
            self._pool_cold += 1
            self._pool_hot = 0
        else:
            self._pool_hot = self._pool_cold = 0
        if (self._pool_hot >= self.policy.up_ticks
                and target < pool_max
                and now - self._last_pool_t >= self.cfg.up_cooldown_s):
            self._last_pool_t = now
            self._pool_hot = 0
            self.router.set_pool_size(target + 1)
            self._event("_pool", "up", "queue", pool=target + 1)
        elif (self._pool_cold >= self.policy.down_ticks
                and target > pool_min
                and now - self._last_pool_t >=
                self.cfg.down_cooldown_s):
            self._last_pool_t = now
            self._pool_cold = 0
            self.router.set_pool_size(target - 1)
            self._event("_pool", "down", "quiet", pool=target - 1)

    # -- rollout duty --------------------------------------------------------

    def _rollout_duty(self, sigs: Dict[str, ModelSignals],
                      now: float) -> None:
        """Staggered checkpoint adoption (fleet/rollout.py): for each
        model whose LOCAL lane watches a checkpoint dir through a
        rollout gate, feed the sequencer this tick's adoption views —
        the lane's manager read directly (it doubles as the canary,
        first in the list), the provider-owned children through their
        heartbeats' per-model rows — plus the newest committed step and
        the model's SLO burn (the wave health gate)."""
        for model in sigs:
            lane = self.router.lanes.get(model)
            mgr = getattr(lane, "manager", None) if lane is not None \
                else None
            if mgr is None or not getattr(mgr, "rollout_gate", None) \
                    or not mgr.checkpoint_dir:
                continue
            ro = self._rollouts.get(model)
            if ro is None:
                ro = RolloutManager(
                    mgr.rollout_gate,
                    wave_size=self.policy.rollout_wave_size,
                    halt_burn=self.policy.rollout_halt_burn,
                    timeout_s=self.policy.rollout_timeout_s,
                    event=(lambda direction, reason, _m=model, **ex:
                           self._event(_m, direction, reason, **ex)),
                    logger=self.log)
                self._rollouts[model] = ro
            st = self._state.get(model)
            ro.tick(self._rollout_views(model, mgr), mgr.latest_seen,
                    st.burn if st else 0.0, now)

    def _rollout_views(self, model: str, mgr) -> List[ReplicaView]:
        views = [ReplicaView(mgr.replica, mgr.step, mgr.swap_failures)]
        for rep, handle in self._owned.get(model, []):
            key = (getattr(handle, "meta", None) or {}).get("tag",
                                                            rep.name)
            step = None
            rollbacks = 0
            hb = (read_heartbeat(handle.heartbeat_path)
                  if handle.heartbeat_path else None)
            if hb:
                row = (hb.get("models") or {}).get(model) or {}
                step = row.get("model_step", row.get("step"))
                rollbacks = int(row.get("swap_failures",
                                        hb.get("rollbacks", 0)) or 0)
            views.append(ReplicaView(key, step, rollbacks))
        return views

    # -- bookkeeping ---------------------------------------------------------

    def _event(self, model: str, direction: str, reason: str,
               **extra: Any) -> None:
        self.scale_events += 1
        self._c_events.inc(model=model, direction=direction,
                           reason=reason)
        entry = {"t": round(time.time(), 3), "tick": self.ticks,
                 "model": model, "direction": direction,
                 "reason": reason, **extra}
        self.audit.append(entry)
        if self.log is not None:
            # "t" stays out of the kv: Logger.metrics stamps its own
            # run-relative t (+ wall-clock ts) on every record, and the
            # audit entry's epoch t would clobber the timeline key
            kv = {k: v for k, v in entry.items()
                  if k not in ("tick", "t")}
            if "step" in kv:
                # rollout events carry the checkpoint step; Logger.metrics
                # reserves "step" for its positional (the tick counter)
                kv["ckpt_step"] = kv.pop("step")
            self.log.metrics(self.ticks, event="fleet_scale", **kv)

    def _log(self, msg: str) -> None:
        if self.log is not None:
            self.log.log(msg)

    def status(self) -> Dict[str, Any]:
        """The /fleet/status JSON. Taken WITHOUT the tick lock when a
        tick is in flight (a grow may block the loop for a subprocess
        spawn; the status endpoint must answer through it) — the reads
        are each individually consistent, the dict is best-effort."""
        locked = self._tick_lock.acquire(timeout=0.25)
        try:
            return self._status_inner()
        except RuntimeError:
            # unlocked read raced a tick's dict mutation: degrade, the
            # next scrape wins
            return {"enabled": True, "busy": True, "ticks": self.ticks}
        finally:
            if locked:
                self._tick_lock.release()

    def _status_inner(self) -> Dict[str, Any]:
        models: Dict[str, Any] = {}
        for model in self._models():
            st = self._state.get(model)
            lat = self.router.latency.get(model)
            win = (lat.windowed(self.cfg.window_s)
                   if lat is not None else {"n": 0, "p99_ms": None})
            reps = list(self.router.replicas.get(model, []))
            models[model] = {
                "replicas": len(reps),
                "routable": sum(
                    1 for r in reps
                    if self.router._replica_routable(r)),
                "owned": len(self._owned.get(model, [])),
                "min": self.cfg.min_replicas,
                "max": self.cfg.max_replicas,
                "slo_p99_ms": self._slo_for(model),
                "p99_ms": win["p99_ms"],
                "window_n": win["n"],
                "burn": round(st.burn, 4) if st else 0.0,
                "hot_ticks": st.hot if st else 0,
                "cold_ticks": st.cold if st else 0,
            }
        out = {
            "enabled": True,
            "running": self._thread is not None,
            "interval_s": self.cfg.interval_s,
            "window_s": self.cfg.window_s,
            "ticks": self.ticks,
            "pressure": round(self.pressure, 4),
            "batch_starvation_s": round(self._starvation_s(), 3),
            "provider": (type(self.provider).__name__
                         if self.provider is not None else None),
            "pool": {"size": self.router.pool_size(),
                     "target": self.router._pool_target,
                     "min": self.cfg.pool_min,
                     "max": self.cfg.pool_max},
            "models": models,
            "retiring": len(self._retiring),
            "scale_events": self.scale_events,
            "audit": list(self.audit)[-20:],
        }
        if self._rollouts:
            out["rollout"] = {m: ro.status()
                              for m, ro in self._rollouts.items()}
        if self.admission is not None and \
                hasattr(self.admission, "status"):
            out["admission"] = self.admission.status()
        return out
