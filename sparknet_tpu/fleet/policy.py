"""Decision logic for the fleet control plane: signals in, verdicts out.

The controller (fleet/controller.py) OWNS the loop — gathering signals,
driving the router and the replica provider, bookkeeping cooldowns. This
module owns the POLICY: pure functions of the signals, so every
threshold is unit-testable without a router, a thread, or a clock.

Signals per model (`ModelSignals`), all from meters the obs stack
already exports:

  - `p99_ms` / `slo_p99_ms`: the router-vantage end-to-end p99 over a
    TIME-sliding window (utils/metrics.LatencyStats.windowed) against
    the model's objective (`--slo-p99-ms`). Their ratio is the **SLO
    burn** — burn 1.0 = exactly at objective, 2.0 = tail twice the
    objective. Quiet models (fewer than `min_window_n` observations)
    read as burn 0: an autoscaler must never act on a three-request
    p99.
  - `queue_frac`: local lane queue depth / max_queue — the leading
    indicator (the queue fills before the tail degrades).
  - `shed_per_s`: deadline/backpressure sheds per second — the trailing
    indicator (by the time requests shed, capacity is already gone).

Two levers, two speeds:

  - **fast** — admission pressure (`pressure_from_burn`): a [0, 1]
    overload level the controller pushes into `PriorityAdmission` every
    tick. Pressure starts rising at `pressure_start` burn and saturates
    at `pressure_full`; under it, low-priority traffic sheds first and
    every tenant's refill tightens (serve/admission.py).
  - **slow** — replicas (`hot_reason` / `is_cold` + the controller's
    hysteresis): `up_ticks` consecutive hot ticks grow the fleet,
    `down_ticks` consecutive cold ticks shrink it, bounded by the
    per-model min/max and the up/down cooldowns — a burst can tighten
    admission instantly but cannot flap replicas.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ModelSignals:
    """One model's control inputs for one tick (controller-gathered)."""

    model: str
    p99_ms: Optional[float]         # windowed router-vantage p99
    slo_p99_ms: Optional[float]     # the objective (None = no SLO)
    n_window: int                   # observations inside the window
    queue_frac: float               # lane queue depth / max_queue
    shed_per_s: float               # sheds per second since last tick
    replicas: int                   # registered replicas (incl. local)
    routable: int                   # currently routable replicas
    # scavenger (batch-tenant) pressure: the fraction of the queue that
    # is low-priority work, and how long the low class has been
    # continuously admission-shed. Low backlog must never read as
    # online demand (the autoscaler would grow the fleet to chase work
    # that exists precisely to soak SLACK) — but neither may pressure
    # pin the door shut on it forever.
    low_queue_frac: float = 0.0
    batch_starvation_s: float = 0.0


def slo_burn(p99_ms: Optional[float],
             slo_p99_ms: Optional[float]) -> float:
    """Observed p99 / objective. 0.0 when either side is unknown: a
    model with no SLO (or no traffic) must read as NOT burning — the
    controller's other signals (queue, shed) still cover it."""
    if p99_ms is None or not slo_p99_ms or slo_p99_ms <= 0:
        return 0.0
    return float(p99_ms) / float(slo_p99_ms)


@dataclass
class FleetPolicy:
    """Thresholds + hysteresis shape (the `sparknet-serve --autoscale`
    CLI and FleetConfig carry these)."""

    # slow lever: replica scale-up triggers (any one suffices)
    burn_up: float = 1.0            # SLO burn at/over this = hot
    queue_high: float = 0.5         # lane queue fraction = hot
    shed_high_per_s: float = 1.0    # sheds/sec = hot
    # scale-down gate (ALL must hold)
    burn_down: float = 0.7          # burn strictly under this = cool
    queue_low: float = 0.1
    # ignore the p99 of a near-empty window (a three-request tail is
    # noise, not a signal)
    min_window_n: int = 16
    # hysteresis: consecutive ticks required before acting
    up_ticks: int = 2
    down_ticks: int = 5
    # fast lever: admission pressure ramps linearly from 0 at
    # pressure_start burn to 1 at pressure_full burn
    pressure_start: float = 1.0
    pressure_full: float = 2.0
    # rollout duty (fleet/rollout.py): staggered checkpoint adoption.
    # After the canary, at most rollout_wave_size replicas swap per
    # wave; a wave only opens while SLO burn sits under
    # rollout_halt_burn, and a phase that hasn't fully adopted within
    # rollout_timeout_s halts the rollout (deny + revert).
    rollout_wave_size: int = 2
    rollout_halt_burn: float = 1.5
    rollout_timeout_s: float = 30.0
    # scavenger coexistence: the low class may be pressure-starved for
    # at most batch_max_starvation_s; past that the controller clamps
    # admission pressure to batch_relief_pressure (just UNDER low's
    # default shed threshold, 0.5) until scavenger traffic flows again.
    # Online work still outranks batch at every queue and bucket — the
    # relief only stops the door being welded shut.
    batch_max_starvation_s: float = 60.0
    batch_relief_pressure: float = 0.45

    def __post_init__(self) -> None:
        # fail at construction, not mid-control-loop (the ElasticConfig
        # rule)
        if self.burn_up <= 0 or self.burn_down <= 0:
            raise ValueError(f"burn thresholds must be > 0 (got "
                             f"up={self.burn_up} down={self.burn_down})")
        if self.burn_down >= self.burn_up:
            raise ValueError(
                f"burn_down ({self.burn_down}) must sit strictly below "
                f"burn_up ({self.burn_up}) — equal thresholds flap")
        if not 0 < self.queue_low < self.queue_high <= 1.0:
            raise ValueError(
                f"need 0 < queue_low < queue_high <= 1 (got "
                f"{self.queue_low}, {self.queue_high})")
        if self.up_ticks < 1 or self.down_ticks < 1:
            raise ValueError("up_ticks/down_ticks must be >= 1")
        if self.pressure_full <= self.pressure_start:
            raise ValueError(
                f"pressure_full ({self.pressure_full}) must exceed "
                f"pressure_start ({self.pressure_start})")
        if self.rollout_wave_size < 1:
            raise ValueError(f"rollout_wave_size must be >= 1 (got "
                             f"{self.rollout_wave_size})")
        if self.rollout_halt_burn <= 0 or self.rollout_timeout_s <= 0:
            raise ValueError(
                f"rollout_halt_burn/rollout_timeout_s must be > 0 (got "
                f"{self.rollout_halt_burn}, {self.rollout_timeout_s})")
        if self.batch_max_starvation_s <= 0:
            raise ValueError(f"batch_max_starvation_s must be > 0 (got "
                             f"{self.batch_max_starvation_s})")
        if not 0.0 <= self.batch_relief_pressure < 1.0:
            raise ValueError(
                f"batch_relief_pressure must be in [0, 1) (got "
                f"{self.batch_relief_pressure})")

    # -- signal -> verdict ---------------------------------------------------

    def burn(self, sig: ModelSignals) -> float:
        """This model's SLO burn, window-size gated."""
        if sig.n_window < self.min_window_n:
            return 0.0
        return slo_burn(sig.p99_ms, sig.slo_p99_ms)

    def pressure_from_burn(self, burn: float) -> float:
        """Admission pressure in [0, 1] (the fast lever's setting)."""
        span = self.pressure_full - self.pressure_start
        return min(1.0, max(0.0, (burn - self.pressure_start) / span))

    @staticmethod
    def online_queue_frac(sig: ModelSignals) -> float:
        """Queue fraction attributable to ONLINE (non-low) work. The
        scale verdicts read this, not the raw fraction: a scavenger job
        keeping the queue full of low-priority units is soaking slack,
        not demanding capacity."""
        return max(0.0, sig.queue_frac - sig.low_queue_frac)

    def hot_reason(self, sig: ModelSignals) -> Optional[str]:
        """The scale-up trigger that fired, or None. Named because the
        reason lands in `fleet_scale_events_total{reason}` and the audit
        trail — "the fleet grew" is not actionable, "it grew because
        shed_rate" is."""
        if self.burn(sig) >= self.burn_up:
            return "slo_burn"
        if self.online_queue_frac(sig) >= self.queue_high:
            return "queue"
        if sig.shed_per_s >= self.shed_high_per_s:
            return "shed"
        return None

    def is_cold(self, sig: ModelSignals) -> bool:
        """Quiet enough to consider giving a replica back: below every
        hot trigger with margin. (An UNKNOWN p99 — idle model — is cold:
        idleness is exactly when shrink should happen. A queue full of
        scavenger units does NOT hold replicas: only online depth
        counts, same as hot_reason.)"""
        burn = self.burn(sig)
        return (burn < self.burn_down
                and self.online_queue_frac(sig) < self.queue_low
                and sig.shed_per_s < self.shed_high_per_s / 2.0)

    def batch_relief(self, starvation_s: float, pressure: float) -> bool:
        """True when admission pressure should be clamped to
        batch_relief_pressure this tick: the low class has been starved
        past the bound AND the computed pressure would keep shedding it."""
        return (starvation_s >= self.batch_max_starvation_s
                and pressure > self.batch_relief_pressure)
